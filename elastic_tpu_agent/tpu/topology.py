"""Static TPU generation table + accelerator-type parsing.

The reference had NVML to answer "how many devices, how much memory"
(pkg/operator/base.go:19-75). TPU has no NVML analogue (SURVEY.md §7 "hard
parts"): inventory is assembled from /dev/accel*, /sys, the TPU-VM metadata
server, and this static per-generation table. The table carries the facts
that are intrinsic to the silicon — TensorCores per chip, HBM per chip,
chips per host — keyed by accelerator-type strings like ``v5litepod-8``,
``v4-16``, ``v5p-16``, ``v6e-8``.

Naming convention note (public Cloud TPU docs): the numeric suffix counts
*TensorCores* for v2/v3/v4/v5p (2 cores/chip) and *chips* for
v5litepod/v6e (1 core/chip).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

GiB = 1024 * 1024 * 1024


@dataclass(frozen=True)
class ChipSpec:
    """Per-chip facts for one TPU generation."""

    family: str            # "v4", "v5e", ...
    cores_per_chip: int    # TensorCores per chip
    hbm_bytes: int         # HBM per chip
    max_chips_per_host: int
    suffix_counts_cores: bool  # accelerator-type suffix unit (see module doc)


# Generation table. Sources: public Cloud TPU system-architecture docs.
_SPECS: Dict[str, ChipSpec] = {
    "v2": ChipSpec("v2", 2, 16 * GiB, 4, True),
    "v3": ChipSpec("v3", 2, 32 * GiB, 4, True),
    "v4": ChipSpec("v4", 2, 32 * GiB, 4, True),
    "v5e": ChipSpec("v5e", 1, 16 * GiB, 8, False),
    "v5p": ChipSpec("v5p", 2, 95 * GiB, 4, True),
    "v6e": ChipSpec("v6e", 1, 32 * GiB, 8, False),
}

# Public name for the generation table: heterogeneous-fleet consumers
# (stub operators per generation, the fleet sim's mixed node shapes,
# parametrized generation tests) iterate it by family key.
CHIP_SPECS = _SPECS

# Accepted accelerator-type spellings -> family key.
_FAMILY_ALIASES = {
    "v2": "v2",
    "v3": "v3",
    "v4": "v4",
    "v5litepod": "v5e",
    "v5e": "v5e",
    "v5p": "v5p",
    "v6e": "v6e",
}

_TYPE_RE = re.compile(r"^(?P<family>[a-z0-9]+?)-(?P<count>\d+)$")


@dataclass(frozen=True)
class TopologyInfo:
    """Parsed accelerator-type: slice-wide and per-host chip facts."""

    accelerator_type: str
    spec: ChipSpec
    total_chips: int       # chips in the whole slice
    total_cores: int       # TensorCores in the whole slice
    chips_per_host: int
    num_hosts: int

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1


def parse_accelerator_type(acc_type: str) -> Optional[TopologyInfo]:
    """Parse e.g. "v5litepod-8" / "v4-16" / "v5p-128"; None when unknown."""
    m = _TYPE_RE.match(acc_type.strip().lower())
    if not m:
        return None
    family = _FAMILY_ALIASES.get(m.group("family"))
    if family is None:
        return None
    spec = _SPECS[family]
    count = int(m.group("count"))
    if count <= 0:
        return None
    if spec.suffix_counts_cores:
        total_cores = count
        total_chips = max(1, count // spec.cores_per_chip)
    else:
        total_chips = count
        total_cores = count * spec.cores_per_chip
    chips_per_host = min(total_chips, spec.max_chips_per_host)
    num_hosts = max(1, (total_chips + chips_per_host - 1) // chips_per_host)
    return TopologyInfo(
        accelerator_type=acc_type,
        spec=spec,
        total_chips=total_chips,
        total_cores=total_cores,
        chips_per_host=chips_per_host,
        num_hosts=num_hosts,
    )


def topology_for_hosts(topo: TopologyInfo, num_hosts: int) -> TopologyInfo:
    """``topo`` resized to ``num_hosts`` hosts (chips-per-host kept).

    The elastic-recovery shape: a slice annotated ``v4-32`` (4 hosts)
    that loses a member re-forms as the same generation and per-host
    chip grid at world size 3 — the accelerator-type string is kept
    verbatim so the workload can still see what it was scheduled as,
    while the host-count-derived env (``TPU_HOST_BOUNDS``) follows the
    surviving world.
    """
    n = max(1, num_hosts)
    return TopologyInfo(
        accelerator_type=topo.accelerator_type,
        spec=topo.spec,
        total_chips=topo.chips_per_host * n,
        total_cores=topo.chips_per_host * n * topo.spec.cores_per_chip,
        chips_per_host=topo.chips_per_host,
        num_hosts=n,
    )


def spec_for_family(family: str) -> Optional[ChipSpec]:
    key = _FAMILY_ALIASES.get(family.lower())
    return _SPECS.get(key) if key else None


def chip_grid(chips_per_host: int) -> Dict[int, Tuple[int, int]]:
    """Host-local chip index -> (x, y) coordinate on the host's ICI grid.

    Mirrors the chip-bounds convention emitted by :func:`host_bounds`
    (``2,cph/2,1`` for >=4 chips, flat otherwise), with chips numbered
    row-major — the same order /dev/accelN enumerates them on TPU-VMs.
    """
    if chips_per_host >= 4:
        xs = 2
    else:
        xs = max(1, chips_per_host)
    return {i: (i % xs, i // xs) for i in range(chips_per_host)}


def ici_distance(a: Tuple[int, int], b: Tuple[int, int]) -> int:
    """Hop count between two chips on the host grid (Manhattan: ICI links
    run along the mesh axes; there is no host-internal wraparound)."""
    return abs(a[0] - b[0]) + abs(a[1] - b[1])


def host_bounds(topo: TopologyInfo) -> Tuple[str, str]:
    """(TPU_CHIPS_PER_HOST_BOUNDS, TPU_HOST_BOUNDS) env values for
    jax.distributed slice formation (BASELINE config 5).

    Physical ICI layouts vary per shape; we emit the standard defaults:
    chips on one host form an x,y grid with z=1, hosts tile the remaining
    dimension. Matches the conventions libtpu expects for the common
    v4/v5p pod-slice shapes and degenerates to flat grids for v5e/v6e.
    """
    cph = topo.chips_per_host
    if cph >= 4:
        chip_bounds = f"2,{cph // 2},1"
    else:
        chip_bounds = f"{cph},1,1"
    n = topo.num_hosts
    # Tile hosts as close to a square as divisibility allows.
    best = (1, n)
    for a in range(1, int(n**0.5) + 1):
        if n % a == 0:
            best = (a, n // a)
    return chip_bounds, f"{best[0]},{best[1]},1"
