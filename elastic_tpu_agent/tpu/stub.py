"""Stub operator: fake chips for hermetic CI (BASELINE config 1).

The reference had no fake backend at all (SURVEY.md §4); this operator is
the deliberate seam that lets the whole control plane — plugins, manager,
GC, Restore, e2e fake-kubelet tests — run on a CPU-only kind node or in CI
with zero TPU hardware.
"""

from __future__ import annotations

import time
from typing import List, Optional

from .operator import LinkingOperator, TPUChip
from .topology import TopologyInfo, parse_accelerator_type


class StubOperator(LinkingOperator):
    """N fake chips with table-accurate HBM/core counts."""

    def __init__(
        self,
        dev_root: str,
        accelerator_type: str = "v5litepod-4",
        num_chips: Optional[int] = None,
        hostname: str = "stub-host",
        worker_id: int = 0,
        worker_hostnames: Optional[List[str]] = None,
    ) -> None:
        super().__init__(dev_root)
        topo = parse_accelerator_type(accelerator_type)
        if topo is None:
            raise ValueError(f"unknown accelerator type {accelerator_type!r}")
        self._topo = topo
        self._num = num_chips if num_chips is not None else topo.chips_per_host
        self._hostname = hostname
        self._worker_id = worker_id
        self._worker_hostnames = list(worker_hostnames or [])
        self._unhealthy: set = set()
        self._utilization: dict = {}
        self._maintenance_event = "NONE"
        self._preempted = False
        # Detection-lag origins (latency.py): every injection stamps
        # WHEN the fault began, so the loop that eventually notices can
        # report origin->detection latency instead of guessing. Tests
        # and the fleet sim may set ``clock`` (common.Clock) to make the
        # stamps skewable/deterministic; None uses the wall clock.
        self.clock = None
        self._origin_ts: dict = {}

    def _stamp_origin(self, kind: str) -> None:
        self._origin_ts[kind] = (
            self.clock.time() if self.clock is not None else time.time()
        )

    def origin_ts(self, kind: str) -> Optional[float]:
        """When the newest injection of ``kind`` ("maintenance",
        "preempted", "unhealthy", "utilization") happened; None if it
        never did."""
        return self._origin_ts.get(kind)

    @property
    def topology(self) -> TopologyInfo:
        return self._topo

    # Same worker-identity surface as TPUVMOperator (tpuvm.py:121-151),
    # so multi-host slice behavior is simulatable host-by-host in CI.
    def worker_id(self) -> int:
        return self._worker_id

    def worker_hostnames(self) -> List[str]:
        return list(self._worker_hostnames)

    # -- fault injection (mirrors tpuvm healthy_indexes semantics) ------------

    def set_unhealthy(self, indexes) -> None:
        if set(indexes) - self._unhealthy:
            self._stamp_origin("unhealthy")
        self._unhealthy = set(indexes)

    def healthy_indexes(self) -> set:
        return {c.index for c in self.devices()} - self._unhealthy

    # -- drain trigger injection (mirrors tpuvm maintenance_event/preempted) --

    def set_maintenance_event(self, event: str) -> None:
        """Inject a GCE-style maintenance announcement
        ("MIGRATE_ON_HOST_MAINTENANCE"/"TERMINATE_ON_HOST_MAINTENANCE";
        "NONE" clears it) — the drain orchestrator's trigger in chaos
        scenarios and the fleet sim."""
        if event != "NONE" and event != self._maintenance_event:
            self._stamp_origin("maintenance")
        self._maintenance_event = event

    def maintenance_event(self) -> str:
        return self._maintenance_event

    def set_preempted(self, flag: bool) -> None:
        """Inject a spot/preemption notice (never clears on real GCE;
        tests may clear it to exercise state transitions)."""
        if flag and not self._preempted:
            self._stamp_origin("preempted")
        self._preempted = bool(flag)

    def preempted(self) -> bool:
        return self._preempted

    # -- utilization telemetry injection (mirrors tpuvm.utilization) ----------

    def set_utilization(
        self, samples: dict, hbm_used: Optional[dict] = None
    ) -> None:
        """Inject per-chip telemetry: ``samples`` maps chip index ->
        duty-cycle percent, ``hbm_used`` (optional) chip index -> bytes.
        Chips absent from both report no telemetry (like a tpu-vm host
        without the sysfs files)."""
        hbm_used = hbm_used or {}
        self._utilization = {
            i: {
                "duty_cycle_percent": float(duty),
                "hbm_used_bytes": int(hbm_used.get(i, 0)),
            }
            for i, duty in samples.items()
        }

    def fail_utilization(
        self, indexes, reason: str = "injected telemetry failure"
    ) -> None:
        """Make the telemetry read fail for these chips (the sampler
        flags a chip unhealthy after a failure streak)."""
        self._stamp_origin("utilization")
        for i in indexes:
            self._utilization[i] = {"error": reason}

    def clear_utilization(self) -> None:
        self._utilization = {}

    def utilization(self) -> dict:
        return {i: dict(v) for i, v in self._utilization.items()}

    def devices(self) -> List[TPUChip]:
        spec = self._topo.spec
        return [
            TPUChip(
                uuid=f"stub-{spec.family}-{self._hostname}-{i}",
                index=i,
                device_path=self.target_path(i),
                hbm_bytes=spec.hbm_bytes,
                cores=spec.cores_per_chip,
            )
            for i in range(self._num)
        ]
