"""TPU-VM operator: real chip discovery on a Cloud TPU host.

Replaces the reference's NVML enumeration (pkg/operator/base.go:19-75,
cgo → driver) with the TPU-native inventory sources (SURVEY.md §2 native
item 3, §7 "hard parts" — there is no NVML analogue, so we assemble from
partial information and tolerate every source being absent):

1. ``/dev/accel*`` (and ``/dev/vfio/*`` on vfio-based stacks) — which
   chardevs exist, i.e. how many chips this host exposes.
2. GCE metadata server — ``accelerator-type`` (e.g. "v5litepod-8") and
   ``agent-worker-number`` / ``tpu-env`` for multi-host slice identity.
3. Environment (``TPU_ACCELERATOR_TYPE``, ``TPU_WORKER_ID``) — GKE and
   test overrides.
4. The static generation table (topology.py) — HBM/TensorCores per chip.
"""

from __future__ import annotations

import glob
import logging
import os
import re
from typing import Callable, Dict, List, Optional

from .operator import LinkingOperator, TPUChip
from .topology import GiB, TopologyInfo, parse_accelerator_type

logger = logging.getLogger(__name__)

_METADATA_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/attributes/"
)
_METADATA_HEADERS = {"Metadata-Flavor": "Google"}
_METADATA_TIMEOUT_S = 2.0

# Conservative fallback when the generation cannot be determined: assume the
# smallest HBM of any supported generation so fractional tpu-memory is never
# over-advertised.
_FALLBACK_HBM_BYTES = 16 * GiB
_FALLBACK_CORES = 1

MetadataFetcher = Callable[[str], Optional[str]]


def _default_metadata_fetcher(attribute: str) -> Optional[str]:
    try:
        import requests

        resp = requests.get(
            _METADATA_URL + attribute,
            headers=_METADATA_HEADERS,
            timeout=_METADATA_TIMEOUT_S,
        )
        if resp.status_code == 200:
            return resp.text.strip()
    except Exception:  # noqa: BLE001 - any transport failure = "absent"
        pass
    return None


def parse_tpu_env(raw: str) -> Dict[str, str]:
    """Parse the metadata ``tpu-env`` attribute: lines of KEY: 'value'."""
    out: Dict[str, str] = {}
    for line in raw.splitlines():
        m = re.match(r"^\s*([A-Z0-9_]+)\s*:\s*'?([^']*)'?\s*$", line)
        if m:
            out[m.group(1)] = m.group(2)
    return out


class TPUVMOperator(LinkingOperator):
    """Discovery against a real (or faked-in-tests) TPU-VM host."""

    def __init__(
        self,
        dev_root: str,
        host_dev_scan_root: Optional[str] = None,
        metadata: MetadataFetcher = _default_metadata_fetcher,
        env: Optional[Dict[str, str]] = None,
    ) -> None:
        # dev_root: where virtual links are created (host /dev mount).
        # host_dev_scan_root: where to look for accel* chardevs (defaults to
        # the same mount — tests point both at a fixture dir).
        super().__init__(dev_root)
        self._scan_root = host_dev_scan_root or dev_root
        self._metadata = metadata
        self._env = env if env is not None else dict(os.environ)
        self._topology: Optional[TopologyInfo] = None
        # Worker identity is fixed for the host's lifetime; memoize so the
        # PreStart hot path never re-hits the metadata server.
        self._worker_id: Optional[int] = None
        self._worker_hostnames: Optional[List[str]] = None

    # -- inventory sources ---------------------------------------------------

    def _accel_indexes(self) -> List[int]:
        found = []
        for path in glob.glob(os.path.join(self._scan_root, "accel[0-9]*")):
            m = re.search(r"accel(\d+)$", path)
            if m:
                found.append(int(m.group(1)))
        return sorted(found)

    def _vfio_paths(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self._scan_root, "vfio", "*")))

    def accelerator_type(self) -> Optional[str]:
        for key in ("TPU_ACCELERATOR_TYPE", "ACCELERATOR_TYPE"):
            if self._env.get(key):
                return self._env[key]
        val = self._metadata("accelerator-type")
        if val:
            return val
        raw = self._metadata("tpu-env")
        if raw:
            parsed = parse_tpu_env(raw)
            if parsed.get("ACCELERATOR_TYPE"):
                return parsed["ACCELERATOR_TYPE"]
        return None

    def worker_id(self) -> int:
        if self._worker_id is not None:
            return self._worker_id
        result = 0
        if self._env.get("TPU_WORKER_ID"):
            try:
                result = int(self._env["TPU_WORKER_ID"])
            except ValueError:
                result = 0
        else:
            val = self._metadata("agent-worker-number")
            if val:
                try:
                    result = int(val)
                except ValueError:
                    result = 0
        self._worker_id = result
        return result

    def worker_hostnames(self) -> List[str]:
        if self._worker_hostnames is not None:
            return self._worker_hostnames
        raw = self._env.get("TPU_WORKER_HOSTNAMES")
        if not raw:
            meta = self._metadata("worker-network-endpoints")
            if meta:
                # comma-separated list of ip:port:... triples; keep the ips
                raw = ",".join(p.split(":")[2] if p.count(":") >= 2 else p
                               for p in meta.split(","))
        self._worker_hostnames = [h for h in (raw or "").split(",") if h]
        return self._worker_hostnames

    @property
    def topology(self) -> Optional[TopologyInfo]:
        if self._topology is None:
            acc = self.accelerator_type()
            if acc:
                self._topology = parse_accelerator_type(acc)
                if self._topology is None:
                    logger.warning("unrecognized accelerator-type %r", acc)
        return self._topology

    # -- TPUOperator ---------------------------------------------------------

    def devices(self) -> List[TPUChip]:
        indexes = self._accel_indexes()
        vfio = self._vfio_paths()
        topo = self.topology
        if topo is not None:
            hbm, cores = topo.spec.hbm_bytes, topo.spec.cores_per_chip
            family = topo.spec.family
        else:
            hbm, cores, family = _FALLBACK_HBM_BYTES, _FALLBACK_CORES, "tpu"
            if indexes:
                logger.warning(
                    "accelerator-type unknown; advertising conservative "
                    "%d GiB HBM / %d core per chip", hbm // GiB, cores,
                )
        worker = self.worker_id()
        return [
            TPUChip(
                uuid=f"{family}-w{worker}-chip{i}",
                index=i,
                device_path=self.target_path(i),
                hbm_bytes=hbm,
                cores=cores,
                extra_paths=vfio,
            )
            for i in indexes
        ]

    def healthy_indexes(self) -> set:
        """A chip is healthy while its /dev/accelN chardev is present; a
        wedged/detached chip (driver reset, host maintenance event) drops
        its node, and kubelet must stop placing fractional units on it."""
        return set(self._accel_indexes())
