"""TPU-VM operator: real chip discovery on a Cloud TPU host.

Replaces the reference's NVML enumeration (pkg/operator/base.go:19-75,
cgo → driver) with the TPU-native inventory sources (SURVEY.md §2 native
item 3, §7 "hard parts" — there is no NVML analogue, so we assemble from
partial information and tolerate every source being absent):

1. ``/dev/accel*`` (and ``/dev/vfio/*`` on vfio-based stacks) — which
   chardevs exist, i.e. how many chips this host exposes.
2. GCE metadata server — ``accelerator-type`` (e.g. "v5litepod-8") and
   ``agent-worker-number`` / ``tpu-env`` for multi-host slice identity.
3. Environment (``TPU_ACCELERATOR_TYPE``, ``TPU_WORKER_ID``) — GKE and
   test overrides.
4. The static generation table (topology.py) — HBM/TensorCores per chip.
"""

from __future__ import annotations

import glob
import logging
import os
import re
import time
from typing import Callable, Dict, List, Optional

from .operator import LinkingOperator, TPUChip
from .topology import GiB, TopologyInfo, parse_accelerator_type

logger = logging.getLogger(__name__)

_METADATA_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/attributes/"
)
# maintenance-event lives directly under instance/, not instance/attributes/
_MAINTENANCE_EVENT_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/"
    "maintenance-event"
)
# spot/preemptible VMs: flips to TRUE when the instance is being
# preempted (the ACPI G2 notice window) — the drain orchestrator's
# second trigger source.
_PREEMPTED_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/preempted"
)
_METADATA_HEADERS = {"Metadata-Flavor": "Google"}
_METADATA_TIMEOUT_S = 2.0

# Poll cost control defaults: maintenance-event / preempted are
# re-fetched at most every POLL_TTL (the drain poll loop must not hammer
# metadata), and after a transport failure (non-GCE host, kind node) the
# endpoint is left alone for ERROR_BACKOFF so polling stays cheap where
# there is no metadata server at all. Overridable per instance
# (constructor / --maintenance-poll-ttl) and via env for tests:
# ELASTIC_TPU_MAINTENANCE_POLL_TTL / ELASTIC_TPU_MAINTENANCE_ERROR_BACKOFF.
_MAINTENANCE_POLL_TTL_S = 30.0
_MAINTENANCE_ERROR_BACKOFF_S = 300.0

# sysfs error counters: only counters that unambiguously mean "this chip is
# broken" flip health — correctable-error counters tick during normal
# operation and must not. Override the name filter via
# ELASTIC_TPU_SYS_ERROR_PATTERNS (comma-separated substrings).
_SYS_ACCEL_ROOT = "/sys/class/accel"
_FATAL_COUNTER_SUBSTRINGS = ("fatal", "uncorrectable")

# sysfs utilization telemetry (sampler.py): the first of these file names
# found under accelN/ or accelN/device/ supplies each value. Override via
# ELASTIC_TPU_SYS_DUTY_FILES / ELASTIC_TPU_SYS_HBM_FILES (comma-separated
# names) for driver stacks exposing different names.
_DUTY_CYCLE_FILES = ("duty_cycle_percent", "duty_cycle", "usage_percent")
_HBM_USED_FILES = ("hbm_used_bytes", "mem_used_bytes", "memory_used")

# Conservative fallback when the generation cannot be determined: assume the
# smallest HBM of any supported generation so fractional tpu-memory is never
# over-advertised.
_FALLBACK_HBM_BYTES = 16 * GiB
_FALLBACK_CORES = 1

MetadataFetcher = Callable[[str], Optional[str]]


def _fetch_metadata_url(url: str) -> Optional[str]:
    try:
        import requests

        resp = requests.get(
            url, headers=_METADATA_HEADERS, timeout=_METADATA_TIMEOUT_S
        )
        if resp.status_code == 200:
            return resp.text.strip()
    except Exception:  # noqa: BLE001 - any transport failure = "absent"
        pass
    return None


def _default_metadata_fetcher(attribute: str) -> Optional[str]:
    return _fetch_metadata_url(_METADATA_URL + attribute)


def _default_maintenance_fetcher() -> Optional[str]:
    """Current GCE maintenance-event value ("NONE" when quiet,
    "MIGRATE_ON_HOST_MAINTENANCE"/"TERMINATE_ON_HOST_MAINTENANCE" when an
    event is imminent); None when the endpoint is unreachable."""
    return _fetch_metadata_url(_MAINTENANCE_EVENT_URL)


def _default_preempted_fetcher() -> Optional[str]:
    """Current GCE ``preempted`` value ("TRUE"/"FALSE"); None when the
    endpoint is unreachable (non-GCE or non-preemptible host)."""
    return _fetch_metadata_url(_PREEMPTED_URL)


_COUNTER_WALK_DEPTH = 3


def _counter_files(chip_dir: str):
    """(dir, filename) pairs under a sysfs accelN entry, to a bounded
    depth. Real sysfs reaches counters through symlinks —
    /sys/class/accel/accelN is itself a link into /sys/devices/..., and
    accelN/device links to the PCI device dir holding aer_dev_fatal /
    aer_dev_uncorrectable — so the class dir and its device link are
    realpath'd explicitly; everything below walks WITHOUT following links
    (sysfs is cyclic through subsystem/ and friends)."""
    roots = [os.path.realpath(chip_dir)]
    dev = os.path.join(chip_dir, "device")
    if os.path.isdir(dev):
        real_dev = os.path.realpath(dev)
        if not any(real_dev.startswith(r + os.sep) or real_dev == r
                   for r in roots):
            roots.append(real_dev)
    for top in roots:
        for root, dirs, files in os.walk(top, followlinks=False):
            depth = root[len(top):].count(os.sep)
            if depth >= _COUNTER_WALK_DEPTH:
                dirs[:] = []
            for name in files:
                yield root, name


def read_counter_file(path: str) -> Optional[int]:
    """Reduce a sysfs error-counter file to one integer.

    Two real-world shapes: a plain single integer (simple driver
    counters), and the PCIe AER table — one ``ERROR_NAME count`` pair per
    line with a ``TOTAL_ERR_*`` summary row, e.g.::

        TLP 0
        FCP 1
        CmpltTO 0
        TOTAL_ERR_FATAL 1

    The AER parse prefers the TOTAL row and otherwise sums the per-error
    rows. (int(read) on the whole file — the previous behavior — raised
    on every real aer_dev_fatal/aer_dev_uncorrectable and silently
    disabled the signal the code targets; ADVICE r2/r3.)
    Returns None for unreadable/unparseable content."""
    try:
        with open(path) as f:
            raw = f.read().strip()
    except OSError:
        return None
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        pass
    total, matched = 0, False
    for line in raw.splitlines():
        parts = line.split()
        if len(parts) < 2:
            continue
        try:
            value = int(parts[-1], 0)
        except ValueError:
            continue
        if parts[0].startswith("TOTAL_ERR"):
            return value
        total += value
        matched = True
    return total if matched else None


def read_float_file(path: str) -> Optional[float]:
    """One float (or int, or AER-table) out of a sysfs telemetry file.
    Drivers report duty cycle as "37" or "37.5"; read_counter_file alone
    would reject the fractional form and a healthy chip would look like
    a telemetry failure."""
    try:
        with open(path) as f:
            raw = f.read().strip()
    except OSError:
        return None
    try:
        return float(raw)
    except ValueError:
        value = read_counter_file(path)
        return float(value) if value is not None else None


def parse_tpu_env(raw: str) -> Dict[str, str]:
    """Parse the metadata ``tpu-env`` attribute: lines of KEY: 'value'."""
    out: Dict[str, str] = {}
    for line in raw.splitlines():
        m = re.match(r"^\s*([A-Z0-9_]+)\s*:\s*'?([^']*)'?\s*$", line)
        if m:
            out[m.group(1)] = m.group(2)
    return out


class TPUVMOperator(LinkingOperator):
    """Discovery against a real (or faked-in-tests) TPU-VM host."""

    def __init__(
        self,
        dev_root: str,
        host_dev_scan_root: Optional[str] = None,
        metadata: MetadataFetcher = _default_metadata_fetcher,
        env: Optional[Dict[str, str]] = None,
        maintenance: Callable[[], Optional[str]] = _default_maintenance_fetcher,
        sys_accel_root: Optional[str] = None,
        preemption: Callable[[], Optional[str]] = _default_preempted_fetcher,
        maintenance_poll_ttl_s: Optional[float] = None,
        maintenance_error_backoff_s: Optional[float] = None,
    ) -> None:
        # dev_root: where virtual links are created (host /dev mount).
        # host_dev_scan_root: where to look for accel* chardevs (defaults to
        # the same mount — tests point both at a fixture dir).
        super().__init__(dev_root)
        self._scan_root = host_dev_scan_root or dev_root
        self._metadata = metadata
        self._env = env if env is not None else dict(os.environ)
        self._topology: Optional[TopologyInfo] = None
        # Worker identity is fixed for the host's lifetime; memoize so the
        # PreStart hot path never re-hits the metadata server.
        self._worker_id: Optional[int] = None
        self._worker_hostnames: Optional[List[str]] = None
        # -- health sources beyond node presence -------------------------
        self._maintenance = maintenance
        self._maint_cached: Optional[str] = None
        self._maint_next_poll = 0.0
        self._preemption = preemption
        self._preempt_cached: Optional[str] = None
        self._preempt_next_poll = 0.0

        def _ttl(env_key: str, arg: Optional[float], default: float) -> float:
            if arg is not None:
                return arg
            raw = self._env.get(env_key)
            try:
                return float(raw) if raw else default
            except ValueError:
                return default

        self._maint_poll_ttl_s = _ttl(
            "ELASTIC_TPU_MAINTENANCE_POLL_TTL",
            maintenance_poll_ttl_s, _MAINTENANCE_POLL_TTL_S,
        )
        self._maint_error_backoff_s = _ttl(
            "ELASTIC_TPU_MAINTENANCE_ERROR_BACKOFF",
            maintenance_error_backoff_s, _MAINTENANCE_ERROR_BACKOFF_S,
        )
        self._sys_root = sys_accel_root or self._env.get(
            "ELASTIC_TPU_SYS_ACCEL_ROOT", _SYS_ACCEL_ROOT
        )
        self._counter_patterns = tuple(
            p.strip() for p in self._env.get(
                "ELASTIC_TPU_SYS_ERROR_PATTERNS", ""
            ).split(",") if p.strip()
        ) or _FATAL_COUNTER_SUBSTRINGS
        self._duty_files = tuple(
            p.strip() for p in self._env.get(
                "ELASTIC_TPU_SYS_DUTY_FILES", ""
            ).split(",") if p.strip()
        ) or _DUTY_CYCLE_FILES
        self._hbm_files = tuple(
            p.strip() for p in self._env.get(
                "ELASTIC_TPU_SYS_HBM_FILES", ""
            ).split(",") if p.strip()
        ) or _HBM_USED_FILES
        # chip -> {counter path -> baseline value}; a chip whose fatal
        # counter moved past its baseline stays unhealthy (sticky) until
        # agent restart — transient "recovery" of a chip that faulted is
        # not trusted.
        self._counter_base: Dict[int, Dict[str, int]] = {}
        self._error_chips: set = set()
        self._ever_present: set = set()
        self._health_reasons: Dict[int, str] = {}
        # chip -> the reason it entered _error_chips; never cleared while
        # the chip stays sticky, so a counter re-baseline (driver reload)
        # can't replace the specific cause with a generic one.
        self._sticky_reasons: Dict[int, str] = {}

    # -- inventory sources ---------------------------------------------------

    def _accel_indexes(self) -> List[int]:
        found = []
        for path in glob.glob(os.path.join(self._scan_root, "accel[0-9]*")):
            m = re.search(r"accel(\d+)$", path)
            if m:
                found.append(int(m.group(1)))
        return sorted(found)

    def _vfio_paths(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self._scan_root, "vfio", "*")))

    def accelerator_type(self) -> Optional[str]:
        for key in ("TPU_ACCELERATOR_TYPE", "ACCELERATOR_TYPE"):
            if self._env.get(key):
                return self._env[key]
        val = self._metadata("accelerator-type")
        if val:
            return val
        raw = self._metadata("tpu-env")
        if raw:
            parsed = parse_tpu_env(raw)
            if parsed.get("ACCELERATOR_TYPE"):
                return parsed["ACCELERATOR_TYPE"]
        return None

    def worker_id(self) -> int:
        if self._worker_id is not None:
            return self._worker_id
        result = 0
        if self._env.get("TPU_WORKER_ID"):
            try:
                result = int(self._env["TPU_WORKER_ID"])
            except ValueError:
                result = 0
        else:
            val = self._metadata("agent-worker-number")
            if val:
                try:
                    result = int(val)
                except ValueError:
                    result = 0
        self._worker_id = result
        return result

    def worker_hostnames(self) -> List[str]:
        if self._worker_hostnames is not None:
            return self._worker_hostnames
        raw = self._env.get("TPU_WORKER_HOSTNAMES")
        if not raw:
            meta = self._metadata("worker-network-endpoints")
            if meta:
                # comma-separated list of ip:port:... triples; keep the ips
                raw = ",".join(p.split(":")[2] if p.count(":") >= 2 else p
                               for p in meta.split(","))
        self._worker_hostnames = [h for h in (raw or "").split(",") if h]
        return self._worker_hostnames

    @property
    def topology(self) -> Optional[TopologyInfo]:
        if self._topology is None:
            acc = self.accelerator_type()
            if acc:
                self._topology = parse_accelerator_type(acc)
                if self._topology is None:
                    logger.warning("unrecognized accelerator-type %r", acc)
        return self._topology

    # -- TPUOperator ---------------------------------------------------------

    def devices(self) -> List[TPUChip]:
        indexes = self._accel_indexes()
        vfio = self._vfio_paths()
        topo = self.topology
        if topo is not None:
            hbm, cores = topo.spec.hbm_bytes, topo.spec.cores_per_chip
            family = topo.spec.family
        else:
            hbm, cores, family = _FALLBACK_HBM_BYTES, _FALLBACK_CORES, "tpu"
            if indexes:
                logger.warning(
                    "accelerator-type unknown; advertising conservative "
                    "%d GiB HBM / %d core per chip", hbm // GiB, cores,
                )
        worker = self.worker_id()
        return [
            TPUChip(
                uuid=f"{family}-w{worker}-chip{i}",
                index=i,
                device_path=self.target_path(i),
                hbm_bytes=hbm,
                cores=cores,
                extra_paths=vfio,
            )
            for i in indexes
        ]

    # -- health ---------------------------------------------------------------

    def maintenance_event(self) -> Optional[str]:
        """The current GCE maintenance-event value, TTL-cached: "NONE"
        while quiet, the event name while one is announced, None while
        the endpoint is unreachable. The drain orchestrator's trigger
        source — an announced event cordons + drains the node instead of
        flipping chips unhealthy (drain.py owns the response)."""
        now = time.monotonic()
        if now >= self._maint_next_poll:
            val = self._maintenance()
            self._maint_cached = val
            self._maint_next_poll = now + (
                self._maint_poll_ttl_s if val is not None
                else self._maint_error_backoff_s
            )
        return self._maint_cached

    def _maintenance_imminent(self) -> bool:
        """True while GCE reports an upcoming host maintenance event
        (TTL-cached via :meth:`maintenance_event`)."""
        return self.maintenance_event() not in (None, "", "NONE")

    def preempted(self) -> bool:
        """True once GCE announces this (spot/preemptible) instance is
        being preempted. Same TTL/backoff discipline as the maintenance
        poll; a host with no ``preempted`` endpoint reads False."""
        now = time.monotonic()
        if now >= self._preempt_next_poll:
            val = self._preemption()
            self._preempt_cached = val
            self._preempt_next_poll = now + (
                self._maint_poll_ttl_s if val is not None
                else self._maint_error_backoff_s
            )
        return (self._preempt_cached or "").strip().upper() == "TRUE"

    def _matching_counter_values(self, chip_dir: str):
        """(name, path, value) for every readable error-counter file under
        a chip dir matching the configured patterns — the ONE scan both
        the health fold and the node-doctor snapshot consume, so a
        discovery fix can never apply to one and not the other."""
        for root, name in _counter_files(chip_dir):
            if not any(p in name for p in self._counter_patterns):
                continue
            path = os.path.join(root, name)
            value = read_counter_file(path)
            if value is not None:
                yield name, path, value

    def _scan_error_counters(self, present: List[int]) -> None:
        """Fold /sys/class/accel/accelN fatal-error counters into the
        sticky error-chip set: the first observation of each counter is its
        baseline (counters survive agent restarts; pre-existing nonzero
        values are not our signal), any later increase marks the chip."""
        for i in present:
            chip_dir = os.path.join(self._sys_root, f"accel{i}")
            if not os.path.isdir(chip_dir):
                continue
            base = self._counter_base.setdefault(i, {})
            for name, path, value in self._matching_counter_values(chip_dir):
                if path not in base:
                    base[path] = value
                elif value > base[path]:
                    if i not in self._error_chips:
                        logger.warning(
                            "chip %d: fatal counter %s %d -> %d; "
                            "marking unhealthy", i, path, base[path],
                            value,
                        )
                    self._error_chips.add(i)
                    self._sticky_reasons[i] = (
                        f"fatal error counter {name} rose to {value}"
                    )
                elif value < base[path]:
                    # Counter reset (driver reload): re-baseline downward,
                    # or errors 1..old-baseline would be masked forever.
                    base[path] = value

    def healthy_indexes(self) -> set:
        """A chip is healthy while (a) its /dev/accelN chardev is present
        (a wedged/detached chip drops its node) and (b) no sysfs
        fatal-error counter has risen since baseline.

        A GCE maintenance event deliberately does NOT fail health any
        more: flipping every chip unhealthy stranded resident workloads
        with no checkpoint signal and let slice peers discover the loss
        after the fact. The drain orchestrator (drain.py) polls
        :meth:`maintenance_event` / :meth:`preempted` and responds with
        the graceful lifecycle instead — cordon (unschedulable without
        unhealthy), checkpoint-signal residents, proactively re-form
        slices, then reclaim on a deadline."""
        present = self._accel_indexes()
        self._ever_present.update(present)
        reasons = {
            i: "device node missing"
            for i in self._ever_present if i not in present
        }
        self._scan_error_counters(present)
        for i in self._error_chips:
            reasons[i] = self._sticky_reasons.get(
                i, "reported unhealthy by operator"
            )
        self._health_reasons = reasons
        return set(present) - self._error_chips

    def health_reasons(self) -> Dict[int, str]:
        """Why each currently-unhealthy chip is unhealthy (best effort)."""
        return dict(self._health_reasons)

    # -- utilization telemetry (sampler.py) -----------------------------------

    def _util_file(self, chip_dir: str, names) -> Optional[str]:
        """First existing candidate file under accelN/ or accelN/device/."""
        dev = os.path.join(chip_dir, "device")
        for name in names:
            for base in (chip_dir, dev):
                path = os.path.join(base, name)
                if os.path.isfile(path) or os.path.islink(path):
                    return path
        return None

    def utilization(self) -> Dict[int, dict]:
        """Per-chip duty cycle / HBM usage from sysfs. A chip with no
        telemetry files contributes no entry (absence != failure); a chip
        whose duty file exists but does not parse contributes an error
        entry — the sampler flags it unhealthy after a streak."""
        out: Dict[int, dict] = {}
        for i in self._accel_indexes():
            chip_dir = os.path.join(self._sys_root, f"accel{i}")
            if not os.path.isdir(chip_dir):
                continue
            duty_path = self._util_file(chip_dir, self._duty_files)
            if duty_path is None:
                continue
            duty = read_float_file(duty_path)
            if duty is None:
                out[i] = {"error": f"unreadable telemetry file {duty_path}"}
                continue
            entry = {"duty_cycle_percent": duty, "hbm_used_bytes": 0}
            hbm_path = self._util_file(chip_dir, self._hbm_files)
            if hbm_path is not None:
                hbm = read_float_file(hbm_path)
                if hbm is not None:
                    entry["hbm_used_bytes"] = int(hbm)
            out[i] = entry
        return out

    def error_counters(self) -> Dict[int, Dict[str, int]]:
        """Current raw values of every matching error-counter file, keyed
        by chip — the node-doctor snapshot (healthy_indexes folds these
        into health; this is the unprocessed evidence)."""
        out: Dict[int, Dict[str, int]] = {}
        for i in self._accel_indexes():
            chip_dir = os.path.join(self._sys_root, f"accel{i}")
            if not os.path.isdir(chip_dir):
                continue
            counters = {
                path: value
                for _, path, value in self._matching_counter_values(chip_dir)
            }
            if counters:
                out[i] = counters
        return out
