"""Exclusive (whole-chip) operator: no virtual nodes needed.

Parity with the reference's NvidiaOperator no-op passthrough
(pkg/operator/nvidia.go:1-22): in whole-chip mode the kubelet's own
device-plugin device list already maps 1:1 to physical chips, so
create/delete/check are no-ops and only discovery matters.
"""

from __future__ import annotations

from typing import List

from .operator import TPUOperator, TPUChip


class ExclusiveOperator(TPUOperator):
    virtual_nodes = False

    def __init__(self, inner: TPUOperator) -> None:
        self._inner = inner

    def devices(self) -> List[TPUChip]:
        return self._inner.devices()

    def health_reasons(self) -> dict:
        # Defined on the TPUOperator base, so __getattr__ would not forward
        # it — delegate explicitly to keep the inner operator's detail.
        return self._inner.health_reasons()

    def utilization(self) -> dict:
        # Same base-class-shadowing concern as health_reasons.
        return self._inner.utilization()

    def error_counters(self) -> dict:
        return self._inner.error_counters()

    def __getattr__(self, name):
        # Forward discovery-adjacent surface (topology, worker_id,
        # worker_hostnames, healthy_indexes, fault-injection seams) so
        # wrapping costs no capability; only create/delete/check are muted.
        return getattr(self._inner, name)

    def create(self, index: int, link_id: str) -> None:  # noqa: ARG002
        return None

    def delete(self, link_id: str) -> None:  # noqa: ARG002
        return None

    def check(self, link_id: str) -> bool:  # noqa: ARG002
        return True
