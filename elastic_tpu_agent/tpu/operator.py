"""TPU operator contract + shared virtual-device-node mechanics.

Capability parity with the reference's ``pkg/operator`` (SURVEY.md §1 L4):
``GPUOperator{Devices, Create, Delete, Check}`` becomes ``TPUOperator``.
The virtual-device scheme carries over: a hash-named symlink under the
host's /dev whose *target* encodes the physical chip, so the OCI prestart
hook can resolve allocations with nothing but readlink
(reference: /dev/elastic-gpu-<id> -> /dev/nvidiaN, operator/gpushare.go:31-55;
hook resolve at elastic-gpu-hook/main.go:122-158).

TPU-native differences:
- targets are ``/dev/accel<index>`` (TPU-VM chardevs) instead of
  ``/dev/nvidiaN``; there is no per-node "ctl" device to mirror, so one
  link per chip (no elastic-gpuctl-* analogue).
- chips carry HBM size, TensorCore count, and (optionally) vfio paths from
  discovery, since fractional tpu-memory advertisement needs HBM and slice
  env needs topology (SURVEY.md §2 native item 3).
"""

from __future__ import annotations

import logging
import os
import re
import threading
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import List, Optional

from .. import faults
from ..common import VirtualDevPrefix
from ..tracing import get_tracer

logger = logging.getLogger(__name__)


class OperatorError(Exception):
    pass


@dataclass(frozen=True)
class TPUChip:
    """One physical TPU chip as discovered on this host."""

    uuid: str              # stable id (e.g. "tpu-v5e-<host>-3" or metadata id)
    index: int             # host-local chip index (the N of /dev/accelN)
    device_path: str       # host path of the chardev, e.g. "/dev/accel3"
    hbm_bytes: int         # HBM capacity of this chip
    cores: int             # TensorCores on this chip
    extra_paths: List[str] = field(default_factory=list)  # e.g. vfio nodes


class TPUOperator(ABC):
    """Physical device layer: discovery + virtual node lifecycle."""

    # Whether this operator materializes per-allocation virtual nodes
    # (/dev/elastic-tpu-<hash>-N). Whole-chip operators set this False and
    # the plugin hands out physical /dev/accel* paths at Allocate instead.
    virtual_nodes: bool = True

    @abstractmethod
    def devices(self) -> List[TPUChip]:
        """Enumerate this host's chips (reference: Devices(), base.go:19-45)."""

    @abstractmethod
    def create(self, index: int, link_id: str) -> None:
        """Materialize virtual node ``elastic-tpu-<link_id>`` -> chip <index>."""

    @abstractmethod
    def delete(self, link_id: str) -> None:
        """Remove the virtual node; missing nodes are not an error."""

    @abstractmethod
    def check(self, link_id: str) -> bool:
        """True when the virtual node exists."""

    def healthy_indexes(self) -> set:
        """Chip indexes currently healthy. Default: every discovered chip.
        Operators with a live health source (device-node presence for
        tpu-vm, injected faults for the stub) override this; the plugin
        layer polls it and flips kubelet device health on changes — a
        capability NVML gave the reference for free (XIDs) and TPU has no
        single analogue for."""
        return {c.index for c in self.devices()}

    def health_reasons(self) -> dict:
        """Best-effort {chip index: why it is unhealthy}, surfaced in the
        TPUChipUnhealthy node event. Default: no detail."""
        return {}

    def utilization(self) -> dict:
        """Per-chip telemetry snapshot for the utilization sampler
        (sampler.py): {chip index: {"duty_cycle_percent": float,
        "hbm_used_bytes": int}}, or {"error": str} per chip whose read
        failed. An empty dict means "this backend has no telemetry" —
        the sampler then records nothing rather than flagging chips
        (absence is not failure)."""
        return {}

    def error_counters(self) -> dict:
        """Raw error-counter snapshot {chip index: {counter path: value}}
        for the node-doctor bundle. Default: none."""
        return {}


# -- shared symlink mechanics -------------------------------------------------

_ACCEL_RE = re.compile(r"accel(\d+)$")


def chip_index_from_target(target: str) -> Optional[int]:
    """Parse the chip index out of a link target like "/dev/accel3"
    (reference parsed N from /dev/nvidiaN, hook main.go:122-130)."""
    m = _ACCEL_RE.search(target)
    return int(m.group(1)) if m else None


class LinkingOperator(TPUOperator):
    """Base for operators that realize virtual devices as symlinks.

    ``dev_root`` is the host's /dev as mounted into the agent container
    (default /host/dev — deploy manifest hostPath). Link *targets* are
    host-namespace paths (/dev/accelN): they may dangle inside the agent
    container, which is fine — only the host-side hook resolves them.
    """

    def __init__(self, dev_root: str, target_root: str = "/dev") -> None:
        self._dev_root = dev_root
        self._target_root = target_root

    def link_path(self, link_id: str) -> str:
        return os.path.join(self._dev_root, VirtualDevPrefix + link_id)

    def target_path(self, index: int) -> str:
        return os.path.join(self._target_root, f"accel{index}")

    def create(self, index: int, link_id: str) -> None:
        """Crash-atomic, idempotent create with verify-after-write.

        The link is made under a temp name and renamed into place
        (``os.replace`` = one atomic rename syscall), so no crash point
        can leave a half-made or wrong-target link at the final path:
        either the old state survives intact or the complete new link
        does. A leaked temp (crash between symlink and rename) carries
        the virtual prefix, so the reconciler's orphan sweep reclaims
        it like any other unrecorded link. Re-creating an existing,
        correct link is a no-op (journal replay / restore path)."""
        faults.fire("operator.create")
        link = self.link_path(link_id)
        target = self.target_path(index)
        with get_tracer().span("operator_create", link=link, target=target):
            try:
                if os.path.islink(link) and os.readlink(link) == target:
                    return  # idempotent re-create (replay/restore path)
                # Unique per pid AND thread: the reconciler's repair of
                # a missing link can race a kubelet-driven rebind of the
                # SAME link id — two threads sharing one temp path would
                # delete each other's pending temps and fail a healthy
                # bind. A temp leaked by a crash carries the virtual
                # prefix, so the orphan sweep reclaims it.
                tmp = f"{link}.{os.getpid()}.{threading.get_ident()}.tmp"
                try:
                    os.unlink(tmp)  # stale temp from this thread's retry
                except FileNotFoundError:
                    pass
                os.symlink(target, tmp)
                os.replace(tmp, link)
            except OSError as e:
                raise OperatorError(f"create {link} -> {target}: {e}") from e
            # Verify-after-write: a create the journal replays must be
            # trustworthy — read the link back instead of assuming the
            # rename landed (NFS-ish hostPaths do lie).
            try:
                back = os.readlink(link)
            except OSError as e:
                raise OperatorError(
                    f"create {link}: verify-after-write failed: {e}"
                ) from e
            if back != target:
                raise OperatorError(
                    f"create {link}: verify-after-write mismatch "
                    f"({back!r} != {target!r})"
                )
        logger.info("created virtual TPU node %s -> %s", link, target)

    def delete(self, link_id: str) -> None:
        """Idempotent delete: ENOENT is success (journal rollback and
        orphan sweeps replay deletes freely), and the removal is
        verified before being reported successful."""
        faults.fire("operator.delete")
        link = self.link_path(link_id)
        with get_tracer().span("operator_delete", link=link):
            try:
                os.unlink(link)
                logger.info("removed virtual TPU node %s", link)
            except FileNotFoundError:
                pass
            except OSError as e:
                raise OperatorError(f"delete {link}: {e}") from e
            if os.path.islink(link):  # verify-after-write
                raise OperatorError(f"delete {link}: link still present")

    def check(self, link_id: str) -> bool:
        return os.path.islink(self.link_path(link_id))

    def resolve(self, link_id: str) -> Optional[int]:
        """Chip index a virtual node points at, or None."""
        try:
            return chip_index_from_target(os.readlink(self.link_path(link_id)))
        except OSError:
            return None

    def list_links(self) -> List[str]:
        """All virtual-node link ids currently present (Restore/GC sweep)."""
        try:
            names = os.listdir(self._dev_root)
        except OSError:
            return []
        return [
            n[len(VirtualDevPrefix):]
            for n in names
            if n.startswith(VirtualDevPrefix)
        ]
