from .operator import TPUChip, TPUOperator, OperatorError
from .stub import StubOperator
from .tpuvm import TPUVMOperator
from .exclusive import ExclusiveOperator
from .topology import ChipSpec, TopologyInfo, parse_accelerator_type

__all__ = [
    "TPUChip",
    "TPUOperator",
    "OperatorError",
    "StubOperator",
    "TPUVMOperator",
    "ExclusiveOperator",
    "ChipSpec",
    "TopologyInfo",
    "parse_accelerator_type",
]
