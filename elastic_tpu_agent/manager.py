"""Manager: lifecycle wiring + the Restore() the reference never wrote.

Capability parity with ``pkg/manager/manager.go`` (SURVEY.md §1 L2):
construct clients, open storage, start the sitter with a delete hook
feeding the GC queue, build the plugin bundle, then Run(). The reference
declared ``GC(); Restore()`` on its interface and implemented neither
(manager.go:17-21); both are real here:

- restore(): at boot, reconcile the checkpoint store against the world —
  re-create missing virtual nodes for live pods (the host's /dev may have
  been wiped), drop state for pods that no longer exist (SURVEY.md §3.5).
- GC runs event-driven from sitter deletions plus a 60s reconcile tick.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from . import rpc, supervisor as supervision, timeline as timeline_mod
from .kube.client import KubeClient
from .kube.locator import KubeletDeviceLocator, PodResourcesSnapshotSource
from .kube.sitter import Sitter
from .plugins.base import PluginConfig
from .plugins.tpushare import DEFAULT_ALLOC_SPEC_DIR, TPUSharePlugin
from .storage import Storage
from .supervisor import CRITICAL, DEGRADED, Supervisor
from .tpu import StubOperator, TPUVMOperator

logger = logging.getLogger(__name__)


@dataclass
class ManagerOptions:
    """Functional-options equivalent (reference: manager.go:33-57)."""

    node_name: str = ""
    db_path: str = "/host/var/lib/elastic-tpu/meta.db"
    kubeconfig: str = ""
    plugin_kind: str = "tpushare"
    operator_kind: str = "tpuvm"  # tpuvm | stub[:<type>] | exclusive[:<inner>]
    dev_root: str = "/host/dev"
    device_plugin_dir: str = rpc.DEVICE_PLUGIN_DIR
    pod_resources_socket: str = rpc.POD_RESOURCES_SOCKET
    alloc_spec_dir: str = DEFAULT_ALLOC_SPEC_DIR
    metrics_port: int = 0  # 0 = disabled
    # Publish bound allocations as ElasticTPU CRD objects (the path the
    # reference commented out; crd_recorder.py). Failures never affect
    # binding; auto-disables if the CRD is absent.
    enable_crd: bool = True
    # Emit core/v1 Events on bind/reclaim/restore (kube/events.py) — the
    # RBAC grant the reference carried but never exercised.
    enable_events: bool = True
    # containerd NRI activation (nri/plugin.py): when set, the agent
    # registers as an external NRI plugin on this socket and injects
    # devices at CreateContainer — the containerd/GKE replacement for the
    # hooks.d chain ("" = off).
    nri_socket: str = ""
    # host path of libtpu.so to bind-mount into TPU containers via NRI
    # ("" = images ship their own).
    nri_libtpu: str = ""
    # Policy (default OFF): when a chip goes unhealthy, ask containerd
    # (via NRI UpdateContainers) to evict containers whose injected
    # devices include it — the bind is immutable post-create, so
    # eviction is the only in-band recovery; kubelet restarts the pod
    # onto healthy chips.
    nri_evict_on_chip_failure: bool = False
    # Utilization & health sampler (sampler.py): per-chip duty-cycle/HBM
    # sampling joined against the allocation store, exported via metrics,
    # /debug/allocations and node-doctor.
    enable_sampler: bool = True
    sampler_period_s: float = 10.0
    # One pod-resources snapshot shared by the core and memory locators:
    # a cold core+memory bind pair costs ONE kubelet List instead of two,
    # and either resource's Allocate-time prefetch warms both PreStarts.
    # False restores the historical one-cache-per-resource shape (the
    # bench's same-run baseline).
    shared_locator_snapshot: bool = True
    # gRPC worker threads per device-plugin resource server
    # (plugins/base.py; CLI --dp-pool-size).
    dp_pool_size: int = 8
    # Supervision (supervisor.py): a subsystem crashing this many times
    # inside the sliding window is circuit-broken (marked failed instead
    # of thrashing); critical subsystems then flip /healthz to 503 so the
    # DaemonSet liveness probe restarts the pod.
    crash_loop_threshold: int = supervision.DEFAULT_CRASH_LOOP_THRESHOLD
    crash_loop_window_s: float = supervision.DEFAULT_CRASH_LOOP_WINDOW_S
    # Continuous reconciler (reconciler.py): the boot-time restore
    # promoted to a supervised loop that keeps diffing store <-> kubelet
    # <-> disk <-> live pods and repairing drift (CLI --reconcile-period
    # / --reconcile-dry-run; dry-run makes periodic passes observe-only
    # — the boot pass always repairs).
    reconcile_period_s: float = 30.0
    reconcile_dry_run: bool = False
    # Slice orchestration (slices/registry.py): how long one apiserver
    # membership snapshot stays fresh — bounds slice-tracking apiserver
    # traffic from the bind path and the reconciler alike.
    slice_membership_ttl_s: float = 5.0
    # Graceful drain lifecycle (drain.py): the hard checkpoint deadline
    # between the drain signal and binding reclaim, and the trigger-poll
    # period (jittered 0.75x-1.25x). --drain-deadline / --drain-period.
    drain_deadline_s: float = 300.0
    drain_period_s: float = 2.0
    # Preemption notice window (--preemption-notice): a spot host gives
    # this much warning before the platform reclaims it, so a
    # preemption-triggered drain's budget (and the pre-copy cutover
    # margin derived from it) is clamped to min(deadline, notice).
    preemption_notice_s: float = 30.0
    # Dynamic fractional re-partitioning (repartition.py): live quota
    # renegotiation for pods that opt in via elasticgpu.io/repartition,
    # with throttle -> evict escalation for sustained overcommit.
    # Requires the sampler (it is the usage signal); --no-repartition /
    # --repartition-period / --qos-evict-after.
    enable_repartition: bool = True
    repartition_period_s: float = 10.0
    qos_evict_after_s: float = 300.0
    # Migration coordinator (migration.py): the verified checkpoint
    # handshake — consume workload acks, complete drains early, gate
    # QoS eviction, publish MigrationRecords, verify resumes on the
    # destination. --migration-period / --no-migration.
    enable_migration: bool = True
    migration_period_s: float = 2.0
    # tpuvm operator: maintenance/preempted metadata poll TTL override
    # (--maintenance-poll-ttl; None = the operator's default, env
    # ELASTIC_TPU_MAINTENANCE_POLL_TTL also honored for tests).
    maintenance_poll_ttl_s: Optional[float] = None
    # Lifecycle timeline (timeline.py): ring cap on the durable event
    # journal (--timeline-cap). Small caps are a test/smoke seam; the
    # eviction counter keeps trims observable either way.
    timeline_cap: int = timeline_mod.DEFAULT_CAP
    # Goodput ledger (goodput.py): journal-replay period for the per-pod
    # state partition + downtime-by-cause rollup (--goodput-period).
    goodput_period_s: float = 10.0
    # Slow-span WARNING/timeline threshold override in milliseconds
    # (--slow-span-ms; None = the tracer's default / the
    # ELASTIC_TPU_SLOW_SPAN_MS env). Slow spans also land in the
    # lifecycle timeline as slow_span events, keyed pod + trace.
    slow_span_ms: Optional[float] = None
    # Continuous sampling profiler (profiler.py): samples per second for
    # the supervised sys._current_frames() walk (--profile-hz; 0 = off).
    profile_hz: float = 0.0
    # Group-commit write batching (storage/batcher.py): >0 coalesces
    # storage commits into one flush per window — load-bearing writes
    # (bind checkpoints, intent journals, agent_state) still block until
    # their covering commit lands; timeline events and intent-commit
    # row drops ride async. 0 = every write commits itself.
    # CLI --storage-batch-window.
    storage_batch_window_s: float = 0.0
    # AsyncSink coalescing window (async_sink.py): >0 makes the CRD and
    # event sinks linger after waking so a bind's burst of apiserver
    # writes batches/dedups into one drain. CLI --sink-flush-window.
    sink_flush_window_s: float = 0.0
    # Event-driven core (events.py): an in-process bus carries pod
    # deltas (apiserver watch), assignment deltas (kubelet List diffs)
    # and store-change notifications (bind/intent/state commits) to the
    # reconciler, drain, repartition, migration and sampler loops, which
    # run targeted passes on relevant events. The jittered periodic
    # sweep stays as the correctness backstop, stretched by
    # event_safety_net_factor while the bus is healthy and the loop is
    # quiet. False = exact pre-event polling (poll-only fallback mode).
    # CLI --no-event-bus / --event-safety-net-factor.
    enable_event_bus: bool = True
    event_safety_net_factor: float = 10.0
    # test seams
    kube_client: Optional[KubeClient] = None
    operator: object = None
    metrics: object = None
    extra: dict = field(default_factory=dict)


def build_operator(opts: ManagerOptions):
    if opts.operator is not None:
        return opts.operator
    kind = opts.operator_kind
    if kind == "exclusive" or kind.startswith("exclusive:"):
        # Whole-chip mode (reference: pkg/operator/nvidia.go no-op
        # passthrough): discovery comes from the wrapped operator, but no
        # virtual nodes are created — device specs hand out the physical
        # /dev/accel* paths directly. `exclusive:<inner>` selects the
        # discovery source, default tpuvm.
        from dataclasses import replace

        from .tpu.exclusive import ExclusiveOperator

        inner_kind = kind.partition(":")[2] or "tpuvm"
        return ExclusiveOperator(
            build_operator(replace(opts, operator_kind=inner_kind))
        )
    if kind == "tpuvm":
        return TPUVMOperator(
            opts.dev_root,
            maintenance_poll_ttl_s=opts.maintenance_poll_ttl_s,
        )
    if kind.startswith("stub"):
        acc = kind.partition(":")[2] or "v5litepod-4"
        # Worker identity for multi-host simulations (kind clusters / CI):
        # the tpuvm operator reads these from the metadata server; the stub
        # takes them from the agent's own environment.
        hostnames = [
            h for h in os.environ.get(
                "ELASTIC_TPU_STUB_HOSTNAMES", ""
            ).split(",") if h
        ]
        try:
            # tolerate malformed values like the tpuvm operator does
            # (tpuvm.py worker_id falls back to 0)
            worker_id = int(os.environ.get("ELASTIC_TPU_STUB_WORKER_ID", "0"))
        except ValueError:
            worker_id = 0
        return StubOperator(
            opts.dev_root, acc,
            hostname=os.environ.get(
                "ELASTIC_TPU_STUB_HOSTNAME", "stub-host"
            ),
            worker_id=worker_id,
            worker_hostnames=hostnames,
        )
    raise ValueError(f"unknown operator kind {kind!r}")


class TPUManager:
    def __init__(self, opts: ManagerOptions) -> None:
        self._opts = opts
        # Event bus first: the storage layer publishes store-change
        # notifications from its commit path, so the bus must exist
        # before the first write. None = poll-only fallback mode; every
        # consumer degenerates to the pre-event jittered sweep.
        self.bus = None
        if opts.enable_event_bus:
            from . import events as events_mod

            self.bus = events_mod.EventBus()
        self.storage = Storage(
            opts.db_path, batch_window_s=opts.storage_batch_window_s,
            bus=self.bus,
        )
        # The lifecycle timeline rides the checkpoint db (one fsync
        # domain, one hostPath) and is handed to every subsystem that
        # makes state transitions — created first so even supervisor
        # bring-up events are journaled.
        self.timeline = timeline_mod.Timeline(
            self.storage,
            node_name=opts.node_name,
            metrics=opts.metrics,
            cap=opts.timeline_cap,
        )
        self.supervisor = Supervisor(
            metrics=opts.metrics,
            crash_loop_threshold=opts.crash_loop_threshold,
            crash_loop_window_s=opts.crash_loop_window_s,
            timeline=self.timeline,
        )
        self.client = opts.kube_client or KubeClient.auto(opts.kubeconfig)
        self.gc_queue: "queue.Queue" = queue.Queue()
        self.sitter = Sitter(
            self.client,
            opts.node_name,
            on_delete=self.gc_queue.put,
            bus=self.bus,
        )
        self.operator = build_operator(opts)
        self.metrics = opts.metrics
        if self.metrics is not None and hasattr(
            self.metrics, "attach_supervisor"
        ):
            self.metrics.attach_supervisor(self.supervisor)
        if self.metrics is not None and hasattr(self.metrics, "attach_sitter"):
            self.metrics.attach_sitter(self.sitter)
        if self.metrics is not None and hasattr(
            self.metrics, "attach_storage"
        ):
            # Write/commit amplification accounting (group-commit
            # batching) rides the scrape like every other counter.
            self.metrics.attach_storage(self.storage)
        if self.metrics is not None and hasattr(
            self.metrics, "attach_timeline"
        ):
            # /debug/timeline serves the journal; /healthz gains the
            # boot id so restarts are attributable from either side.
            self.metrics.attach_timeline(self.timeline)
        if self.metrics is not None:
            try:
                n = len(self.operator.devices())
                self.metrics.chips.set(n)
                self.metrics.healthy_chips.set(n)
            except Exception:  # noqa: BLE001 - discovery failure: gauge stays 0
                logger.exception("chip discovery for metrics failed")
        # Critical-path latency observatory (latency.py) + continuous
        # profiler (profiler.py). The observatory listens on the
        # process-wide tracer; in the fleet sim many agents share that
        # tracer, so both the observatory and the slow-span handler
        # filter on the trace's node attribute (stop() deregisters).
        from .latency import BindLatencyObservatory, DetectionLagTracker
        from .profiler import SamplingProfiler
        from .tracing import get_tracer

        self.lag_tracker = DetectionLagTracker(metrics=self.metrics)
        self.latency = BindLatencyObservatory(
            metrics=self.metrics, node_name=opts.node_name
        )
        self.profiler = SamplingProfiler(hz=opts.profile_hz)
        tracer = get_tracer()
        if opts.slow_span_ms is not None:
            tracer.slow_span_s = max(0.0, opts.slow_span_ms / 1000.0)
        tracer.add_listener(self.latency.observe_trace)

        def _on_slow_span(tr, sp) -> None:
            node = str(tr.attrs.get("node", ""))
            if opts.node_name and node and node != opts.node_name:
                return  # another sim agent's span on the shared tracer
            pod = str(
                tr.attrs.get("pod", "")
                or ((tr.attrs.get("pods") or [""]) or [""])[0]
            )
            self.timeline.emit(
                timeline_mod.KIND_SLOW_SPAN,
                keys={"pod": pod, "trace": tr.trace_id},
                span=sp.name,
                duration_ms=round(sp.duration_s * 1000, 3),
                threshold_ms=round(tracer.slow_span_s * 1000, 3),
                op=tr.name,
            )

        self._on_slow_span = _on_slow_span
        tracer.add_slow_span_listener(self._on_slow_span)
        if self.metrics is not None and hasattr(
            self.metrics, "attach_latency"
        ):
            self.metrics.attach_latency(self.latency, self.lag_tracker)
        if self.metrics is not None and hasattr(
            self.metrics, "attach_profiler"
        ):
            self.metrics.attach_profiler(self.profiler)
        self.crd_recorder = None
        if opts.enable_crd:
            from .crd_recorder import build_recorder

            self.crd_recorder = build_recorder(
                self.client, opts.node_name, self.operator,
                metrics=self.metrics,
                flush_window_s=opts.sink_flush_window_s,
            )
        self.events = None
        if opts.enable_events:
            from .kube.events import build_event_recorder

            self.events = build_event_recorder(
                self.client, opts.node_name, metrics=self.metrics,
                flush_window_s=opts.sink_flush_window_s,
            )
        self.sampler = None
        if opts.enable_sampler:
            from .sampler import UtilizationSampler

            self.sampler = UtilizationSampler(
                self.operator,
                storage=self.storage,
                metrics=self.metrics,
                alloc_spec_dir=opts.alloc_spec_dir,
                period_s=opts.sampler_period_s,
                lag_tracker=self.lag_tracker,
                bus=self.bus,
            )
            if self.metrics is not None and hasattr(
                self.metrics, "attach_sampler"
            ):
                self.metrics.attach_sampler(self.sampler)
        from .slices import SliceRegistry

        # Slice orchestration (slices/): the registry owns multi-host
        # slice membership/identity; PreStart stamps through it and the
        # reconciler's reformer advances it on member loss.
        self.slice_registry = SliceRegistry(
            node_name=opts.node_name,
            kube_client=self.client,
            metrics=self.metrics,
            events=self.events,
            membership_ttl_s=opts.slice_membership_ttl_s,
        )
        pr_client = rpc.PodResourcesClient(opts.pod_resources_socket)
        self.pr_client = pr_client
        if opts.shared_locator_snapshot:
            shared_source = PodResourcesSnapshotSource(
                pr_client, metrics=self.metrics, bus=self.bus
            )
            # The reconciler diffs against the same snapshot layer the
            # locators use, so its periodic List rides the single-flight
            # machinery instead of adding independent kubelet load.
            self.locator_source = shared_source
            locator_factory = lambda res: KubeletDeviceLocator(  # noqa: E731
                res, source=shared_source
            )
        else:
            # Only the reconciler's source publishes assignment deltas;
            # the per-resource locator sources stay silent so one
            # kubelet change is one event, not one per cache.
            self.locator_source = PodResourcesSnapshotSource(
                pr_client, metrics=self.metrics, bus=self.bus
            )
            locator_factory = lambda res: KubeletDeviceLocator(  # noqa: E731
                res,
                source=PodResourcesSnapshotSource(
                    pr_client, metrics=self.metrics
                ),
            )
        self.config = PluginConfig(
            node_name=opts.node_name,
            device_plugin_dir=opts.device_plugin_dir,
            pod_resources_socket=opts.pod_resources_socket,
            grpc_pool_size=opts.dp_pool_size,
            operator=self.operator,
            sitter=self.sitter,
            storage=self.storage,
            locator_factory=locator_factory,
            metrics=self.metrics,
            crd_recorder=self.crd_recorder,
            events=self.events,
            sampler=self.sampler,
            slice_registry=self.slice_registry,
            timeline=self.timeline,
            extra={"alloc_spec_dir": opts.alloc_spec_dir, **opts.extra},
        )
        from .plugins.base import plugin_factory

        self.plugin = plugin_factory(opts.plugin_kind, self.config)
        if self.sampler is not None and hasattr(self.plugin, "locator_stats"):
            self.sampler.locator_stats_fn = self.plugin.locator_stats
        if self.sampler is not None and hasattr(self.plugin, "bind_stats"):
            self.sampler.bind_stats_fn = self.plugin.bind_stats
        if self.sampler is not None and hasattr(self.plugin, "core"):
            # Snapshot health from the plugin's applied view, not a fresh
            # operator probe — debug HTTP threads must not race the
            # health poller through TPUVMOperator's unsynchronized state.
            self.sampler.unhealthy_view_fn = self.plugin.core.unhealthy_chips
        from .reconciler import Reconciler
        from .slices import SliceReformer

        self.slice_reformer = SliceReformer(
            self.slice_registry, self.plugin,
            metrics=self.metrics, events=self.events,
            timeline=self.timeline,
        )
        self.reconciler = Reconciler(
            storage=self.storage,
            operator=self.operator,
            plugin=self.plugin,
            sitter=self.sitter,
            snapshot_source=self.locator_source,
            alloc_spec_dir=opts.alloc_spec_dir,
            metrics=self.metrics,
            events=self.events,
            crd_recorder=self.crd_recorder,
            period_s=opts.reconcile_period_s,
            dry_run=opts.reconcile_dry_run,
            slice_reformer=self.slice_reformer,
            timeline=self.timeline,
            lag_tracker=self.lag_tracker,
            bus=self.bus,
            event_safety_net_factor=opts.event_safety_net_factor,
        )
        from .drain import DrainOrchestrator

        # Graceful drain lifecycle (drain.py): maintenance events,
        # preemption notices and operator-requested drains cordon +
        # checkpoint-signal + proactively re-form slices + reclaim on a
        # deadline, with every transition journaled in storage.
        self.drain = DrainOrchestrator(
            operator=self.operator,
            plugin=self.plugin,
            storage=self.storage,
            sitter=self.sitter,
            reconciler=self.reconciler,
            kube_client=self.client,
            events=self.events,
            metrics=self.metrics,
            node_name=opts.node_name,
            deadline_s=opts.drain_deadline_s,
            preemption_notice_s=opts.preemption_notice_s,
            period_s=opts.drain_period_s,
            timeline=self.timeline,
            lag_tracker=self.lag_tracker,
            bus=self.bus,
            event_safety_net_factor=opts.event_safety_net_factor,
        )
        # While the drain has reclaimed bindings, kubelet's still-listed
        # assignments must not be replayed back by the reconciler.
        self.reconciler.drain = self.drain
        # Migration coordinator (migration.py): the verified checkpoint
        # handshake on top of the drain's signal — consume acks,
        # reclaim acked residents early, publish MigrationRecords,
        # verify inbound resumes.
        self.migration = None
        if opts.enable_migration:
            from .migration import MigrationCoordinator

            self.migration = MigrationCoordinator(
                storage=self.storage,
                plugin=self.plugin,
                sitter=self.sitter,
                reconciler=self.reconciler,
                drain=self.drain,
                kube_client=self.client,
                crd_recorder=self.crd_recorder,
                events=self.events,
                metrics=self.metrics,
                node_name=opts.node_name,
                alloc_spec_dir=opts.alloc_spec_dir,
                period_s=opts.migration_period_s,
                timeline=self.timeline,
                lag_tracker=self.lag_tracker,
                bus=self.bus,
                event_safety_net_factor=opts.event_safety_net_factor,
            )
            # Early-reclaimed residents' kubelet assignments must not be
            # replayed back; the drain classifies completions by ack.
            self.reconciler.migration = self.migration
            self.drain.migration = self.migration
        # Dynamic fractional re-partitioning (repartition.py): sampler
        # windows -> live quota restamps. The sampler IS the usage
        # signal, so no sampler means no repartitioning.
        self.repartition = None
        if opts.enable_repartition and self.sampler is not None:
            from .repartition import RepartitionController

            self.repartition = RepartitionController(
                sampler=self.sampler,
                storage=self.storage,
                sitter=self.sitter,
                plugin=self.plugin,
                reconciler=self.reconciler,
                metrics=self.metrics,
                events=self.events,
                timeline=self.timeline,
                node_name=opts.node_name,
                period_s=opts.repartition_period_s,
                evict_after_s=opts.qos_evict_after_s,
                lag_tracker=self.lag_tracker,
                bus=self.bus,
                event_safety_net_factor=opts.event_safety_net_factor,
            )
            # Evicted pods' kubelet assignments must not be replayed
            # back, and the overcommit alarm must judge usage against
            # the EFFECTIVE (adjusted) grant.
            self.reconciler.repartition = self.repartition
            self.sampler.grant_adjust_fn = (
                self.repartition.core_delta_percent
            )
            self.sampler.repartition_status_fn = self.repartition.status
            # QoS eviction gated by the checkpoint handshake: a
            # throttled pod's durable ack publishes a MigrationRecord
            # before (and can advance) the reclaim.
            self.repartition.migration = self.migration
        if self.sampler is not None:
            # Self-reports steer attribution (and, with the controller
            # on, ENFORCEMENT), so only opted-in pods' usage files are
            # ever trusted — wired unconditionally: even in alarm-only
            # mode (--no-repartition) a non-participant must not
            # under-report and shift phantom duty onto a co-tenant the
            # overcommit alarm then blames.
            def _report_allowed(pod_key: str) -> bool:
                from .qos import repartition_opt_in

                ns, _, name = pod_key.partition("/")
                pod = self.sitter.get_pod(ns, name)
                if pod is None:
                    return False
                ann = (pod.get("metadata") or {}).get("annotations") or {}
                return repartition_opt_in(ann)

            self.sampler.usage_report_allowed_fn = _report_allowed
            # /debug/allocations and the doctor bundle carry the live
            # reconcile/journal state (open intents, per-class repairs).
            self.sampler.reconcile_status_fn = self.reconciler.status
            self.sampler.slice_status_fn = self.slice_registry.status
            self.sampler.drain_status_fn = self.drain.status
            if self.migration is not None:
                self.sampler.migration_status_fn = self.migration.status
            if self.bus is not None:
                self.sampler.event_bus_stats_fn = self.bus.stats
        # Goodput ledger (goodput.py): replays the timeline journal into
        # per-pod productive/downtime partitions with causal attribution
        # — the SLI the drain/migration/repartition machinery above is
        # judged by. Reads the same db the journal writes, so it needs
        # no hooks into the subsystems themselves.
        from .goodput import GoodputLedger

        self.goodput = GoodputLedger(
            storage=self.storage,
            node_name=opts.node_name,
            metrics=self.metrics,
            migration=self.migration,
            period_s=opts.goodput_period_s,
            lag_tracker=self.lag_tracker,
        )
        if self.metrics is not None and hasattr(
            self.metrics, "attach_goodput"
        ):
            self.metrics.attach_goodput(self.goodput)
        self.nri_plugin = None
        if opts.nri_socket:
            from .nri import NRIPlugin

            # Mount.source in an NRI adjustment resolves in the HOST mount
            # namespace; the agent's own view is under the /host hostPath
            # prefix, so strip it for the mount source.
            host_alloc = opts.alloc_spec_dir
            if host_alloc.startswith("/host/"):
                host_alloc = host_alloc[len("/host"):]
            self.nri_plugin = NRIPlugin(
                socket_path=opts.nri_socket,
                alloc_spec_dir=opts.alloc_spec_dir,
                host_alloc_dir=host_alloc,
                dev_root=opts.dev_root,
                libtpu_path=opts.nri_libtpu,
                metrics=self.metrics,
            )
            if opts.nri_evict_on_chip_failure:
                if hasattr(self.plugin, "on_chips_failed"):
                    self.plugin.on_chips_failed = (
                        self.nri_plugin.evict_for_chips
                    )
                    self.plugin.on_chips_recovered = (
                        self.nri_plugin.clear_failed_chips
                    )
                else:
                    logger.warning(
                        "nri_evict_on_chip_failure set but plugin kind "
                        "%r has no health hooks; policy is INACTIVE",
                        opts.plugin_kind,
                    )
        self._stop = threading.Event()
        self._stopped = False

    # -- Restore (SURVEY.md §3.5: declared-but-unimplemented upstream) --------

    def restore(self) -> dict:
        """Boot-time convergence: one reconciler pass with boot semantics
        (acts immediately — the device-plugin servers are not registered
        yet, so no bind can be in flight). The same logic then keeps
        running periodically as the supervised ``reconciler`` subsystem;
        this entry point survives for its callers (run(), tests, tools)
        and for the Restored node event + restore metrics."""
        from .tracing import get_tracer

        with get_tracer().trace("restore") as tr:
            report = self.reconciler.reconcile_once(boot=True)
            tr.set(**{
                k: v for k, v in report.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            })
        logger.info("restore report: %s", report)
        replayed = (
            report["intents_committed"] + report["intents_rolled_back"]
            + report["replayed_binds"] + report["rebound_drift"]
        )
        if self.events is not None and (
            report["restored_links"] or report["reclaimed_pods"]
            or report["orphan_links"] or report["orphan_specs"] or replayed
        ):
            from .kube.events import ReasonRestored

            self.events.node_event(
                ReasonRestored,
                "agent restart reconcile: "
                f"{report['restored_links']} link(s) restored, "
                f"{report['reclaimed_pods']} dead pod(s) reclaimed, "
                f"{report['orphan_links'] + report['orphan_specs']} "
                "orphan artifact(s) swept, "
                f"{replayed} interrupted bind(s) recovered",
            )
        if self.metrics is not None:
            self.metrics.restored_links.inc(report["restored_links"])
            self.metrics.bound_allocations.set(self.storage.count())
        return report

    def check_allocatable_drift(self) -> Optional[dict]:
        """Cross-check kubelet's allocatable-device view (pod-resources v1
        GetAllocatableResources) against this agent's advertisement — a
        chip kubelet still counts allocatable that we no longer advertise
        (or vice versa) means scheduler math is wrong on this node.

        Returns {resource: {"missing": [chips], "extra": [chips]}} for
        drifted resources, {} when in sync, None when the kubelet cannot
        answer (v1alpha1-only or the allocatable gate is off)."""
        from .common import ResourceTPUCore, ResourceTPUMemory
        from .plugins.tpushare import chip_of_device_id

        if getattr(self.plugin, "cordoned", False):
            # A drain cordon advertises every device Unhealthy by
            # design; comparing kubelet's (correctly shrunken) view
            # against discovery would cry drift on every drained node.
            return None
        try:
            resp = self.pr_client.get_allocatable_resources()
        except Exception as e:  # noqa: BLE001 - diagnostic, never fatal
            logger.warning("allocatable cross-check failed: %s", e)
            return None
        if resp is None:
            return None
        ours = {c.index for c in self.operator.devices()}
        # Chips we ourselves advertise Unhealthy are EXPECTED to be absent
        # from kubelet's allocatable view — comparing against them would
        # turn every health report into a false drift warning.
        core = getattr(self.plugin, "core", None)
        if core is not None and hasattr(core, "unhealthy_chips"):
            ours -= core.unhealthy_chips()
        drift: dict = {}
        for resource in (ResourceTPUCore, ResourceTPUMemory):
            seen: set = set()
            found = False
            for dev in resp.devices:
                if dev.resource_name != resource:
                    continue
                found = True
                for did in dev.device_ids:
                    chip = chip_of_device_id(did)
                    if chip is not None:
                        seen.add(chip)
            if not found:
                # kubelet has not consumed our ListAndWatch yet (fresh
                # boot) — absence is indistinguishable from lag; skip.
                continue
            missing = sorted(ours - seen)
            extra = sorted(seen - ours)
            if missing or extra:
                drift[resource] = {"missing": missing, "extra": extra}
        if drift:
            logger.warning("allocatable drift vs kubelet: %s", drift)
            if self.events is not None:
                from .kube.events import ReasonAllocatableDrift

                parts = []
                for resource, d in sorted(drift.items()):
                    if d["missing"]:
                        parts.append(
                            f"{resource}: kubelet missing chip(s) "
                            f"{','.join(map(str, d['missing']))}"
                        )
                    if d["extra"]:
                        parts.append(
                            f"{resource}: kubelet still counts absent "
                            f"chip(s) {','.join(map(str, d['extra']))}"
                        )
                self.events.node_event(
                    ReasonAllocatableDrift,
                    "kubelet allocatable view disagrees with agent "
                    "advertisement — " + "; ".join(parts),
                    type_="Warning",
                )
        return drift

    _ALLOCATABLE_CHECK_DELAY_S = 10.0

    def _deferred_allocatable_check(self, stop: threading.Event) -> None:
        # Deferred: right after Register, kubelet has not consumed the
        # first ListAndWatch yet, so an immediate check would always cry
        # drift on a fresh boot. Registered one-shot under the supervisor:
        # a crash here is retried with backoff instead of being swallowed.
        if stop.wait(self._ALLOCATABLE_CHECK_DELAY_S):
            return
        self.check_allocatable_drift()

    # -- Run ------------------------------------------------------------------

    def run(self, block: bool = True) -> None:
        """Start sitter, wait for sync, restore, start plugins + GC —
        every background loop registered as a supervised subsystem
        (supervisor.py): uncaught-exception trap, jittered restart
        backoff, crash-loop circuit breaker, criticality-aware /healthz.

        ``block=True`` blocks on the supervisor's terminal event (global
        stop, or a critical subsystem circuit-breaking) — previously it
        joined the GC thread alone, so a crashed GC exited (or wedged)
        the whole agent arbitrarily."""
        from . import __version__

        # agent_started FIRST: histories read across restarts must show
        # the boot boundary (version + boot id) before any event this
        # process emits, and the build-info/start-time gauges make the
        # same facts scrapeable.
        self.timeline.emit(
            timeline_mod.KIND_AGENT_STARTED,
            version=__version__,
            boot_id=self.timeline.boot_id,
        )
        if self.metrics is not None:
            if hasattr(self.metrics, "build_info"):
                try:
                    self.metrics.build_info.labels(
                        version=__version__
                    ).set(1)
                except Exception:  # noqa: BLE001 - observability only
                    logger.exception("build-info gauge failed")
            if hasattr(self.metrics, "agent_start_time"):
                try:
                    self.metrics.agent_start_time.set(time.time())
                except Exception:  # noqa: BLE001
                    pass
        self.supervisor.start(self._stop)
        # Sitter is CRITICAL: binds read annotations from its cache and GC
        # learns deletions through it; a circuit-broken sitter means the
        # node can neither bind correctly nor reclaim.
        self.supervisor.register("sitter", self.sitter.run, CRITICAL)
        if not self.sitter.wait_synced(timeout=60.0):
            logger.warning("sitter not synced after 60s; continuing anyway")
        if self.crd_recorder is not None:
            # Capacity first, bindings after: CRD consumers should see this
            # node's chips as Available inventory from boot (reference CRD
            # phases, types.go:49-78), not only Bound lifecycle objects.
            try:
                self.crd_recorder.publish_inventory(self.operator.devices())
            except Exception:  # noqa: BLE001 - observability, never fatal
                logger.exception("inventory publication failed")
        # Journaled drain state BEFORE the boot reconcile: a node that
        # rebooted mid-drain must re-enter the lifecycle (cordon back
        # up, replay suppression armed) before the boot pass runs, or
        # restore() would faithfully replay the very bindings the drain
        # reclaimed. The supervised loop's own resume() is then a no-op
        # re-read.
        self.drain.resume()
        if self.migration is not None:
            # Journaled handshake state BEFORE the boot reconcile, like
            # the drain: replay suppression for early-reclaimed pods
            # must be armed before restore() walks kubelet's
            # still-listed assignments, and half-published records must
            # finish publishing.
            self.migration.resume()
        if self.repartition is not None:
            # Journaled quota ledger BEFORE the boot reconcile, like the
            # drain: replay suppression for QoS-evicted pods must be
            # armed before restore() walks kubelet's assignments, and a
            # crash mid-restamp must converge before binds resume.
            self.repartition.resume()
        # Goodput anchors BEFORE the first replay: pods whose bind
        # events the ring already trimmed keep their journaled lifetime
        # starts across the restart, like drain/migration state.
        self.goodput.resume()
        self.restore()
        # Device-plugin serve loops: one per extended resource, CRITICAL —
        # a dead ListAndWatch leaves kubelet advertising stale devices.
        for server in getattr(self.plugin, "servers", []):
            self.supervisor.register(
                f"device-plugin:{server.resource_name}", server.run, CRITICAL
            )
        self.supervisor.register(
            "gc",
            lambda stop: self.plugin.gc(self.gc_queue, stop),
            CRITICAL,
        )
        if hasattr(self.plugin, "health_loop"):
            self.supervisor.register(
                "health", self.plugin.health_loop, DEGRADED
            )
        # Continuous reconciler: DEGRADED — a broken reconciler leaves the
        # node binding (with the boot-converged state) while /healthz and
        # the doctor bundle surface the loss of self-repair.
        self.supervisor.register("reconciler", self.reconciler.run, DEGRADED)
        # Drain orchestrator: DEGRADED — losing lifecycle handling must
        # not take binding down; resume() re-enters the journaled drain
        # on every (re)start, so a crashed loop (or agent) picks the
        # drain back up where it died.
        self.supervisor.register("drain", self.drain.run, DEGRADED)
        if self.migration is not None:
            # Migration coordinator: DEGRADED — losing the handshake
            # must not take binding down; drains then simply run to
            # their deadline, exactly the pre-handshake behavior.
            self.supervisor.register(
                "migration", self.migration.run, DEGRADED
            )
        if self.repartition is not None:
            # Repartition controller: DEGRADED — losing live quota
            # renegotiation leaves static grants in force, never binding.
            self.supervisor.register(
                "repartition", self.repartition.run, DEGRADED
            )
        if self.sampler is not None:
            self.supervisor.register("sampler", self.sampler.run, DEGRADED)
        if self._opts.profile_hz > 0:
            # Continuous self-profiler: DEGRADED — observability must never
            # take binding down. A crashed profiler restarts with its stack
            # table intact (same instance, table survives the respawn).
            self.supervisor.register("profiler", self.profiler.run, DEGRADED)
        # Goodput ledger: DEGRADED — losing the SLI rollup must never
        # take binding down; the journal keeps accruing either way and
        # the next tick replays it all.
        self.supervisor.register("goodput", self.goodput.run, DEGRADED)
        if self.nri_plugin is not None:
            self.supervisor.register("nri", self.nri_plugin.run, DEGRADED)
        if self.crd_recorder is not None and hasattr(
            self.crd_recorder, "run_supervised"
        ):
            self.supervisor.register(
                "crd-recorder", self.crd_recorder.run_supervised, DEGRADED
            )
        if self.events is not None and hasattr(self.events, "run_supervised"):
            self.supervisor.register(
                "events", self.events.run_supervised, DEGRADED
            )
        self.supervisor.register(
            "allocatable-check", self._deferred_allocatable_check, DEGRADED,
            one_shot=True,
        )
        if block:
            self.supervisor.wait_terminal()

    def stop(self) -> None:
        if self._stopped:  # idempotent: double-stop must be harmless
            return
        self._stopped = True
        self._stop.set()
        # Detach the latency listeners from the process-global tracer:
        # fleet-sim restarts construct a fresh manager per node and a
        # stale listener would keep attributing the next incarnation's
        # traces to this one's (dead) observatory.
        from .tracing import get_tracer

        tracer = get_tracer()
        tracer.remove_listener(self.latency.observe_trace)
        tracer.remove_slow_span_listener(self._on_slow_span)
        self.gc_queue.put(None)  # wake GC so it can observe stop
        # Join GC before stopping the recorder: an in-flight gc_once() may
        # still enqueue record_released, which would be silently dropped if
        # the recorder worker had already consumed its stop sentinel.
        self.supervisor.join("gc", timeout=10.0)
        # Same invariant for the health poller: it submits events too.
        self.supervisor.join("health", timeout=10.0)
        self.supervisor.join("sampler", timeout=10.0)
        # The reconciler both writes storage and submits CRD releases:
        # join it before the recorder stops and the db closes.
        self.supervisor.join("reconciler", timeout=10.0)
        # The drain loop journals into storage and emits events too.
        self.supervisor.join("drain", timeout=10.0)
        # The migration coordinator journals, reclaims and publishes
        # through the CRD sink; join it before the recorder stops and
        # the db closes.
        self.supervisor.join("migration", timeout=10.0)
        # The repartition loop journals and restamps specs; join it
        # before the recorder stops and the db closes.
        self.supervisor.join("repartition", timeout=10.0)
        # The goodput ledger reads the journal and writes its anchors;
        # join it before the db closes under it.
        self.supervisor.join("goodput", timeout=10.0)
        if self.nri_plugin is not None:
            self.nri_plugin.stop()
        if hasattr(self.plugin, "core"):
            self.plugin.core.stop_streams()
            self.plugin.memory.stop_streams()
        if self.crd_recorder is not None:
            self.crd_recorder.stop()
        if self.events is not None:
            self.events.stop()
        self.storage.close()
