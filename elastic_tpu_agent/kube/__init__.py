from .locator import DeviceLocator, KubeletDeviceLocator, LocateError
from .sitter import Sitter

__all__ = ["DeviceLocator", "KubeletDeviceLocator", "LocateError", "Sitter"]
