"""Sitter: node-filtered pod cache with a delete hook feeding GC.

Capability parity with the reference's ``pkg/kube/sitter.go`` (SURVEY.md §1
L5): an informer-style list+watch over the pods bound to this node, a read
cache (get_pod), apiserver fallbacks (get_pod_from_api /
get_node_from_api), has_synced, and a DeleteFunc hook that forwards pod
deletions to the manager's GC channel.

Instead of the reference's 1-second full resync (sitter.go:61, papering
over watch staleness), we run a real watch with re-list on expiry plus a
periodic safety re-list.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from .. import events as events_mod
from .. import faults
from ..common import JitteredBackoff
from .client import KubeClient, KubeError

logger = logging.getLogger(__name__)

DeleteHook = Callable[[dict], None]

# list/watch failure backoff: jittered exponential instead of the old
# fixed 1.0s — a dead apiserver must not be hammered once a second by
# every node's agent in lockstep, and recovery still starts fast.
RETRY_MIN_S = 1.0
RETRY_MAX_S = 30.0


class Sitter:
    def __init__(
        self,
        client: KubeClient,
        node_name: str,
        on_delete: Optional[DeleteHook] = None,
        relist_interval_s: float = 30.0,
        bus=None,
    ) -> None:
        self._client = client
        self._node = node_name
        self._on_delete = on_delete
        self._relist_s = relist_interval_s
        # Event bus (events.EventBus, optional): pod deltas publish on
        # POD_DELTA straight off the watch stream; a dead list/watch
        # flips the bus degraded so every subscribed loop collapses its
        # stretched safety-net sweep back to the base period with no
        # coverage gap (the AsyncSink/brownout fix — see run()).
        self._bus = bus
        self._lock = threading.RLock()
        self._cache: Dict[Tuple[str, str], dict] = {}
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # monotonic timestamp of the last successful apiserver contact
        # (relist success or watch event); 0.0 = never. Staleness is
        # surfaced via /healthz and elastic_tpu_sitter_sync_age_seconds
        # so a long apiserver outage is visible instead of silent cache
        # rot.
        self._last_sync_monotonic = 0.0

    # -- cache reads ----------------------------------------------------------

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def wait_synced(self, timeout: Optional[float] = None) -> bool:
        return self._synced.wait(timeout)

    def sync_age_s(self) -> Optional[float]:
        """Seconds since the cache last heard from the apiserver, or None
        before the first successful list."""
        last = self._last_sync_monotonic
        if last == 0.0:
            return None
        return max(0.0, time.monotonic() - last)

    def get_pod(self, namespace: str, name: str) -> Optional[dict]:
        with self._lock:
            return self._cache.get((namespace, name))

    def pods(self) -> list:
        with self._lock:
            return list(self._cache.values())

    # -- apiserver fallbacks (reference: sitter.go GetPodFromApiServer) -------

    def get_pod_from_api(self, namespace: str, name: str) -> Optional[dict]:
        return self._client.get_pod(namespace, name)

    def get_node_from_api(self, name: str) -> Optional[dict]:
        return self._client.get_node(name)

    # -- list+watch loop ------------------------------------------------------

    @staticmethod
    def _key(pod: dict) -> Tuple[str, str]:
        md = pod.get("metadata", {})
        return md.get("namespace", ""), md.get("name", "")

    def _relist(self) -> str:
        faults.fire("sitter.relist")
        items, rv = self._client.list_pods(self._node)
        fresh = {self._key(p): p for p in items}
        with self._lock:
            gone = set(self._cache) - set(fresh)
            gone_pods = [self._cache[k] for k in gone]
            self._cache = fresh
        # Deletions that happened while we were not watching still reach GC.
        for pod in gone_pods:
            self._fire_delete(pod)
            self._publish(events_mod.POD_DELTA, "relist-gone", pod)
        self._last_sync_monotonic = time.monotonic()
        self._synced.set()
        return rv

    def _publish(self, topic: str, kind: str, pod: dict) -> None:
        if self._bus is None:
            return
        ns, name = self._key(pod)
        md = pod.get("metadata", {})
        self._bus.publish(topic, kind=kind, key=f"{ns}/{name}",
                          payload={"uid": md.get("uid", ""),
                                   "phase": pod.get("status", {})
                                   .get("phase", "")})

    def _fire_delete(self, pod: dict) -> None:
        if self._on_delete is not None:
            try:
                self._on_delete(pod)
            except Exception:  # noqa: BLE001
                logger.exception("delete hook failed")

    def _handle_event(self, event: dict) -> None:
        etype = event.get("type")
        pod = event.get("object", {})
        key = self._key(pod)
        if etype in ("ADDED", "MODIFIED"):
            with self._lock:
                self._cache[key] = pod
            self._publish(events_mod.POD_DELTA, etype.lower(), pod)
        elif etype == "DELETED":
            with self._lock:
                self._cache.pop(key, None)
            self._fire_delete(pod)
            self._publish(events_mod.POD_DELTA, "deleted", pod)
        elif etype == "ERROR":
            raise KubeError(f"watch error event: {pod}")

    def run(self, stop: threading.Event) -> None:
        """Blocking list+watch loop until ``stop`` (the supervised entry
        point; ``start()`` wraps it in a thread for direct use)."""
        backoff = JitteredBackoff(RETRY_MIN_S, RETRY_MAX_S)
        while not stop.is_set():
            try:
                rv = self._relist()
                backoff.reset()  # apiserver answered
                if self._bus is not None:
                    # The re-list caught us up on anything missed while
                    # the watch was down — safe to let loops stretch
                    # their safety-net sweeps again.
                    self._bus.set_degraded("sitter-watch", False)
                watch_timeout = max(1, int(self._relist_s))
                for event in self._client.watch_pods(
                    self._node, rv, timeout_s=watch_timeout
                ):
                    faults.fire("sitter.watch")
                    self._handle_event(event)
                    self._last_sync_monotonic = time.monotonic()
                    if stop.is_set():
                        return
            except Exception as e:  # noqa: BLE001
                if self._bus is not None:
                    # Watch stream died (apiserver brownout, network
                    # partition): pod deltas stop flowing, so loops
                    # must NOT keep sleeping their stretched periods.
                    # set_degraded broadcasts a BUS_WAKE that collapses
                    # every subscriber back to its base sweep period
                    # immediately — no coverage gap between push dying
                    # and poll resuming.
                    self._bus.set_degraded("sitter-watch", True)
                delay = backoff.next_delay()
                logger.warning(
                    "sitter list/watch failed (%s); retrying in %.1fs "
                    "(cache age: %s)",
                    e, delay,
                    "never-synced" if self.sync_age_s() is None
                    else f"{self.sync_age_s():.0f}s",
                )
                stop.wait(delay)

    def start(self, stop: threading.Event) -> None:
        self._thread = threading.Thread(
            target=self.run, args=(stop,), daemon=True, name="sitter"
        )
        self._thread.start()
