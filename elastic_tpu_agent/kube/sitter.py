"""Sitter: node-filtered pod cache with a delete hook feeding GC.

Capability parity with the reference's ``pkg/kube/sitter.go`` (SURVEY.md §1
L5): an informer-style list+watch over the pods bound to this node, a read
cache (get_pod), apiserver fallbacks (get_pod_from_api /
get_node_from_api), has_synced, and a DeleteFunc hook that forwards pod
deletions to the manager's GC channel.

Instead of the reference's 1-second full resync (sitter.go:61, papering
over watch staleness), we run a real watch with re-list on expiry plus a
periodic safety re-list.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional, Tuple

from .client import KubeClient, KubeError

logger = logging.getLogger(__name__)

DeleteHook = Callable[[dict], None]


class Sitter:
    def __init__(
        self,
        client: KubeClient,
        node_name: str,
        on_delete: Optional[DeleteHook] = None,
        relist_interval_s: float = 30.0,
    ) -> None:
        self._client = client
        self._node = node_name
        self._on_delete = on_delete
        self._relist_s = relist_interval_s
        self._lock = threading.RLock()
        self._cache: Dict[Tuple[str, str], dict] = {}
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- cache reads ----------------------------------------------------------

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def wait_synced(self, timeout: Optional[float] = None) -> bool:
        return self._synced.wait(timeout)

    def get_pod(self, namespace: str, name: str) -> Optional[dict]:
        with self._lock:
            return self._cache.get((namespace, name))

    def pods(self) -> list:
        with self._lock:
            return list(self._cache.values())

    # -- apiserver fallbacks (reference: sitter.go GetPodFromApiServer) -------

    def get_pod_from_api(self, namespace: str, name: str) -> Optional[dict]:
        return self._client.get_pod(namespace, name)

    def get_node_from_api(self, name: str) -> Optional[dict]:
        return self._client.get_node(name)

    # -- list+watch loop ------------------------------------------------------

    @staticmethod
    def _key(pod: dict) -> Tuple[str, str]:
        md = pod.get("metadata", {})
        return md.get("namespace", ""), md.get("name", "")

    def _relist(self) -> str:
        items, rv = self._client.list_pods(self._node)
        fresh = {self._key(p): p for p in items}
        with self._lock:
            gone = set(self._cache) - set(fresh)
            gone_pods = [self._cache[k] for k in gone]
            self._cache = fresh
        # Deletions that happened while we were not watching still reach GC.
        for pod in gone_pods:
            self._fire_delete(pod)
        self._synced.set()
        return rv

    def _fire_delete(self, pod: dict) -> None:
        if self._on_delete is not None:
            try:
                self._on_delete(pod)
            except Exception:  # noqa: BLE001
                logger.exception("delete hook failed")

    def _handle_event(self, event: dict) -> None:
        etype = event.get("type")
        pod = event.get("object", {})
        key = self._key(pod)
        if etype in ("ADDED", "MODIFIED"):
            with self._lock:
                self._cache[key] = pod
        elif etype == "DELETED":
            with self._lock:
                self._cache.pop(key, None)
            self._fire_delete(pod)
        elif etype == "ERROR":
            raise KubeError(f"watch error event: {pod}")

    def _run(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                rv = self._relist()
                watch_timeout = max(1, int(self._relist_s))
                for event in self._client.watch_pods(
                    self._node, rv, timeout_s=watch_timeout
                ):
                    self._handle_event(event)
                    if stop.is_set():
                        return
            except Exception as e:  # noqa: BLE001
                logger.warning("sitter list/watch failed (%s); retrying", e)
                stop.wait(1.0)

    def start(self, stop: threading.Event) -> None:
        self._thread = threading.Thread(
            target=self._run, args=(stop,), daemon=True, name="sitter"
        )
        self._thread.start()
