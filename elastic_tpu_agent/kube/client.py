"""Minimal Kubernetes API client: exactly what the agent needs.

The reference used client-go (clientset + informer factory). This image has
no kubernetes Python package, and the agent touches a tiny API surface —
get/list/watch pods filtered to one node, get node — so a small REST client
over ``requests`` is the honest dependency-free equivalent
(reference client construction: pkg/common/util.go:20-50, in-cluster or
kubeconfig).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, Iterator, Optional, Tuple

import requests

logger = logging.getLogger(__name__)

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeError(Exception):
    pass


class KubeClient:
    # Default (connect, read) timeout for every request: a black-holed
    # apiserver connection must surface as an exception, not a forever-hung
    # thread (the CRD recorder's self-disable depends on failures raising).
    # watch_pods passes its own window-sized timeout.
    DEFAULT_TIMEOUT = (5.0, 30.0)

    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        ca_cert: Optional[str] = None,
        session: Optional[requests.Session] = None,
    ) -> None:
        self._base = base_url.rstrip("/")
        self._session = session or requests.Session()
        if token:
            self._session.headers["Authorization"] = f"Bearer {token}"
        self._verify = ca_cert if ca_cert else False

    # -- constructors ---------------------------------------------------------

    @classmethod
    def in_cluster(cls) -> "KubeClient":
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise KubeError("not running in-cluster (no KUBERNETES_SERVICE_HOST)")
        token_path = os.path.join(SERVICE_ACCOUNT_DIR, "token")
        ca_path = os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
        with open(token_path) as f:
            token = f.read().strip()
        ca = ca_path if os.path.exists(ca_path) else None
        return cls(f"https://{host}:{port}", token=token, ca_cert=ca)

    @classmethod
    def from_kubeconfig(cls, path: str) -> "KubeClient":
        """Supports the common kubeconfig shapes: token / token-file /
        client-cert auth, with both file-path and inline base64 ``*-data``
        variants (kind and GKE kubeconfigs embed the data forms)."""
        import base64
        import tempfile

        import yaml

        def materialize(data_b64: str, suffix: str) -> str:
            f = tempfile.NamedTemporaryFile(
                prefix="elastic-tpu-kubeconfig-", suffix=suffix, delete=False
            )
            f.write(base64.b64decode(data_b64))
            f.close()
            return f.name

        with open(path) as f:
            cfg = yaml.safe_load(f)
        ctx_name = cfg.get("current-context")
        ctx = next(
            c["context"] for c in cfg["contexts"] if c["name"] == ctx_name
        )
        cluster = next(
            c["cluster"] for c in cfg["clusters"] if c["name"] == ctx["cluster"]
        )
        user = next(u["user"] for u in cfg["users"] if u["name"] == ctx["user"])
        session = requests.Session()
        token = user.get("token")
        if not token and user.get("token-file"):
            with open(user["token-file"]) as tf:
                token = tf.read().strip()
        cert = user.get("client-certificate")
        key = user.get("client-key")
        if not cert and user.get("client-certificate-data"):
            cert = materialize(user["client-certificate-data"], ".crt")
        if not key and user.get("client-key-data"):
            key = materialize(user["client-key-data"], ".key")
        if cert and key:
            session.cert = (cert, key)
        ca = cluster.get("certificate-authority")
        if not ca and cluster.get("certificate-authority-data"):
            ca = materialize(cluster["certificate-authority-data"], ".ca.crt")
        return cls(
            cluster["server"], token=token, ca_cert=ca, session=session
        )

    @classmethod
    def auto(cls, kubeconfig: str = "") -> "KubeClient":
        if kubeconfig:
            return cls.from_kubeconfig(kubeconfig)
        return cls.in_cluster()

    # -- request plumbing -----------------------------------------------------

    def _get(self, path: str, params: Optional[Dict] = None, **kw):
        kw.setdefault("timeout", self.DEFAULT_TIMEOUT)
        return self._session.get(
            self._base + path, params=params, verify=self._verify, **kw
        )

    def _post(self, path: str, body: dict, **kw):
        kw.setdefault("timeout", self.DEFAULT_TIMEOUT)
        return self._session.post(
            self._base + path, json=body, verify=self._verify, **kw
        )

    def _put(self, path: str, body: dict, **kw):
        kw.setdefault("timeout", self.DEFAULT_TIMEOUT)
        return self._session.put(
            self._base + path, json=body, verify=self._verify, **kw
        )

    def _delete(self, path: str, **kw):
        kw.setdefault("timeout", self.DEFAULT_TIMEOUT)
        return self._session.delete(
            self._base + path, verify=self._verify, **kw
        )

    def _patch(self, path: str, body: dict, **kw):
        kw.setdefault("timeout", self.DEFAULT_TIMEOUT)
        headers = dict(kw.pop("headers", {}))
        headers.setdefault("Content-Type", "application/merge-patch+json")
        return self._session.patch(
            self._base + path, data=json.dumps(body), headers=headers,
            verify=self._verify, **kw
        )

    # -- API surface ----------------------------------------------------------

    def get_pod(self, namespace: str, name: str) -> Optional[dict]:
        """Pod manifest dict, or None on 404 (apiserver-NotFound is a GC
        decision input, reference: base.go:266-277)."""
        r = self._get(f"/api/v1/namespaces/{namespace}/pods/{name}")
        if r.status_code == 404:
            return None
        if r.status_code != 200:
            raise KubeError(f"get pod {namespace}/{name}: {r.status_code}")
        return r.json()

    def get_node(self, name: str) -> Optional[dict]:
        r = self._get(f"/api/v1/nodes/{name}")
        if r.status_code == 404:
            return None
        if r.status_code != 200:
            raise KubeError(f"get node {name}: {r.status_code}")
        return r.json()

    def list_pods(
        self, node_name: str, page_limit: int = 500
    ) -> Tuple[list, str]:
        """All pods bound to ``node_name`` + the list resourceVersion
        (fieldSelector parity: sitter.go:73-77). Paginated: apiservers
        enforce page caps server-side, and a node-scoped list that
        ignored ``continue`` would silently truncate the sitter's cache
        on a busy node."""
        items: list = []
        cont = ""
        while True:
            params = {
                "fieldSelector": f"spec.nodeName={node_name}",
                "limit": str(page_limit),
            }
            if cont:
                params["continue"] = cont
            r = self._get("/api/v1/pods", params=params)
            if r.status_code != 200:
                raise KubeError(f"list pods: {r.status_code}")
            body = r.json()
            items.extend(body.get("items", []))
            meta = body.get("metadata", {}) or {}
            rv = meta.get("resourceVersion", "")
            cont = meta.get("continue", "")
            if not cont:
                return items, rv

    def list_all_pods(self, page_limit: int = 500) -> list:
        """Every pod in the cluster (no node fieldSelector) — the slice
        registry's membership source: cooperating slice members live on
        OTHER nodes, so the node-scoped sitter cannot see them. Callers
        (slices/registry.py) TTL-cache the result and count it
        (`elastic_tpu_apiserver_pod_list_total`); paginated so one
        agent's membership refresh never asks a 10k-pod apiserver for
        the whole cluster in one response."""
        items: list = []
        cont = ""
        while True:
            params = {"limit": str(page_limit)}
            if cont:
                params["continue"] = cont
            r = self._get("/api/v1/pods", params=params)
            if r.status_code != 200:
                raise KubeError(f"list all pods: {r.status_code}")
            body = r.json()
            items.extend(body.get("items", []))
            cont = (body.get("metadata") or {}).get("continue", "")
            if not cont:
                return items

    def patch_pod_annotations(
        self, namespace: str, name: str, annotations: Dict[str, Optional[str]]
    ) -> Optional[dict]:
        """Merge-patch a pod's metadata.annotations (a None value deletes
        the key, merge-patch semantics); returns None on 404 — a gone pod
        needs no annotation, and callers retrying cleanup must be able to
        tell "done" from "failed". The drain orchestrator stamps
        ``elasticgpu.io/draining`` on its resident slice-member pods this
        way, so cooperating agents re-form the survivor world BEFORE the
        host dies."""
        r = self._patch(
            f"/api/v1/namespaces/{namespace}/pods/{name}",
            {"metadata": {"annotations": annotations}},
        )
        if r.status_code == 404:
            return None
        if r.status_code != 200:
            raise KubeError(
                f"patch pod {namespace}/{name}: {r.status_code}"
            )
        return r.json()

    def create_event(self, namespace: str, event: dict) -> dict:
        """POST a core/v1 Event (reference RBAC granted this and never
        used it; see kube/events.py)."""
        r = self._post(f"/api/v1/namespaces/{namespace}/events", event)
        if r.status_code not in (200, 201):
            raise KubeError(f"create event: {r.status_code}")
        return r.json()

    def watch_pods(
        self, node_name: str, resource_version: str, timeout_s: int = 60
    ) -> Iterator[dict]:
        """Stream watch events ({"type": ..., "object": pod}) until the
        server closes the window. Caller re-lists on error/410."""
        r = self._get(
            "/api/v1/pods",
            params={
                "watch": "true",
                "fieldSelector": f"spec.nodeName={node_name}",
                "resourceVersion": resource_version,
                "timeoutSeconds": str(timeout_s),
            },
            stream=True,
            timeout=timeout_s + 10,
        )
        if r.status_code != 200:
            raise KubeError(f"watch pods: {r.status_code}")
        for line in r.iter_lines():
            if line:
                yield json.loads(line)
