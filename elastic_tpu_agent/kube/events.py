"""k8s Event emission for allocation lifecycle.

The reference's RBAC granted events create/patch and no code ever used it
(SURVEY.md §5.5; reference deploy/elastic-gpu-agent.yaml:15-21 vs zero
recorder code). Here the grant is earned: binds, bind failures, GC
reclaims, and restore sweeps surface as Events on the involved Pod (or
this Node for podless actions), so `kubectl describe pod` answers "why
does my container (not) have its TPU" without node access.

Emission rides the shared AsyncSink: off the bind hot path, never raises,
self-disables when the apiserver persistently refuses us.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from datetime import datetime, timezone
from typing import Dict, Optional, Tuple

from ..async_sink import AsyncSink, drop_hook, register_sink_metrics

logger = logging.getLogger(__name__)

COMPONENT = "elastic-tpu-agent"

# Client-side aggregation: identical events inside this window are folded
# into one object with a bumped count, so a crash-looping pod (kubelet
# retries PreStart on every restart backoff) cannot churn etcd with an
# unbounded TPUBindFailed stream.
AGGREGATION_WINDOW_S = 60.0
_MAX_TRACKED_KEYS = 1024

# apiserver rejects metadata.name > 253 chars; leave room for ".<16hex>".
_MAX_BASE_LEN = 253 - 17

# Reasons (CamelCase by k8s convention)
ReasonBound = "TPUBound"
ReasonBindFailed = "TPUBindFailed"
ReasonReclaimed = "TPUReclaimed"
ReasonRestored = "TPURestored"
ReasonReconciled = "TPUReconciled"
ReasonChipUnhealthy = "TPUChipUnhealthy"
ReasonChipHealthy = "TPUChipHealthy"
ReasonAllocatableDrift = "TPUAllocatableDrift"
ReasonSliceReformed = "TPUSliceReformed"
ReasonSliceInconsistent = "TPUSliceInconsistent"
# Graceful drain lifecycle (drain.py)
ReasonMaintenanceImminent = "TPUMaintenanceImminent"
ReasonNodeDraining = "TPUNodeDraining"
ReasonNodeDrained = "TPUNodeDrained"
ReasonDrainCancelled = "TPUDrainCancelled"

ReasonRepartitioned = "TPURepartitioned"
ReasonThrottled = "TPUThrottled"
ReasonQoSEvicted = "TPUQoSEvicted"
# Migration handshake (migration.py): a resident's checkpoint verified
# durable (ack consumed, record published), and the destination-side
# resume verified at the acked step / current world size.
ReasonMigrationRecorded = "TPUMigrationRecorded"
ReasonMigrationCompleted = "TPUMigrationCompleted"


class EventRecorder:
    """Posts core/v1 Events; all methods non-blocking and never raise."""

    def __init__(
        self, kube_client, node_name: str, metrics=None,
        flush_window_s: float = 0.0,
    ) -> None:
        self._client = kube_client
        self._node = node_name
        self._sink = AsyncSink(
            "event-recorder", on_drop=drop_hook(metrics),
            flush_window_s=flush_window_s,
        )
        register_sink_metrics(self._sink, metrics)
        # key -> (last_emit_monotonic, suppressed_since_then, emit_ctx)
        # where emit_ctx = (namespace, base, involved, reason, message, type_)
        # is kept so suppressed tails can be surfaced after the window.
        self._recent: Dict[Tuple, Tuple[float, int, Tuple]] = {}
        self._recent_lock = threading.Lock()
        self._stopped = threading.Event()
        # Without this sweeper, occurrences folded inside the window would
        # only surface on the NEXT post-window emission for the same key —
        # a storm that stops would lose its tail counts forever.
        self._sweeper = threading.Thread(
            target=self._sweep_loop, daemon=True, name="event-residuals"
        )
        self._sweeper.start()

    @property
    def disabled(self) -> bool:
        return self._sink.disabled

    def flush(self, timeout: float = 10.0) -> bool:
        return self._sink.flush(timeout=timeout)

    def run_supervised(self, stop) -> None:
        """Supervisor target (supervisor.py): watchdog over the sink's
        internal worker thread."""
        self._sink.run_supervised(stop)

    def stop(self, timeout: float = 30.0) -> None:
        # Generous default: the sink drains on stop (async_sink); a short
        # cap would abandon queued events at shutdown.
        self._stopped.set()
        # Join the sweeper BEFORE the force flush: a sweep that already
        # zeroed a suppressed count under the lock but hasn't posted it yet
        # would otherwise race the sink shutdown and drop the tail silently.
        self._sweeper.join(timeout=timeout)
        self.flush_residuals(force=True)
        self._sink.stop(timeout=timeout)

    def _sweep_loop(self) -> None:
        while not self._stopped.wait(AGGREGATION_WINDOW_S):
            try:
                self.flush_residuals()
            except Exception:  # noqa: BLE001 - observability must not wedge
                logger.exception("residual event sweep failed")

    def flush_residuals(self, force: bool = False) -> None:
        """Publish counts folded during aggregation windows that have since
        lapsed (or all pending counts when ``force``), so storm tails are
        surfaced even if the storm stopped before the next emission."""
        now = time.monotonic()
        due = []
        with self._recent_lock:
            for key, (last, suppressed, ctx) in list(self._recent.items()):
                if suppressed <= 0:
                    continue
                if force or now - last >= AGGREGATION_WINDOW_S:
                    due.append((suppressed, ctx))
                    self._recent[key] = (last, 0, ctx)
        for count, ctx in due:
            self._post(*ctx, count=count)

    # -- emitters -------------------------------------------------------------

    @staticmethod
    def _tag_trace(message: str, trace_id: str) -> str:
        """Suffix the allocation trace id so `kubectl describe pod`
        hands the operator the key into /debug/traces (tracing.py).
        Falls back to the caller's current trace when none is given."""
        if not trace_id:
            from ..tracing import get_tracer

            trace_id = get_tracer().current_id()
        return f"{message} [trace {trace_id}]" if trace_id else message

    def pod_event(
        self,
        namespace: str,
        pod: str,
        reason: str,
        message: str,
        type_: str = "Normal",
        uid: str = "",
        trace_id: str = "",
    ) -> None:
        involved = {
            "kind": "Pod",
            "apiVersion": "v1",
            "namespace": namespace,
            "name": pod,
        }
        if uid:
            involved["uid"] = uid
        self._emit(
            namespace, pod, involved, reason, message, type_,
            display=self._tag_trace(message, trace_id),
        )

    def node_event(
        self,
        reason: str,
        message: str,
        type_: str = "Normal",
        trace_id: str = "",
    ) -> None:
        involved = {"kind": "Node", "apiVersion": "v1", "name": self._node}
        self._emit(
            "default", self._node, involved, reason, message, type_,
            display=self._tag_trace(message, trace_id),
        )

    def _should_emit(self, key: Tuple, ctx: Tuple) -> int:
        """0 = suppress (inside the aggregation window); otherwise the
        count to publish (1 + occurrences folded since the last emit)."""
        now = time.monotonic()
        with self._recent_lock:
            if len(self._recent) > _MAX_TRACKED_KEYS:
                cutoff = now - AGGREGATION_WINDOW_S
                self._recent = {
                    k: v for k, v in self._recent.items() if v[0] >= cutoff
                }
                if len(self._recent) > _MAX_TRACKED_KEYS:
                    # Event storm: every key is still inside the window.
                    # Hard-cap by evicting the oldest emitters — an evicted
                    # key re-emits early (one extra Event) and its folded
                    # occurrence count is dropped with it; bounded memory
                    # beats exact counts during a storm.
                    keep = sorted(
                        self._recent.items(), key=lambda kv: -kv[1][0]
                    )[:_MAX_TRACKED_KEYS]
                    self._recent = dict(keep)
            last, suppressed, _ = self._recent.get(key, (0.0, 0, ()))
            if last and now - last < AGGREGATION_WINDOW_S:
                self._recent[key] = (last, suppressed + 1, ctx)
                return 0
            self._recent[key] = (now, 0, ctx)
            return 1 + suppressed

    def _emit(
        self, namespace: str, base: str, involved: dict,
        reason: str, message: str, type_: str,
        display: Optional[str] = None,
    ) -> None:
        # The aggregation key uses the RAW message: the displayed form
        # may carry a per-attempt trace id, and keying on that would
        # defeat the fold (every crash-loop retry would be "new").
        ctx = (namespace, base, involved, reason, display or message, type_)
        count = self._should_emit(
            (namespace, involved.get("kind"), involved.get("name"),
             reason, message),
            ctx,
        )
        if count == 0:
            return
        self._post(*ctx, count=count)

    def _post(
        self, namespace: str, base: str, involved: dict,
        reason: str, message: str, type_: str, count: int,
    ) -> None:
        now = datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
        body = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                # unique per emission, like client-go's name.timestamp form;
                # base truncated so the name stays under the 253-char limit
                "name": f"{base[:_MAX_BASE_LEN]}.{os.urandom(8).hex()}",
                "namespace": namespace,
            },
            "involvedObject": involved,
            "reason": reason,
            "message": message,
            "type": type_,
            "source": {"component": COMPONENT, "host": self._node},
            "firstTimestamp": now,
            "lastTimestamp": now,
            "count": count,
            "reportingComponent": COMPONENT,
            "reportingInstance": self._node,
        }
        self._sink.submit(lambda: self._client.create_event(namespace, body))


def build_event_recorder(
    kube_client, node_name: str, metrics=None, flush_window_s: float = 0.0
) -> Optional[EventRecorder]:
    if kube_client is None or not node_name:
        return None
    return EventRecorder(
        kube_client, node_name, metrics=metrics,
        flush_window_s=flush_window_s,
    )
