"""Device->pod locator: which pod/container owns this fake-device set?

Capability parity with the reference's ``pkg/kube/locator.go`` (SURVEY.md §1
L5): PreStartContainer only receives device IDs, so the agent asks the
kubelet pod-resources API for the full node dump and matches the sorted
ID set. Both response shapes are handled: k8s ≤1.20 returned all IDs of a
resource in one ContainerDevices entry, ≥1.21 one entry per ID
(locator.go:69-89) — we simply merge every entry of the target resource per
container before comparing.

Perf (this is the Allocate/PreStart p50 hot path, BASELINE.md): the
reference issued a full-node List per Locate call, O(pods x containers x
devices) each time. We keep a hash-indexed cache of the last List and only
re-List on a cache miss, so steady-state repeat locates are O(1) and a
single List serves all misses in one PreStart burst.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from abc import ABC, abstractmethod
from typing import Dict, Optional

from ..rpc import PodResourcesClient
from ..tracing import get_tracer
from ..types import Device, PodContainer, device_hash

logger = logging.getLogger(__name__)

# The cache is replaced wholesale on every List, so its size tracks live
# node pods (kubelet caps out at a few hundred). The cap is a backstop
# against a pathological pod-resources response (e.g. a buggy kubelet
# echoing stale pods into the 16MiB List): evicted entries just fall back
# to an inline refresh at locate() time.
_MAX_CACHE_ENTRIES = 4096


class LocateError(Exception):
    pass


class DeviceLocator(ABC):
    @abstractmethod
    def locate(self, device: Device) -> PodContainer:
        """Resolve the owner of this device set; raises LocateError."""


class KubeletDeviceLocator(DeviceLocator):
    """One locator per extended resource (reference: base.go:56-58)."""

    # How long a cache miss will wait for an in-flight refresh (usually
    # the Allocate-time prefetch) before paying its own List. A full-node
    # List is single-digit ms even at 1000 pods, so this bound only bites
    # when the kubelet itself is stalling.
    JOIN_REFRESH_TIMEOUT_S = 0.25

    def __init__(self, resource: str, client: PodResourcesClient) -> None:
        self._resource = resource
        self._client = client
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._cache: Dict[str, PodContainer] = {}  # device-set hash -> owner
        self._refresh_seq = 0       # ordering guard: a slow, stale List
        self._installed_seq = 0     # must never replace a newer snapshot
        self._refreshing = 0        # in-flight List count (join target)
        self._prefetch_wake = threading.Event()
        self._prefetch_thread: Optional[threading.Thread] = None
        self._prefetch_debounce_s = 0.0005

    def _refresh(self) -> Dict[str, PodContainer]:
        """Full List -> rebuild hash index for our resource. Returns the
        fresh snapshot; installs it into the shared cache only if no
        later-started refresh already installed its result (a slow stale
        prefetch must never clobber a newer inline refresh)."""
        with self._lock:
            self._refresh_seq += 1
            seq = self._refresh_seq
            self._refreshing += 1
        try:
            with get_tracer().span(
                "pod_resources_list", resource=self._resource
            ) as sp:
                resp = self._client.list()
                sp.set(pods=len(resp.pod_resources))
            fresh: Dict[str, PodContainer] = {}
            for pod in resp.pod_resources:
                for container in pod.containers:
                    ids = []
                    for dev in container.devices:
                        if dev.resource_name == self._resource:
                            # merges both the ≤1.20 one-entry-many-ids and
                            # the ≥1.21 one-id-per-entry shapes
                            ids.extend(dev.device_ids)
                    if ids:
                        fresh[device_hash(ids)] = PodContainer(
                            pod.namespace, pod.name, container.name
                        )
            install = fresh
            if len(fresh) > _MAX_CACHE_ENTRIES:
                logger.warning(
                    "pod-resources List yielded %d device sets; capping "
                    "cache at %d", len(fresh), _MAX_CACHE_ENTRIES,
                )
                # cap only the shared cache; the caller still consults the
                # full snapshot, so evicted sets resolve on their inline
                # refresh
                install = dict(
                    itertools.islice(fresh.items(), _MAX_CACHE_ENTRIES)
                )
            with self._cond:
                if seq > self._installed_seq:
                    self._installed_seq = seq
                    self._cache = install
            return fresh
        finally:
            # ANY exit — including a parse failure after a successful
            # List — must release the in-flight count, or joiners would
            # pay the full join timeout on every future miss.
            with self._cond:
                self._refreshing -= 1
                self._cond.notify_all()

    def locate(self, device: Device) -> PodContainer:
        with get_tracer().span(
            "locator_locate", resource=self._resource, hash=device.hash
        ) as sp:
            owner = self._locate(device, sp)
            sp.set(pod=owner.pod_key, container=owner.container)
            return owner

    def _locate(self, device: Device, sp) -> PodContainer:
        key = device.hash
        with self._cond:
            hit = self._cache.get(key)
            if hit is None and (
                self._refreshing > 0 or self._prefetch_wake.is_set()
            ):
                # A List is in flight or about to start (the Allocate-time
                # prefetch): join it instead of paying a duplicate full
                # List — the common PreStart-raced-the-prefetch case.
                seen = self._installed_seq
                self._cond.wait_for(
                    lambda: (
                        self._installed_seq > seen
                        or (
                            self._refreshing == 0
                            and not self._prefetch_wake.is_set()
                        )
                    ),
                    timeout=self.JOIN_REFRESH_TIMEOUT_S,
                )
                hit = self._cache.get(key)
        if hit is not None:
            sp.set(cache_hit=True)
            return hit
        sp.set(cache_hit=False)
        # Miss: refresh inline, consulting OUR OWN snapshot (the shared
        # cache may be concurrently replaced by a prefetch). One retry
        # absorbs transient channel resets from concurrent users.
        last_error: Optional[Exception] = None
        for _ in range(2):
            try:
                fresh = self._refresh()
            except Exception as e:  # noqa: BLE001 - client re-dials next call
                last_error = e
                continue
            hit = fresh.get(key)
            if hit is not None:
                return hit
            last_error = None
            break
        if last_error is not None:
            raise LocateError(
                f"pod-resources List failed: {last_error}"
            ) from last_error
        raise LocateError(
            f"no pod owns device set {key} for {self._resource}"
        )

    def invalidate(self) -> None:
        with self._lock:
            self._cache = {}

    def stats(self) -> Dict[str, object]:
        """Cache introspection for the debug/diagnostics surfaces
        (/debug/allocations, node-doctor): is the hash index warm, how
        many device sets it holds, and whether a refresh is in flight."""
        with self._lock:
            return {
                "resource": self._resource,
                "cache_entries": len(self._cache),
                "installed_seq": self._installed_seq,
                "refresh_seq": self._refresh_seq,
                "refreshing": self._refreshing,
                "prefetch_pending": self._prefetch_wake.is_set(),
            }

    def prefetch_async(self) -> None:
        """Refresh the hash index in the background.

        Called at Allocate time: kubelet records the assignment right after
        the Allocate RPC returns and then spends sandbox-setup time before
        PreStartContainer, so the full pod-resources List overlaps work we
        are not on the critical path for — PreStart's locate() then hits
        the warm cache instead of paying the O(node pods) List inline (the
        reference paid it on every PreStart, locator.go:43-93).

        A single persistent worker debounces bursts: the wake flag
        coalesces any number of prefetch requests into one List, and the
        small debounce delay lets kubelet's assignment record land before
        the snapshot is taken. A miss at PreStart still falls back to a
        fresh inline List, so this is purely an overlap optimization.
        """
        with self._lock:
            if self._prefetch_thread is None:
                self._prefetch_thread = threading.Thread(
                    target=self._prefetch_loop,
                    daemon=True,
                    name=f"locator-prefetch-{self._resource}",
                )
                self._prefetch_thread.start()
        self._prefetch_wake.set()

    def _prefetch_loop(self) -> None:
        while True:
            self._prefetch_wake.wait()
            time.sleep(self._prefetch_debounce_s)
            # Clear-then-refresh under the cond: a locate() miss joining a
            # "pending" prefetch keys off wake-or-refreshing; without the
            # lock there is a visible instant where both are false and the
            # join falls through to a duplicate List.
            with self._cond:
                self._prefetch_wake.clear()
                self._refreshing += 1
            try:
                self._refresh()
            except Exception:  # noqa: BLE001 - locate() retries inline
                pass
            finally:
                with self._cond:
                    self._refreshing -= 1
                    self._cond.notify_all()
