"""Device->pod locator: which pod/container owns this fake-device set?

Capability parity with the reference's ``pkg/kube/locator.go`` (SURVEY.md §1
L5): PreStartContainer only receives device IDs, so the agent asks the
kubelet pod-resources API for the full node dump and matches the sorted
ID set. Both response shapes are handled: k8s ≤1.20 returned all IDs of a
resource in one ContainerDevices entry, ≥1.21 one entry per ID
(locator.go:69-89) — we simply merge every entry of the target resource per
container before comparing.

Perf (this is the Allocate/PreStart p50 hot path, BASELINE.md): the
reference issued a full-node List per Locate call, O(pods x containers x
devices) each time. We keep a hash-indexed cache of the last List and only
re-List on a cache miss, so steady-state repeat locates are O(1) and a
single List serves all misses in one PreStart burst.
"""

from __future__ import annotations

import logging
import threading
from abc import ABC, abstractmethod
from typing import Dict, Optional

from ..rpc import PodResourcesClient
from ..types import Device, PodContainer, device_hash

logger = logging.getLogger(__name__)


class LocateError(Exception):
    pass


class DeviceLocator(ABC):
    @abstractmethod
    def locate(self, device: Device) -> PodContainer:
        """Resolve the owner of this device set; raises LocateError."""


class KubeletDeviceLocator(DeviceLocator):
    """One locator per extended resource (reference: base.go:56-58)."""

    def __init__(self, resource: str, client: PodResourcesClient) -> None:
        self._resource = resource
        self._client = client
        self._lock = threading.Lock()
        self._cache: Dict[str, PodContainer] = {}  # device-set hash -> owner

    def _refresh(self) -> None:
        """Full List -> rebuild hash index for our resource."""
        resp = self._client.list()
        fresh: Dict[str, PodContainer] = {}
        for pod in resp.pod_resources:
            for container in pod.containers:
                ids = []
                for dev in container.devices:
                    if dev.resource_name == self._resource:
                        # merges both the ≤1.20 one-entry-many-ids and the
                        # ≥1.21 one-id-per-entry shapes
                        ids.extend(dev.device_ids)
                if ids:
                    fresh[device_hash(ids)] = PodContainer(
                        pod.namespace, pod.name, container.name
                    )
        with self._lock:
            self._cache = fresh

    def locate(self, device: Device) -> PodContainer:
        key = device.hash
        with self._lock:
            hit = self._cache.get(key)
        if hit is not None:
            return hit
        try:
            self._refresh()
        except Exception as e:  # noqa: BLE001 - client re-dials next call
            raise LocateError(f"pod-resources List failed: {e}") from e
        with self._lock:
            hit = self._cache.get(key)
        if hit is None:
            raise LocateError(
                f"no pod owns device set {key} for {self._resource}"
            )
        return hit

    def invalidate(self) -> None:
        with self._lock:
            self._cache = {}
