"""Device->pod locator: which pod/container owns this fake-device set?

Capability parity with the reference's ``pkg/kube/locator.go`` (SURVEY.md §1
L5): PreStartContainer only receives device IDs, so the agent asks the
kubelet pod-resources API for the full node dump and matches the sorted
ID set. Both response shapes are handled: k8s ≤1.20 returned all IDs of a
resource in one ContainerDevices entry, ≥1.21 one entry per ID
(locator.go:69-89) — we simply merge every entry of the target resource per
container before comparing.

Perf (this is the Allocate/PreStart p50 hot path, BASELINE.md): the
reference issued a full-node List per Locate call, O(pods x containers x
devices) each time. Two layers fix that:

- ``PodResourcesSnapshotSource`` — ONE kubelet ``List`` builds a
  hash-indexed snapshot for EVERY extended resource in the response, with
  single-flight refresh (concurrent misses join one in-flight List instead
  of stampeding the kubelet) and a debounced background prefetch. The
  manager shares one source across the core and memory locators, so a
  cold core+memory bind pair costs one List, not two.
- ``KubeletDeviceLocator`` — a thin per-resource view over a source:
  steady-state repeat locates are O(1) dict hits; a miss joins or pays a
  refresh and retries once.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from abc import ABC, abstractmethod
from typing import Dict, Optional

from .. import events as events_mod
from ..rpc import PodResourcesClient
from ..tracing import get_tracer
from ..types import Device, PodContainer, device_hash

logger = logging.getLogger(__name__)

# The snapshot is replaced wholesale on every List, so its size tracks live
# node pods (kubelet caps out at a few hundred). The cap is a backstop
# against a pathological pod-resources response (e.g. a buggy kubelet
# echoing stale pods into the 16MiB List): evicted entries just fall back
# to an inline refresh at locate() time. Applied PER RESOURCE.
_MAX_CACHE_ENTRIES = 4096


class LocateError(Exception):
    pass


class DeviceLocator(ABC):
    @abstractmethod
    def locate(self, device: Device) -> PodContainer:
        """Resolve the owner of this device set; raises LocateError."""


class PodResourcesSnapshotSource:
    """Shared, single-flight pod-resources snapshot layer.

    One kubelet ``List`` yields ``{resource: {device-set hash: owner}}``
    for every resource in the response; any number of per-resource
    locators consume it. Refreshes are single-flight: a caller that
    misses while a List is in flight (usually the Allocate-time prefetch,
    or a sibling resource's cold locate) joins it instead of paying a
    duplicate full-node dump.
    """

    # How long a cache miss will wait for an in-flight refresh (usually
    # the Allocate-time prefetch) before paying its own List. A full-node
    # List is single-digit ms even at 1000 pods, so this bound only bites
    # when the kubelet itself is stalling.
    JOIN_REFRESH_TIMEOUT_S = 0.25
    # How long refresh() queues behind another caller's in-flight List
    # before abandoning single-flight and issuing its own concurrently.
    # Just over the client's per-List deadline: a healthy kubelet never
    # trips it, while a STALLED one degrades to the concurrent-failure
    # shape (every miss errors out in ~one List deadline) instead of
    # serializing misses one stalled List at a time.
    STALL_WAIT_TIMEOUT_S = 6.0

    def __init__(self, client: PodResourcesClient, metrics=None,
                 bus=None) -> None:
        self._client = client
        # Optional AgentMetrics: every List issued is counted in
        # elastic_tpu_kubelet_list_total so per-bind kubelet request
        # amplification is measured at the source (fleet aggregator),
        # not inferred from locator stats after the fact.
        self._metrics = metrics
        # Optional events.EventBus: every installed List is diffed
        # against the previous one and the per-hash deltas published on
        # ASSIGNMENT_DELTA, so subscribed loops (reconciler, sampler
        # join) react to kubelet-side assignment changes instead of
        # rediscovering them on their next sweep.
        self._bus = bus
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # resource -> device-set hash -> owner
        self._snapshot: Dict[str, Dict[str, PodContainer]] = {}
        self._refresh_seq = 0       # ordering guard: a slow, stale List
        self._installed_seq = 0     # must never replace a newer snapshot
        self._done_seq = 0          # highest seq whose List has completed
        # In-flight List count. Single-flight keeps it at <=1 on a
        # healthy kubelet; the stall-timeout escape lets it exceed 1 so
        # a wedged List cannot serialize every miss behind it.
        self._refresh_active = 0
        self._refreshing = 0        # in-flight List count (join target)
        self._last_full: Dict[str, Dict[str, PodContainer]] = {}
        # resource -> hash -> (owner, device-id tuple): the same List,
        # with the raw ids retained. The reconciler needs them — a bind
        # replay must reconstruct the exact Device from kubelet's
        # assignment, not just learn who owns a hash.
        self._last_assign: Dict[str, Dict[str, tuple]] = {}
        self._prefetch_wake = threading.Event()
        self._prefetch_thread: Optional[threading.Thread] = None
        self._prefetch_debounce_s = 0.0005
        self.lists_total = 0        # kubelet Lists actually issued

    def join_or_lookup(
        self, resource: str, key: str
    ) -> Optional[PodContainer]:
        """Fast-path lookup that, on a miss with a List in flight (or a
        prefetch about to start), waits for that List to land and looks
        again — the common PreStart-raced-the-prefetch case."""
        with self._cond:
            hit = self._snapshot.get(resource, {}).get(key)
            if hit is None and (
                self._refreshing > 0 or self._prefetch_wake.is_set()
            ):
                seen = self._installed_seq
                self._cond.wait_for(
                    lambda: (
                        self._installed_seq > seen
                        or (
                            self._refreshing == 0
                            and not self._prefetch_wake.is_set()
                        )
                    ),
                    timeout=self.JOIN_REFRESH_TIMEOUT_S,
                )
                hit = self._snapshot.get(resource, {}).get(key)
            return hit

    @staticmethod
    def _build_index(resp) -> tuple:
        """One pass over the List: (hash->owner index, hash->(owner, ids)
        assignment map), both keyed per resource. The owner index is
        DERIVED from the assignment map so the two views can never
        drift."""
        assign: Dict[str, Dict[str, tuple]] = {}
        for pod in resp.pod_resources:
            for container in pod.containers:
                ids_by_resource: Dict[str, list] = {}
                for dev in container.devices:
                    # merges both the ≤1.20 one-entry-many-ids and
                    # the ≥1.21 one-id-per-entry shapes
                    ids_by_resource.setdefault(
                        dev.resource_name, []
                    ).extend(dev.device_ids)
                for resource, ids in ids_by_resource.items():
                    if ids:
                        assign.setdefault(resource, {})[
                            device_hash(ids)
                        ] = (
                            PodContainer(
                                pod.namespace, pod.name, container.name
                            ),
                            tuple(sorted(ids)),
                        )
        fresh = {
            resource: {h: owner_ids[0] for h, owner_ids in entries.items()}
            for resource, entries in assign.items()
        }
        return fresh, assign

    @staticmethod
    def _capped(
        fresh: Dict[str, Dict[str, PodContainer]]
    ) -> Dict[str, Dict[str, PodContainer]]:
        capped = {
            res: len(index) for res, index in fresh.items()
            if len(index) > _MAX_CACHE_ENTRIES
        }
        if not capped:
            return fresh
        logger.warning(
            "pod-resources List yielded %s device sets; capping "
            "each resource's cache at %d", capped, _MAX_CACHE_ENTRIES,
        )
        # cap only the shared snapshot; refresh() callers still consult
        # the full return value, so evicted sets resolve on their inline
        # refresh
        return {
            res: (
                dict(itertools.islice(index.items(), _MAX_CACHE_ENTRIES))
                if res in capped else index
            )
            for res, index in fresh.items()
        }

    def refresh(
        self, fresh_start: bool = True
    ) -> Dict[str, Dict[str, PodContainer]]:
        """Full List -> rebuild the hash index for every resource;
        returns the fresh (uncapped) snapshot.

        SINGLE-FLIGHT: at most one List is in flight per source, ever.
        With ``fresh_start=True`` (a locate miss) the caller is
        guaranteed a snapshot from a List that STARTED after this call —
        so an assignment kubelet recorded before the miss is visible —
        but concurrent missers coalesce onto ONE such List instead of
        stampeding the kubelet (a restore storm used to issue one List
        per in-flight PreStart). ``fresh_start=False`` (the prefetch) is
        best-effort: any List completing after the call suffices, so a
        prefetch that finds a refresh already in flight just rides it.

        Installs into the shared snapshot only if no later-started
        refresh already installed its result (a slow stale List must
        never clobber a newer one)."""
        with self._cond:
            # The requirement is fixed at entry: fresh_start needs any
            # run with seq > the one in flight (or last started) NOW —
            # i.e. a run that starts after this call; best-effort needs
            # any run COMPLETING after this call.
            need = (
                self._refresh_seq + 1 if fresh_start
                else self._done_seq + 1
            )
            deadline = time.monotonic() + self.STALL_WAIT_TIMEOUT_S
            while self._done_seq < need and self._refresh_active > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # The in-flight List is stalling (kubelet wedged):
                    # stop queueing behind it and pay our own List in
                    # parallel, so misses fail/succeed in ~one List
                    # deadline instead of one stalled List EACH.
                    break
                self._cond.wait(timeout=remaining)
            if self._done_seq >= need:
                return self._last_full
            self._refresh_active += 1
            self._refreshing += 1
            self._refresh_seq += 1
            seq = self._refresh_seq
        try:
            with get_tracer().span("pod_resources_list") as sp:
                resp = self._client.list()
                self.lists_total += 1
                m = self._metrics
                if m is not None and hasattr(m, "kubelet_lists"):
                    try:
                        m.kubelet_lists.inc()
                    except Exception:  # noqa: BLE001 - never fail a List
                        pass
                sp.set(pods=len(resp.pod_resources))
            fresh, assign = self._build_index(resp)
            install = self._capped(fresh)
            deltas = None
            with self._cond:
                if seq > self._installed_seq:
                    if self._bus is not None and self._installed_seq > 0:
                        deltas = self._assignment_deltas(
                            self._last_assign, assign
                        )
                    self._installed_seq = seq
                    self._snapshot = install
                    self._last_full = fresh
                    self._last_assign = assign
                self._done_seq = max(self._done_seq, seq)
            if deltas:
                # Published OUTSIDE the cond: publish fans out to
                # subscriber queues (their own locks) and must never
                # extend the snapshot critical section.
                for kind, resource, hsh, owner in deltas:
                    self._bus.publish(
                        events_mod.ASSIGNMENT_DELTA, kind=kind, key=hsh,
                        payload={"resource": resource, "owner": owner},
                    )
            return fresh
        finally:
            # ANY exit — including a parse failure after a successful
            # List — must release the single-flight slot, or every
            # future miss would queue behind a corpse.
            with self._cond:
                self._refresh_active -= 1
                self._refreshing -= 1
                self._cond.notify_all()

    @staticmethod
    def _assignment_deltas(old: Dict[str, Dict[str, tuple]],
                           new: Dict[str, Dict[str, tuple]]) -> list:
        """Per-hash diff between two kubelet assignment snapshots:
        ``(kind, resource, hash, "ns/pod/container")`` tuples with kind
        in added/removed/owner-changed. O(assignments); bounded by node
        pod count."""
        deltas = []
        for resource in set(old) | set(new):
            before = old.get(resource, {})
            after = new.get(resource, {})
            for hsh in set(before) | set(after):
                b, a = before.get(hsh), after.get(hsh)
                if b is None and a is not None:
                    kind, owner = "added", a[0]
                elif b is not None and a is None:
                    kind, owner = "removed", b[0]
                elif b is not None and a is not None and b[0] != a[0]:
                    kind, owner = "owner-changed", a[0]
                else:
                    continue
                deltas.append((
                    kind, resource, hsh,
                    f"{owner.pod_key}/{owner.container}",
                ))
        return deltas

    def invalidate(self) -> None:
        with self._lock:
            self._snapshot = {}

    def stats(self) -> Dict[str, object]:
        """Snapshot introspection for the debug/diagnostics surfaces."""
        with self._lock:
            return {
                "resources": {
                    res: len(index)
                    for res, index in self._snapshot.items()
                },
                "installed_seq": self._installed_seq,
                "refresh_seq": self._refresh_seq,
                "refreshing": self._refreshing,
                "prefetch_pending": self._prefetch_wake.is_set(),
                "lists_total": self.lists_total,
            }

    def resource_entries(self, resource: str) -> Dict[str, PodContainer]:
        with self._lock:
            return self._snapshot.get(resource, {})

    def assignments(
        self, fresh_start: bool = True
    ) -> Dict[str, Dict[str, tuple]]:
        """Fresh kubelet view with device ids retained:
        ``{resource: {hash: (owner, ids)}}`` — the reconciler's side of
        the store<->kubelet diff. ``fresh_start`` has refresh()'s
        semantics (True = a List that started after this call)."""
        self.refresh(fresh_start=fresh_start)
        with self._lock:
            return {
                res: dict(entries)
                for res, entries in self._last_assign.items()
            }

    def prefetch_async(self) -> None:
        """Refresh the snapshot in the background.

        Called at Allocate time: kubelet records the assignment right after
        the Allocate RPC returns and then spends sandbox-setup time before
        PreStartContainer, so the full pod-resources List overlaps work we
        are not on the critical path for — PreStart's locate() then hits
        the warm snapshot instead of paying the O(node pods) List inline
        (the reference paid it on every PreStart, locator.go:43-93). With
        the source shared across resources, the core plugin's prefetch
        warms the memory plugin's PreStart too (and vice versa).

        A single persistent worker debounces bursts: the wake flag
        coalesces any number of prefetch requests into one List, and the
        small debounce delay lets kubelet's assignment record land before
        the snapshot is taken. A miss at PreStart still falls back to a
        fresh inline List, so this is purely an overlap optimization.
        """
        with self._lock:
            if self._prefetch_thread is None:
                self._prefetch_thread = threading.Thread(
                    target=self._prefetch_loop,
                    daemon=True,
                    name="pod-resources-prefetch",
                )
                self._prefetch_thread.start()
        self._prefetch_wake.set()

    def _prefetch_loop(self) -> None:
        while True:
            self._prefetch_wake.wait()
            time.sleep(self._prefetch_debounce_s)
            # Clear-then-refresh under the cond: a locate() miss joining a
            # "pending" prefetch keys off wake-or-refreshing; without the
            # lock there is a visible instant where both are false and the
            # join falls through to a duplicate List.
            with self._cond:
                self._prefetch_wake.clear()
                self._refreshing += 1
            try:
                # Best-effort freshness: a refresh already in flight (a
                # concurrent miss, or the sibling resource's prefetch) is
                # ridden, not duplicated — under a bind storm the
                # prefetch stream collapses into the misses' Lists.
                self.refresh(fresh_start=False)
            except Exception:  # noqa: BLE001 - locate() retries inline
                pass
            finally:
                with self._cond:
                    self._refreshing -= 1
                    self._cond.notify_all()


class KubeletDeviceLocator(DeviceLocator):
    """Per-resource locate() view over a PodResourcesSnapshotSource.

    One locator per extended resource (reference: base.go:56-58). Pass
    ``source`` to share one snapshot layer across resources (the manager
    does — that is what halves cold-locate Lists); constructing with a
    bare ``client`` keeps the old one-source-per-locator shape for tests
    and tools.
    """

    def __init__(
        self,
        resource: str,
        client: Optional[PodResourcesClient] = None,
        source: Optional[PodResourcesSnapshotSource] = None,
    ) -> None:
        if source is None:
            if client is None:
                raise ValueError("need a client or a shared source")
            source = PodResourcesSnapshotSource(client)
        self._resource = resource
        self._source = source

    @property
    def source(self) -> PodResourcesSnapshotSource:
        return self._source

    @property
    def _cache(self) -> Dict[str, PodContainer]:
        """This resource's live hash index (introspection/tests)."""
        return self._source.resource_entries(self._resource)

    def locate(self, device: Device) -> PodContainer:
        with get_tracer().span(
            "locator_locate", resource=self._resource, hash=device.hash
        ) as sp:
            owner = self._locate(device, sp)
            sp.set(pod=owner.pod_key, container=owner.container)
            return owner

    def _locate(self, device: Device, sp) -> PodContainer:
        key = device.hash
        hit = self._source.join_or_lookup(self._resource, key)
        if hit is not None:
            sp.set(cache_hit=True)
            return hit
        sp.set(cache_hit=False)
        # Miss: refresh inline, consulting OUR OWN snapshot (the shared
        # one may be concurrently replaced by a prefetch). One retry
        # absorbs transient channel resets from concurrent users.
        last_error: Optional[Exception] = None
        for _ in range(2):
            try:
                fresh = self._source.refresh()
            except Exception as e:  # noqa: BLE001 - client re-dials next call
                last_error = e
                continue
            hit = fresh.get(self._resource, {}).get(key)
            if hit is not None:
                return hit
            last_error = None
            break
        if last_error is not None:
            raise LocateError(
                f"pod-resources List failed: {last_error}"
            ) from last_error
        raise LocateError(
            f"no pod owns device set {key} for {self._resource}"
        )

    def invalidate(self) -> None:
        self._source.invalidate()

    def stats(self) -> Dict[str, object]:
        """Cache introspection for the debug/diagnostics surfaces
        (/debug/allocations, node-doctor): is the hash index warm, how
        many device sets it holds, and whether a refresh is in flight."""
        src = self._source.stats()
        return {
            "resource": self._resource,
            "cache_entries": src["resources"].get(self._resource, 0),
            "installed_seq": src["installed_seq"],
            "refresh_seq": src["refresh_seq"],
            "refreshing": src["refreshing"],
            "prefetch_pending": src["prefetch_pending"],
            "lists_total": src["lists_total"],
            "shared_source": True,
        }

    def prefetch_async(self) -> None:
        self._source.prefetch_async()
