"""QoS env computation: HBM quota + priority co-location (BASELINE config 4).

There is no CUDA-style driver interception on TPU (SURVEY.md §7 "hard
parts"): chip-level partition comes from device visibility; *sub-chip*
core% and HBM quota are cooperative, enforced through env consumed by
libtpu/XLA/JAX inside the container. The honest boundary:

- ``ELASTIC_TPU_HBM_LIMIT_BYTES`` / ``ELASTIC_TPU_HBM_FRACTION`` — hard
  quota for the workload runtime; our workloads package maps it onto
  JAX/XLA client memory limits; any JAX image can apply it via
  /run/elastic-tpu/env.
- ``ELASTIC_TPU_CORE_UNITS`` — core share in 1% units (duty-cycle hint;
  TensorCore time-slicing is not enforceable from outside libtpu).
- ``ELASTIC_TPU_PRIORITY`` — high|low, from the scheduler's annotation or
  the pod priorityClassName; low-priority workloads should enable
  preemptible/donation behavior.
"""

from __future__ import annotations

from typing import Dict, Optional

AnnotationQoSPriority = "elasticgpu.io/qos-priority"


def qos_env(
    annotations: Dict[str, str],
    pod_spec: Optional[dict] = None,
    hbm_limit_bytes: Optional[int] = None,
    chip_hbm_bytes: Optional[int] = None,
    core_units: Optional[int] = None,
) -> Dict[str, str]:
    env: Dict[str, str] = {}
    if hbm_limit_bytes:
        env["ELASTIC_TPU_HBM_LIMIT_BYTES"] = str(hbm_limit_bytes)
        if chip_hbm_bytes:
            frac = min(1.0, hbm_limit_bytes / chip_hbm_bytes)
            env["ELASTIC_TPU_HBM_FRACTION"] = f"{frac:.4f}"
    if core_units is not None:
        env["ELASTIC_TPU_CORE_UNITS"] = str(core_units)
    priority = annotations.get(AnnotationQoSPriority, "")
    if not priority and pod_spec:
        pc = (pod_spec.get("spec") or {}).get("priorityClassName", "")
        if pc:
            priority = "high" if "high" in pc.lower() else "low"
    if priority in ("high", "low"):
        env["ELASTIC_TPU_PRIORITY"] = priority
    return env
