"""QoS env computation: HBM quota + priority co-location (BASELINE config 4).

There is no CUDA-style driver interception on TPU (SURVEY.md §7 "hard
parts"): chip-level partition comes from device visibility; *sub-chip*
core% and HBM quota are cooperative, enforced through env consumed by
libtpu/XLA/JAX inside the container. The honest boundary:

- ``ELASTIC_TPU_HBM_LIMIT_BYTES`` / ``ELASTIC_TPU_HBM_FRACTION`` — hard
  quota for the workload runtime; our workloads package maps it onto
  JAX/XLA client memory limits; any JAX image can apply it via
  /run/elastic-tpu/env.
- ``ELASTIC_TPU_CORE_UNITS`` — core share in 1% units (duty-cycle hint;
  TensorCore time-slicing is not enforceable from outside libtpu).
- ``ELASTIC_TPU_PRIORITY`` — high|low, from the scheduler's annotation or
  the pod priorityClassName; low-priority workloads should enable
  preemptible/donation behavior.

Every annotation-sourced value is VALIDATED here, not trusted: quota env
feeds straight into runtime memory limits inside the container, so a
malformed annotation (non-numeric core units, an HBM quota larger than
the chip, a request above the pod's actual grant) must degrade to the
derived grant — never pass through and never fail the bind. Annotation
overrides can only shrink a quota below the grant (a self-imposed cap,
e.g. for a bursty sidecar), never raise it: raising is the repartition
controller's job (repartition.py), which moves real slack between
co-located pods instead of minting units from an annotation.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

from .common import AnnotationRepartition

logger = logging.getLogger(__name__)

AnnotationQoSPriority = "elasticgpu.io/qos-priority"
# Self-imposed quota caps (validated, clamp-only-downward): a pod may ask
# to be held below its grant, never above it.
AnnotationQoSCoreUnits = "elasticgpu.io/qos-core-units"
AnnotationQoSHBMLimit = "elasticgpu.io/qos-hbm-limit-bytes"

# The env keys this module owns (shared with repartition.py's restamps so
# the two writers can never disagree on spelling).
EnvQoSCoreUnits = "ELASTIC_TPU_CORE_UNITS"
EnvQoSHBMLimit = "ELASTIC_TPU_HBM_LIMIT_BYTES"
EnvQoSHBMFraction = "ELASTIC_TPU_HBM_FRACTION"
EnvQoSPriority = "ELASTIC_TPU_PRIORITY"

_TRUTHY = ("true", "1", "yes", "enabled")


def _annotation_int(
    annotations: Dict[str, str], key: str
) -> Optional[int]:
    """A positive int annotation value, or None when absent/malformed
    (malformed values are logged and IGNORED — a typo in a quota
    annotation must not fail the bind or pass through unvalidated)."""
    raw = annotations.get(key)
    if raw is None:
        return None
    try:
        value = int(str(raw).strip())
    except (TypeError, ValueError):
        logger.warning(
            "qos: ignoring malformed annotation %s=%r (not an integer)",
            key, raw,
        )
        return None
    if value <= 0:
        logger.warning(
            "qos: ignoring annotation %s=%r (must be a positive integer)",
            key, raw,
        )
        return None
    return value


def _derive_priority(
    annotations: Dict[str, str], pod_spec: Optional[dict] = None
) -> Optional[str]:
    """high|low from the annotation (validated), else from
    priorityClassName, else None (indeterminate). The ONE place the
    mapping lives: qos_env's stamped env and the repartition
    controller's donation precedence read the same derivation."""
    priority = str(annotations.get(AnnotationQoSPriority, "")).strip().lower()
    if priority in ("high", "low"):
        return priority
    if priority:
        logger.warning(
            "qos: ignoring malformed annotation %s=%r (want high|low)",
            AnnotationQoSPriority, annotations.get(AnnotationQoSPriority),
        )
    if pod_spec:
        pc = (pod_spec.get("spec") or {}).get("priorityClassName", "")
        if pc:
            return "high" if "high" in pc.lower() else "low"
    return None


def pod_priority(
    annotations: Dict[str, str], pod_spec: Optional[dict] = None
) -> str:
    """The pod's QoS priority, defaulting indeterminate to "low" (the
    safe default for donation precedence — an unclassified pod never
    outranks anyone)."""
    return _derive_priority(annotations, pod_spec) or "low"


def repartition_opt_in(annotations: Dict[str, str]) -> bool:
    """Whether the pod opted into live re-partitioning
    (``elasticgpu.io/repartition``); unknown values read as opted-OUT
    (quota renegotiation must never surprise a pod that didn't ask)."""
    return (
        str(annotations.get(AnnotationRepartition, "")).strip().lower()
        in _TRUTHY
    )


def qos_env(
    annotations: Dict[str, str],
    pod_spec: Optional[dict] = None,
    hbm_limit_bytes: Optional[int] = None,
    chip_hbm_bytes: Optional[int] = None,
    core_units: Optional[int] = None,
) -> Dict[str, str]:
    env: Dict[str, str] = {}
    # -- derived-quota validation (the grant itself) ----------------------
    try:
        hbm_limit_bytes = (
            int(hbm_limit_bytes) if hbm_limit_bytes is not None else None
        )
    except (TypeError, ValueError):
        logger.warning(
            "qos: dropping non-numeric hbm_limit_bytes %r", hbm_limit_bytes
        )
        hbm_limit_bytes = None
    if hbm_limit_bytes is not None and hbm_limit_bytes <= 0:
        hbm_limit_bytes = None
    if (
        hbm_limit_bytes
        and chip_hbm_bytes
        and hbm_limit_bytes > chip_hbm_bytes
    ):
        # A grant above the chip's HBM is a scheduler accounting bug; the
        # runtime limit must still be physically satisfiable.
        logger.warning(
            "qos: HBM quota %d exceeds chip HBM %d; clamping",
            hbm_limit_bytes, chip_hbm_bytes,
        )
        hbm_limit_bytes = chip_hbm_bytes
    try:
        core_units = int(core_units) if core_units is not None else None
    except (TypeError, ValueError):
        logger.warning("qos: dropping non-numeric core_units %r", core_units)
        core_units = None
    if core_units is not None and core_units < 0:
        logger.warning("qos: dropping negative core_units %d", core_units)
        core_units = None
    # -- annotation overrides: clamp-only-downward ------------------------
    ann_hbm = _annotation_int(annotations, AnnotationQoSHBMLimit)
    if ann_hbm is not None:
        if hbm_limit_bytes:
            hbm_limit_bytes = min(hbm_limit_bytes, ann_hbm)
        # No derived grant (core-only pod): the annotation alone never
        # mints an HBM quota — there is nothing to cap.
    ann_units = _annotation_int(annotations, AnnotationQoSCoreUnits)
    if ann_units is not None and core_units is not None:
        if ann_units > core_units:
            logger.warning(
                "qos: annotation %s=%d exceeds the granted %d core "
                "units; using the grant",
                AnnotationQoSCoreUnits, ann_units, core_units,
            )
        else:
            core_units = ann_units
    # -- emit -------------------------------------------------------------
    if hbm_limit_bytes:
        env[EnvQoSHBMLimit] = str(hbm_limit_bytes)
        if chip_hbm_bytes:
            frac = min(1.0, hbm_limit_bytes / chip_hbm_bytes)
            env[EnvQoSHBMFraction] = f"{frac:.4f}"
    if core_units is not None:
        env[EnvQoSCoreUnits] = str(core_units)
    priority = _derive_priority(annotations, pod_spec)
    if priority:
        env[EnvQoSPriority] = priority
    return env
