from .base import DevicePluginServer, PluginConfig, plugin_factory
from .tpushare import TPUSharePlugin


def restamp_owner_env(
    spec_plugin, owner, records, env_updates, remove_keys=(),
):
    """Restamp env keys into every on-disk alloc spec of ONE container,
    under the owner's bind stripe.

    The single post-bind env-mutation path: the drain orchestrator's
    checkpoint signal, its cancel cleanup, and the repartition
    controller's quota updates all go through here, so the three writers
    can never drift in locking (the same stripe the bind path takes) or
    merge semantics (restamp_spec_env_locked updates the merged env AND
    the pre-merge ``own`` snapshot of every sibling spec). Returns the
    number of spec files carrying the requested env afterwards.

    Callers must NOT already hold the owner's stripe (it is not
    reentrant); use ``spec_plugin.restamp_spec_env_locked`` directly
    from code that does.
    """
    from . import tpushare

    with tpushare.bind_lock(owner.pod_key):
        return spec_plugin.restamp_spec_env_locked(
            owner, records, env_updates, remove_keys=remove_keys
        )


__all__ = [
    "DevicePluginServer",
    "PluginConfig",
    "plugin_factory",
    "restamp_owner_env",
    "TPUSharePlugin",
]
