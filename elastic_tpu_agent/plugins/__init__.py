from .base import DevicePluginServer, PluginConfig, plugin_factory
from .tpushare import TPUSharePlugin

__all__ = [
    "DevicePluginServer",
    "PluginConfig",
    "plugin_factory",
    "TPUSharePlugin",
]
