"""tpushare: fractional TPU core + HBM device plugins.

Capability parity with the reference's ``pkg/plugins/gpushare.go``
(SURVEY.md §1 L3, §3.2), TPU-native:

- ``elasticgpu.io/tpu-core``: 100 fake devices per chip (1% granularity,
  reference const.go:4).
- ``elasticgpu.io/tpu-memory``: 1 fake device per MiB of HBM
  (reference gpushare.go:161).
- Allocate answers with hash-named virtual device nodes and env; the
  external elastic scheduler has already annotated the pod with the chosen
  physical chips; PreStartContainer resolves the requesting pod via the
  pod-resources locator, reads the annotations, materializes the virtual
  nodes, persists the binding, and writes the allocation spec consumed by
  the OCI hook.

TPU-native device injection (replaces the patched nvidia-container-toolkit
ELF, SURVEY.md §2 #16): the *core* plugin's Allocate response maps each
virtual node ``/dev/elastic-tpu-<hash>-<p>`` to container path
``/dev/accel<p>``. At container-create time the runtime stat-follows the
symlink (created during PreStartContainer) to the real chardev, so the
container sees a dense, renumbered chip namespace — no toolkit binary in
the happy path. The memory plugin carries env only (its PreStart still
creates its own hash links so the hook can resolve memory-only pods, and
the hook handles libtpu.so + env-file injection; see native/).

Defects of the reference deliberately not replicated (SURVEY.md §7):
symlink-count mismatch between Allocate/GC (150-core case) — we persist
exactly the created node ids; core+mem records overwriting each other —
records are keyed per resource.
"""

from __future__ import annotations

import json
import logging
import math
import os
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import faults, rpc
from ..common import (
    AnnotationAssumed,
    AnnotationSliceID,
    AnnotationTraceID,
    BytesPerMemoryUnit,
    EnvSliceEpoch,
    EnvSliceName,
    EnvAllocationHash,
    EnvTPUVisibleChips,
    EnvTPUVisibleDevices,
    ResourceTPUCore,
    ResourceTPUMemory,
    StripedLockSet,
    TPUPercentEachChip,
    container_annotation,
)
from ..gen import deviceplugin_pb2 as dp
from ..kube.events import (
    ReasonBindFailed,
    ReasonBound,
    ReasonChipHealthy,
    ReasonChipUnhealthy,
    ReasonReclaimed,
)
from ..kube.locator import DeviceLocator, LocateError
from ..qos import qos_env
from ..slice_env import slice_env_for_pod
from ..slices import packing
from .. import timeline as tl
from ..tracing import get_tracer
from ..types import AllocationRecord, Device, PodContainer, PodInfo
from .base import DevicePluginServer, PluginConfig

logger = logging.getLogger(__name__)

CORE_ENDPOINT = "elastic-tpushare-core.sock"
MEM_ENDPOINT = "elastic-tpushare-mem.sock"

# Where allocation specs for the OCI hook live, as seen by the agent
# (host path /var/lib/elastic-tpu/alloc, hostPath-mounted).
DEFAULT_ALLOC_SPEC_DIR = "/host/var/lib/elastic-tpu/alloc"

GC_PERIOD_S = 60.0  # reference: base.go:248

# Per-owner (namespace/name) striped locks serializing alloc-spec writes
# across the core and memory plugin servers (both live in the one agent
# process): concurrent PreStarts for the SAME container can't interleave
# their sibling merges — they share a pod key, hence a stripe — while
# unrelated pods bind in parallel. The predecessor was one process-global
# lock, which serialized the whole node's bind traffic through a single
# critical section; kubelet drives these handlers from a thread pool and
# a node restart re-binds every pod at once, so the global lock was the
# pipeline's scaling limit (BENCH churn phase measures the difference).
# 256 stripes keeps the collision odds low for a full device-plugin
# handler pool's worth of concurrent binds while costing ~10KB of locks.
DEFAULT_BIND_LOCK_STRIPES = 256
_BIND_LOCKS = StripedLockSet(DEFAULT_BIND_LOCK_STRIPES)


def set_bind_lock_stripes(stripes: int) -> StripedLockSet:
    """Reconfigure the bind-lock striping (bench/test seam; ``1`` restores
    the historical global-lock behavior as a same-run baseline). Only safe
    with no binds in flight."""
    global _BIND_LOCKS
    _BIND_LOCKS = StripedLockSet(stripes)
    return _BIND_LOCKS


def bind_lock_stats() -> Dict:
    return _BIND_LOCKS.stats()


def bind_lock(pod_key: str):
    """Context manager holding the owner's bind stripe — the reconciler
    serializes intent rollback / drift repair against live binds with
    exactly the lock the bind path itself uses. NOT reentrant: never
    call back into plugin methods that take the stripe themselves
    (``remove_alloc_spec``) while holding it — use the ``_locked``
    variants."""
    return _BIND_LOCKS.acquire(pod_key)


def _safe_int(value, default: int = 0) -> int:
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


def _write_json_atomic(path: str, payload: Dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def _merge_spec_payloads(a: Dict, b: Dict) -> Tuple[Dict, Dict]:
    """Union two alloc-spec payloads for the same container; returns both
    with identical devices/env (each keeps its own hash + resource).

    Env conflicts resolve deterministically: the tpu-core payload's values
    win (resource-specific keys — core units vs HBM quota — never collide;
    shared keys like TPU_VISIBLE_* agree anyway since both plugins read the
    same scheduler annotation)."""
    chip_to_path: Dict[int, str] = {}
    for p in (a, b):
        for c, d in zip(p.get("chip_indexes", []), p.get("device_paths", [])):
            chip_to_path[c] = d
    chips = sorted(chip_to_path)
    env: Dict[str, str] = {}
    # core last -> core wins ties
    first, second = (
        (b, a) if a.get("resource") == ResourceTPUCore else (a, b)
    )
    env.update(first.get("env", {}))
    env.update(second.get("env", {}))
    resources = sorted(
        {a.get("resource", ""), b.get("resource", "")}
        | set(a.get("resources", []))
        | set(b.get("resources", []))
    )
    out = []
    for p in (a, b):
        m = dict(p)
        m["chip_indexes"] = chips
        m["device_paths"] = [chip_to_path[c] for c in chips]
        m["env"] = env
        m["resources"] = resources
        out.append(m)
    return out[0], out[1]


def core_device_id(chip: int, unit: int) -> str:
    return f"tpu-core-{chip}-{unit}"


def mem_device_id(chip: int, unit: int) -> str:
    return f"tpu-mem-{chip}-{unit}"


def chip_of_device_id(device_id: str) -> Optional[int]:
    parts = device_id.split("-")
    try:
        return int(parts[2])
    except (IndexError, ValueError):
        return None


# The packing policy (minimal chip count, minimal ICI span, deterministic
# tie-break) moved to the slice-orchestration layer — placement is a slice
# concern shared with the registry/recovery machinery. These aliases keep
# the historical seam for tests and external callers.
_pick_chip_set = packing.pick_chip_set
_greedy_chip_set = packing.greedy_chip_set
_EXACT_PACK_MAX_CHIPS = packing.EXACT_PACK_MAX_CHIPS


def _parse_chip_annotation(value: str) -> List[int]:
    """"0" or "0,1" -> [0, 1] (reference consumed the same shape,
    gpushare.go:103-112)."""
    out = []
    for part in value.split(","):
        part = part.strip()
        if part:
            out.append(int(part))
    return out


class _ListAndWatchMixin:
    """Shared ListAndWatch machinery: initial send + resend on notify."""

    def __init__(self) -> None:
        self._law_cond = threading.Condition()
        self._law_version = 0
        self._stopped = False
        # Coalesced broadcasts: rapid health flips (flapping chip, burst
        # of notify calls) bump the version many times but often settle
        # on an identical device list — each stream dedups on a
        # (device-id, health) signature and skips the redundant yield,
        # so kubelet never reprocesses an update that changes nothing.
        self._law_dedup_total = 0

    def notify_devices_changed(self) -> None:
        with self._law_cond:
            self._law_version += 1
            self._law_cond.notify_all()

    def stop_streams(self) -> None:
        with self._law_cond:
            self._stopped = True
            self._law_cond.notify_all()

    def _device_list(self) -> List[dp.Device]:
        raise NotImplementedError

    def ListAndWatch(self, request, context):  # noqa: N802, ARG002
        version = -1
        sent_sig = None
        while True:
            with self._law_cond:
                while self._law_version == version and not self._stopped:
                    self._law_cond.wait(timeout=5.0)
                    if not context.is_active():
                        return
                if self._stopped:
                    return
                version = self._law_version
            devices = self._device_list()
            sig = tuple((d.ID, d.health) for d in devices)
            if sig == sent_sig:
                # A->B->A flip settled back before this stream caught
                # up: nothing to tell kubelet.
                with self._law_cond:
                    self._law_dedup_total += 1
                continue
            sent_sig = sig
            yield dp.ListAndWatchResponse(devices=devices)


class _TPUSharePluginBase(_ListAndWatchMixin, rpc.DevicePluginServicer):
    """Common Allocate/PreStart skeleton for the core and memory plugins."""

    resource: str = ""

    def __init__(self, config: PluginConfig) -> None:
        _ListAndWatchMixin.__init__(self)
        self._config = config
        self._operator = config.operator
        self._sitter = config.sitter
        self._storage = config.storage
        self._locator: DeviceLocator = config.locator_factory(self.resource)
        self._metrics = config.metrics
        self._crd = config.crd_recorder
        self._events = config.events
        self._chips = {c.index: c for c in self._operator.devices()}
        # Whole-chip (exclusive) mode: the operator makes no virtual
        # nodes; advertisement/env/qos all branch on this one flag.
        self._whole_chip = not getattr(self._operator, "virtual_nodes", True)
        self._unhealthy_chips: set = set()
        # Drain cordon (drain.py): while set, every device is advertised
        # Unhealthy so kubelet stops NEW placements — but the chips are
        # NOT in _unhealthy_chips, so no ChipUnhealthy events fire, the
        # CRD inventory stays Available, eviction policy hooks stay
        # quiet, and resident bindings ride on untouched.
        self._cordoned = False
        self._alloc_dir = config.extra.get(
            "alloc_spec_dir", DEFAULT_ALLOC_SPEC_DIR
        )
        self._slices = getattr(config, "slice_registry", None)
        self._timeline = getattr(config, "timeline", None)
        self._inflight_lock = threading.Lock()
        self._binds_inflight = 0
        self._binds_total = 0
        self._bind_failures_total = 0
        # Bind fast path: the identity-independent part of an alloc
        # spec (device paths, visibility env, host topology facts) is
        # pre-materialized per chip-index set — rendering a spec then
        # substitutes pod identity instead of recomputing topology on
        # every bind. Chip paths are fixed at discovery, so entries
        # never go stale; the cap only bounds a pathological
        # combination explosion.
        self._spec_templates: Dict[tuple, Dict] = {}
        self._spec_template_cap = 256
        self._spec_template_hits = 0
        self._spec_template_misses = 0
        self._host_facts: Optional[tuple] = None

    # -- health ---------------------------------------------------------------

    def unhealthy_chips(self) -> set:
        """Chip indexes currently advertised Unhealthy to kubelet (public
        accessor — external consumers like the manager's allocatable
        cross-check must not depend on private state)."""
        return set(self._unhealthy_chips)

    def locator_stats(self) -> Dict:
        """Locator cache introspection for /debug/allocations and the
        node-doctor bundle (empty when the locator has no stats)."""
        if hasattr(self._locator, "stats"):
            return self._locator.stats()
        return {}

    def _chip_health(self, chip_index: int) -> str:
        return (
            rpc.UNHEALTHY
            if self._cordoned or chip_index in self._unhealthy_chips
            else rpc.HEALTHY
        )

    @property
    def cordoned(self) -> bool:
        """True while a drain has this resource's devices advertised
        unschedulable (distinct from unhealthy — see set_cordoned)."""
        return self._cordoned

    def set_cordoned(self, flag: bool) -> None:
        """Flip the drain cordon and wake ListAndWatch so kubelet sees
        every device Unhealthy (no new placements) or Healthy again —
        WITHOUT touching the health accounting (no events, no CRD
        Failed, no eviction hooks). Idempotent."""
        flag = bool(flag)
        if flag == self._cordoned:
            return
        self._cordoned = flag
        logger.warning(
            "%s: devices %s by drain cordon", self.resource,
            "unschedulable" if flag else "re-schedulable",
        )
        self.notify_devices_changed()

    def apply_health(self, healthy: set) -> tuple:
        """Apply an operator health view; on change, flip device health and
        wake ListAndWatch so kubelet stops (or resumes) placing units on
        the affected chips. Returns (went_bad, recovered) chip-index sets."""
        unhealthy = set(self._chips) - healthy
        if unhealthy == self._unhealthy_chips:
            return set(), set()
        went_bad = unhealthy - self._unhealthy_chips
        recovered = self._unhealthy_chips - unhealthy
        self._unhealthy_chips = unhealthy
        if went_bad:
            logger.warning(
                "%s: chips %s now unhealthy", self.resource, sorted(went_bad)
            )
        if recovered:
            logger.info(
                "%s: chips %s recovered", self.resource, sorted(recovered)
            )
        self.notify_devices_changed()
        return went_bad, recovered

    # -- helpers --------------------------------------------------------------

    def _chips_for_request(self, n_ids: int) -> int:
        raise NotImplementedError

    def _alloc_envs(self, device: Device, n_chips: int) -> Dict[str, str]:
        # qos_env derives the quota/units values from _qos_kwargs — the
        # single source shared with the PreStart alloc spec, so the
        # Allocate-time env and the hook-injected env can never disagree.
        envs = {EnvAllocationHash: device.hash}
        envs.update(qos_env({}, **self._qos_kwargs(device)))
        return envs

    def _alloc_device_specs(self, device: Device, n_chips: int) -> List[dp.DeviceSpec]:
        return []

    def GetDevicePluginOptions(self, request, context):  # noqa: N802, ARG002
        return dp.DevicePluginOptions(
            pre_start_required=True,
            get_preferred_allocation_available=True,
        )

    # -- Allocate -------------------------------------------------------------

    def Allocate(self, request, context):  # noqa: N802, ARG002
        t0 = time.monotonic()
        with get_tracer().trace(
            "Allocate", resource=self.resource,
            requests=len(request.container_requests),
            node=self._config.node_name,
        ) as tr:
            responses = []
            hashes = []
            for creq in request.container_requests:
                device = Device(creq.devicesIDs, self.resource)
                hashes.append(device.hash)
                n_chips = self._chips_for_request(len(creq.devicesIDs))
                with get_tracer().span(
                    "build_response", hash=device.hash,
                    n_ids=len(creq.devicesIDs), n_chips=n_chips,
                ):
                    responses.append(
                        dp.ContainerAllocateResponse(
                            envs=self._alloc_envs(device, n_chips),
                            devices=self._alloc_device_specs(device, n_chips),
                        )
                    )
                logger.info(
                    "Allocate %s: %d ids -> hash %s (%d chip slots) "
                    "[trace %s]",
                    self.resource, len(creq.devicesIDs), device.hash,
                    n_chips, tr.trace_id,
                )
            tr.set(hashes=hashes)
            resp = dp.AllocateResponse(container_responses=responses)
            if self._metrics is not None:
                self._metrics.observe_allocate(time.monotonic() - t0)
            # Warm the locate cache while kubelet sets up the sandbox, so
            # the upcoming PreStartContainer skips the O(node pods) List.
            if hasattr(self._locator, "prefetch_async"):
                with get_tracer().span("prefetch_locator"):
                    self._locator.prefetch_async()
        return resp

    # -- GetPreferredAllocation ----------------------------------------------

    def GetPreferredAllocation(self, request, context):  # noqa: N802, ARG002
        """Pack the allocation onto as few, ICI-adjacent chips as possible.

        The reference never implemented this (base.go:86-88 returns empty),
        which lets kubelet scatter fake ids across chips arbitrarily. Dense
        packing keeps fractional allocations chip-aligned; when a request
        *must* span chips, the chip set is chosen for minimum ICI hop span
        (topology.chip_grid) so intra-pod collectives ride the shortest
        mesh paths — a TPU concern with no GPU analogue in the reference.
        """
        responses = []
        for creq in request.container_requests:
            need = creq.allocation_size - len(creq.must_include_deviceIDs)
            chosen = list(creq.must_include_deviceIDs)
            if need > 0:
                by_chip: Dict[int, List[str]] = {}
                unparseable: List[str] = []
                for did in creq.available_deviceIDs:
                    if did in chosen:
                        continue
                    chip = chip_of_device_id(did)
                    if chip is None:
                        # Don't bucket junk onto chip 0 — that would skew
                        # packing toward it. Kept as last-resort filler only.
                        unparseable.append(did)
                        continue
                    by_chip.setdefault(chip, []).append(did)
                pinned = {
                    c for c in (
                        chip_of_device_id(did)
                        for did in creq.must_include_deviceIDs
                    ) if c is not None
                }
                for chip in _pick_chip_set(
                    by_chip, need, len(self._chips), pinned
                ):
                    take = by_chip[chip][:need]
                    chosen.extend(take)
                    need -= len(take)
                    if need <= 0:
                        break
                if need > 0 and unparseable:
                    chosen.extend(unparseable[:need])
            self._note_packing(
                (c for c in (chip_of_device_id(d) for d in chosen)
                 if c is not None),
                observe=False,  # proposal, not a bind
            )
            responses.append(
                dp.ContainerPreferredAllocationResponse(deviceIDs=chosen)
            )
        return dp.PreferredAllocationResponse(container_responses=responses)

    # -- PreStartContainer ----------------------------------------------------

    def PreStartContainer(self, request, context):  # noqa: N802, ARG002
        t0 = time.monotonic()
        device = Device(request.devicesIDs, self.resource)
        with self._inflight_lock:
            self._binds_inflight += 1
        if self._metrics is not None and hasattr(
            self._metrics, "bind_inflight"
        ):
            self._metrics.bind_inflight.inc()
        with get_tracer().trace(
            "PreStartContainer", resource=self.resource, hash=device.hash,
            n_ids=len(request.devicesIDs),
            node=self._config.node_name,
        ) as tr:
            try:
                self._bind(device)
                with self._inflight_lock:
                    self._binds_total += 1
            except Exception:
                logger.exception(
                    "PreStartContainer %s failed for %s [trace %s]",
                    self.resource, device.hash, tr.trace_id,
                )
                with self._inflight_lock:
                    self._bind_failures_total += 1
                raise
            finally:
                with self._inflight_lock:
                    self._binds_inflight -= 1
                if self._metrics is not None:
                    if hasattr(self._metrics, "bind_inflight"):
                        self._metrics.bind_inflight.dec()
                    self._metrics.observe_prestart(time.monotonic() - t0)
        return dp.PreStartContainerResponse()

    def bind_stats(self) -> Dict:
        """Bind-pipeline introspection for /debug/allocations and the
        node-doctor bundle."""
        with self._inflight_lock:
            out = {
                "inflight": self._binds_inflight,
                "binds_total": self._binds_total,
                "bind_failures_total": self._bind_failures_total,
                "spec_template_hits": self._spec_template_hits,
                "spec_template_misses": self._spec_template_misses,
            }
        with self._law_cond:
            out["law_dedup_total"] = self._law_dedup_total
        return out

    def _lookup_pod(self, owner) -> Optional[dict]:
        with get_tracer().span(
            "pod_lookup", pod=f"{owner.namespace}/{owner.name}"
        ) as sp:
            pod = self._sitter.get_pod(owner.namespace, owner.name)
            sp.set(informer_hit=pod is not None)
            if pod is None:
                pod = self._sitter.get_pod_from_api(owner.namespace, owner.name)
        return pod

    def _bind(self, device: Device) -> None:
        owner = self._locator.locate(device)
        pod = self._lookup_pod(owner)
        if pod is None and hasattr(self._locator, "invalidate"):
            # The locator cache may hold a dead owner for a *reused* fake-id
            # set (kubelet recycles ids once the old pod is gone). Force a
            # fresh pod-resources List and retry once.
            self._locator.invalidate()
            owner = self._locator.locate(device)
            pod = self._lookup_pod(owner)
        if pod is None:
            raise LocateError(f"pod {owner.pod_key} not found anywhere")
        # From here the trace is attributable to a pod — /debug/traces
        # filters on exactly this attribute.
        get_tracer().annotate(
            pod=f"{owner.namespace}/{owner.name}", container=owner.container
        )
        # Cross-node continuity: if admission stamped a trace id on the
        # pod, this bind continues under it — the fleet observatory can
        # then follow one id from apiserver admission to whichever node's
        # agent bound the pod (both the core and the memory bind of a
        # container adopt the same id: they are one logical allocation).
        admission_id = (
            pod.get("metadata", {}).get("annotations", {}) or {}
        ).get(AnnotationTraceID, "")
        if admission_id:
            get_tracer().adopt_id(admission_id)
        try:
            self._bind_located(device, owner, pod)
        except Exception as e:
            if self._events is not None:
                self._events.pod_event(
                    owner.namespace, owner.name, ReasonBindFailed,
                    f"{self.resource} {device.hash}: {e}", type_="Warning",
                    uid=pod.get("metadata", {}).get("uid", ""),
                )
            raise

    def _chips_from_ids(self, device: Device) -> List[int]:
        """Chip indexes encoded in the fake device ids themselves — the
        authoritative source in whole-chip (exclusive) mode, where no
        scheduler annotation redirects the placement."""
        return sorted({
            c for c in (chip_of_device_id(i) for i in device.ids)
            if c is not None
        })

    def _note_packing(self, chip_indexes, observe: bool = True) -> None:
        """Export the packing score (total ICI span of the chip set) —
        per bind as the ``elastic_tpu_packing_ici_span`` histogram and as
        a ``packing_span`` attribute on the active trace, so a scheduler
        that spreads a grant across the mesh is a visible regression.
        ``observe=False`` annotates the trace only: admission-time
        proposals (GetPreferredAllocation) may never bind, and counting
        them would double the per-BIND histogram."""
        span = packing.packing_score(chip_indexes, len(self._chips))
        get_tracer().annotate(packing_span=span)
        if observe and self._metrics is not None and hasattr(
            self._metrics, "packing_span"
        ):
            try:
                self._metrics.packing_span.observe(span)
            except Exception:  # noqa: BLE001 - metrics never break a bind
                pass

    def _journal_intent(
        self, owner, device: Device, chip_indexes: List[int],
        planned: List[str],
    ) -> int:
        """Write-ahead intent: everything recovery needs to roll this
        bind back (the link ids it will create, the spec hash) or replay
        it (the exact device ids), durably recorded BEFORE the first
        side effect."""
        intent_id = self._storage.journal_intent(
            owner.pod_key, owner.container, self.resource, device.hash,
            {
                "device_ids": list(device.ids),
                "chip_indexes": list(chip_indexes),
                "planned_link_ids": list(planned),
            },
        )
        if self._timeline is not None:
            self._timeline.emit(
                tl.KIND_BIND_INTENT,
                keys=self._bind_keys(owner, device, chip_indexes),
                resource=self.resource, intent_id=intent_id,
                n_ids=len(device.ids),
            )
        return intent_id

    def _bind_keys(
        self, owner, device: Device, chip_indexes: List[int],
        slice_id: str = "",
    ) -> Dict:
        keys = {
            "pod": owner.pod_key,
            "container": owner.container,
            "hash": device.hash,
            "chips": list(chip_indexes),
        }
        if slice_id:
            keys["slice"] = slice_id
        return keys

    def _emit_rollback(
        self, owner, device: Device, chip_indexes: List[int],
        intent_id: int, reason: str,
    ) -> None:
        if self._timeline is not None:
            self._timeline.emit(
                tl.KIND_BIND_ROLLBACK,
                keys=self._bind_keys(owner, device, chip_indexes),
                resource=self.resource, intent_id=intent_id,
                reason=reason,
            )

    def _bind_located(self, device: Device, owner, pod: dict) -> None:
        annotations = pod.get("metadata", {}).get("annotations", {}) or {}
        slice_id = annotations.get(AnnotationSliceID, "")
        if slice_id:
            # Slice-aware traces: /debug/traces and the fleet observatory
            # can follow every member bind of one slice by this attribute.
            get_tracer().annotate(slice=slice_id)
        # Crash-window failpoints (test-only): each names the window a
        # process death is injected into, proving the journal recovers it.
        faults.fire("bind.pre_journal")
        if self._whole_chip:
            # Whole-chip mode (reference: the nvidia no-op operator,
            # pkg/operator/nvidia.go): kubelet's device choice IS the
            # placement; no elastic-scheduler annotation is required and no
            # virtual nodes exist — Allocate already handed out the
            # physical /dev/accel* paths.
            chip_indexes = self._chips_from_ids(device)
            self._require_known_chips(chip_indexes)
            self._note_packing(chip_indexes)
            intent_id = self._journal_intent(owner, device, chip_indexes, [])
            try:
                faults.fire("bind.post_journal")
                try:
                    self._finish_bind(
                        device, owner, pod, annotations, chip_indexes,
                        created=[], intent_id=intent_id,
                    )
                except Exception:
                    # Handled failure: the bind rolled itself back, so
                    # the intent must not linger for the reconciler.
                    self._storage.journal_remove(intent_id)
                    self._emit_rollback(
                        owner, device, chip_indexes, intent_id,
                        "handled_failure",
                    )
                    raise
            finally:
                # On EVERY exit (BaseException included) this thread
                # stops shielding the intent from the reconciler; a
                # dead thread's row becomes recoverable immediately.
                self._storage.intent_done(intent_id)
            return
        if annotations.get(AnnotationAssumed) != "true":
            raise LocateError(
                f"pod {owner.pod_key} not assumed by the elastic scheduler"
            )
        ann_key = container_annotation(owner.container)
        if ann_key not in annotations:
            raise LocateError(
                f"pod {owner.pod_key} missing annotation {ann_key}"
            )
        # Canonical device ordering (satellite of the slice orchestrator):
        # the in-container numbering (TPU_VISIBLE_CHIPS position p ->
        # /dev/accel<p>) follows the grid walk of the chip set, not the
        # order the scheduler happened to write the annotation in — a
        # reformed or replayed slice member gets identical device
        # numbering every time.
        chip_indexes = packing.canonical_chip_order(
            _parse_chip_annotation(annotations[ann_key]), len(self._chips)
        )
        expected = self._chips_for_request(len(device.ids))
        if len(chip_indexes) != expected:
            # Allocate guessed minimum packing (ceil(units/chip)); a
            # scheduler that spreads wider binds all annotated chips into
            # the alloc spec, but kubelet installed device-cgroup allow
            # rules only for Allocate's ``expected`` DeviceSpecs. The NRI
            # adjustment re-derives LinuxDevice (cgroup) entries from the
            # spec, so spread works there; the hooks.d path mknods the
            # extra nodes WITHOUT cgroup rules — a non-privileged
            # container gets EPERM on them (docs/operations.md).
            logger.warning(
                "%s %s: scheduler spread %d chips, Allocate assumed %d; "
                "extra chips are usable via the NRI path only — on "
                "hooks.d a non-privileged container will get EPERM on "
                "them (see docs/operations.md)",
                self.resource, device.hash, len(chip_indexes), expected,
            )
        self._require_known_chips(chip_indexes)
        self._note_packing(chip_indexes)

        # Intent journaled before the first side effect; materialize
        # virtual nodes; roll back on partial failure (reference:
        # gpushare.go:133-142).
        planned = [f"{device.hash}-{p}" for p in range(len(chip_indexes))]
        intent_id = self._journal_intent(owner, device, chip_indexes, planned)
        try:
            faults.fire("bind.post_journal")
            created: List[str] = []
            try:
                with get_tracer().span(
                    "materialize_nodes", chips=list(chip_indexes)
                ):
                    for p, idx in enumerate(chip_indexes):
                        link_id = f"{device.hash}-{p}"
                        self._operator.create(idx, link_id)
                        created.append(link_id)
                faults.fire("bind.post_create")
            except Exception:
                self._rollback_created(created)
                self._storage.journal_remove(intent_id)
                self._emit_rollback(
                    owner, device, chip_indexes, intent_id,
                    "materialize_failed",
                )
                raise
            try:
                self._finish_bind(
                    device, owner, pod, annotations, chip_indexes, created,
                    intent_id=intent_id,
                )
            except Exception:
                # Handled failure: _finish_bind already rolled back the
                # spec/links; clear the intent so only a real crash
                # leaves one.
                self._storage.journal_remove(intent_id)
                self._emit_rollback(
                    owner, device, chip_indexes, intent_id,
                    "handled_failure",
                )
                raise
        finally:
            # On EVERY exit (BaseException included) this thread stops
            # shielding the intent from the reconciler; a dead thread's
            # row becomes recoverable immediately.
            self._storage.intent_done(intent_id)

    def _rollback_created(self, created: List[str]) -> None:
        for link_id in created:
            try:
                self._operator.delete(link_id)
            except Exception:  # noqa: BLE001
                logger.warning("rollback: failed deleting %s", link_id)

    def _require_known_chips(self, chip_indexes: List[int]) -> None:
        unknown = [i for i in chip_indexes if i not in self._chips]
        if unknown:
            raise LocateError(
                f"chips {unknown} not present on this host"
            )

    def _finish_bind(
        self,
        device: Device,
        owner,
        pod: dict,
        annotations: Dict,
        chip_indexes: List[int],
        created: List[str],
        intent_id: Optional[int] = None,
    ) -> None:
        # One PER-OWNER lock spans sibling discovery, the spec write, AND
        # the storage save that publishes this allocation: a core/memory
        # PreStart pair for the same container racing here could otherwise
        # both miss the sibling (save not yet visible) and write unmerged
        # specs — and the checkpoint below is a read-modify-write that
        # would lose one record. Sibling pairs share a pod key, hence a
        # stripe; unrelated pods take different stripes and bind in
        # parallel (a node restart re-binds every pod at once — the burst
        # the striping exists for).
        locks = _BIND_LOCKS  # one reference: acquire/release must pair
        with get_tracer().span("bind_lock_wait") as sp:
            lock_wait_s = locks.acquire_key(owner.pod_key)
            sp.set(wait_ms=round(lock_wait_s * 1000, 3))
        try:
            if self._metrics is not None and hasattr(
                self._metrics, "bind_lock_wait"
            ):
                self._metrics.bind_lock_wait.observe(lock_wait_s)
            own_path = os.path.join(self._alloc_dir, f"{device.hash}.json")
            fresh_bind = not os.path.exists(own_path)
            try:
                with get_tracer().span("write_alloc_spec", hash=device.hash):
                    self._write_alloc_spec(
                        device, owner, chip_indexes, annotations, pod
                    )
            except Exception:
                # Sibling files are merged before the own file lands; a
                # mid-write failure may have left them naming this failed
                # allocation — restore them before surfacing the error.
                # Only for a FRESH bind though: a transient failure while
                # re-binding (container restart) must leave the previous,
                # still-valid on-disk specs alone.
                if fresh_bind:
                    try:
                        os.unlink(own_path)
                    except OSError:
                        pass
                    self._restore_sibling_specs(owner, device.hash)
                self._rollback_created(created)
                raise
            faults.fire("bind.post_spec")

            record = AllocationRecord(
                device=device,
                chip_indexes=chip_indexes,
                created_node_ids=created,
            )
            with get_tracer().span("checkpoint"):
                # mutate() adds storage's own per-key serialization on
                # top of the bind lock, so the read-modify-write stays
                # atomic even against writers that don't hold a bind
                # stripe (restore, tools).
                self._storage.mutate(
                    owner.namespace, owner.name,
                    lambda info: info.set_allocation(owner.container, record),
                )
            faults.fire("bind.post_checkpoint")
            if intent_id is not None:
                # Commit = drop the journal row, INSIDE the stripe: the
                # reconciler's "intent still open?" re-check holds this
                # stripe too, so open-at-recheck implies no concurrent
                # bind is past its checkpoint for this pod.
                self._storage.journal_commit(intent_id)
        finally:
            locks.release_key(owner.pod_key)
        # The post-lock sink fan-out (timeline journal, gauge refresh,
        # CRD + Event enqueue) is its own critical-path phase: the
        # writes are async-queued but the ENQUEUE work runs on the bind
        # thread, and the latency observatory attributes it.
        with get_tracer().span("sink_enqueue"):
            if self._timeline is not None:
                # Commit phase of the bind story: journaled AFTER the
                # record checkpoint + journal_commit (a crash in between
                # is exactly what the reconciler's intent resolution —
                # and its own reconcile_repair event — narrates instead).
                self._timeline.emit(
                    tl.KIND_BIND_COMMIT,
                    keys=self._bind_keys(
                        owner, device, chip_indexes,
                        slice_id=annotations.get(AnnotationSliceID, ""),
                    ),
                    resource=self.resource, intent_id=intent_id,
                    links=len(created),
                )
            if self._metrics is not None:
                # O(1) COUNT(*) — the per-bind gauge update must not
                # deserialize the whole store (it used to scan every row).
                self._metrics.bound_allocations.set(self._storage.count())
            if self._crd is not None:
                self._crd.record_bound(
                    device.hash, self.resource, len(device.ids),
                    owner.namespace, owner.name, owner.container,
                    chip_indexes,
                    trace_id=get_tracer().current_id(),
                )
            if self._events is not None:
                self._events.pod_event(
                    owner.namespace, owner.name, ReasonBound,
                    f"bound {self.resource} ({len(device.ids)} units) to "
                    f"TPU chip(s) "
                    f"{','.join(str(i) for i in chip_indexes)}",
                    uid=pod.get("metadata", {}).get("uid", ""),
                )
        logger.info(
            "bound %s %s -> %s chips %s",
            self.resource, device.hash, owner.pod_key, chip_indexes,
        )

    # -- allocation spec for the OCI hook -------------------------------------

    def _qos_kwargs(self, device: Device) -> Dict:
        """Per-resource inputs for qos_env (overridden by subclasses)."""
        return {}

    def _host_slice_facts(self):
        """(topology, worker_id, hostnames) from the operator when it knows
        them (tpu-vm/stub operators do; exclusive wrapper may not).
        Cached after the first probe: host identity is fixed for the
        agent's lifetime, and the per-bind operator round-trips were
        pure recompute on the hot path."""
        if self._host_facts is None:
            op = self._operator
            topo = getattr(op, "topology", None)
            worker_id = op.worker_id() if hasattr(op, "worker_id") else 0
            hostnames = (
                op.worker_hostnames()
                if hasattr(op, "worker_hostnames") else []
            )
            self._host_facts = (topo, worker_id, hostnames)
        return self._host_facts

    def _spec_template(self, chip_indexes: List[int]) -> Dict:
        """The identity-independent spec skeleton for one chip-index
        set: device paths + visibility env. Benign races just build the
        same template twice."""
        key = tuple(chip_indexes)
        tpl = self._spec_templates.get(key)
        if tpl is None:
            visible = ",".join(str(p) for p in range(len(chip_indexes)))
            tpl = {
                "device_paths": [
                    self._chips[i].device_path for i in chip_indexes
                ],
                "base_env": {
                    EnvTPUVisibleChips: visible,
                    EnvTPUVisibleDevices: visible,
                },
            }
            if len(self._spec_templates) >= self._spec_template_cap:
                self._spec_templates.clear()
            self._spec_templates[key] = tpl
            with self._inflight_lock:
                self._spec_template_misses += 1
        else:
            with self._inflight_lock:
                self._spec_template_hits += 1
        return tpl

    def _spec_payload(
        self,
        device: Device,
        owner,
        chip_indexes: List[int],
        annotations: Dict,
        pod: Optional[dict] = None,
    ) -> Dict:
        tpl = self._spec_template(chip_indexes)
        env = dict(tpl["base_env"])
        env.update(qos_env(annotations, pod_spec=pod, **self._qos_kwargs(device)))
        topo, worker_id, hostnames = self._host_slice_facts()
        if self._slices is not None:
            # Registry-derived slice env: deterministic worker ordering,
            # reform-aware world size, slice name + epoch (slices/).
            slice_env = self._slices.pod_env(
                annotations, topo, worker_id, hostnames
            )
            if slice_env.get(EnvSliceName):
                try:
                    wid = int(slice_env.get("TPU_WORKER_ID", "0"))
                except ValueError:
                    wid = 0
                self._slices.record_local_pod(
                    slice_env[EnvSliceName],
                    f"{owner.namespace}/{owner.name}", wid,
                )
                if self._timeline is not None:
                    # Formation stamp: this bind just wrote the slice's
                    # world + epoch into the pod's env — the event a
                    # later reform (or a triage session asking "what
                    # world did the runner boot into?") is diffed
                    # against.
                    self._timeline.emit(
                        tl.KIND_SLICE_FORMED,
                        keys={
                            "pod": f"{owner.namespace}/{owner.name}",
                            "container": owner.container,
                            "slice": slice_env[EnvSliceName],
                            "chips": list(chip_indexes),
                        },
                        resource=self.resource,
                        epoch=_safe_int(slice_env.get(EnvSliceEpoch)),
                        worker_id=wid,
                        hosts=slice_env.get("TPU_WORKER_HOSTNAMES", ""),
                    )
        else:
            slice_env = slice_env_for_pod(
                annotations, topo, worker_id, hostnames
            )
        env.update(slice_env)
        trace_id = get_tracer().current_id()
        if trace_id:
            # Propagated through the hook-authored env file so the
            # in-pod flight recorder (workloads/telemetry.py) tags its
            # step records with the bind's trace id.
            env["ELASTIC_TPU_TRACE_ID"] = trace_id
        return {
            "hash": device.hash,
            "resource": self.resource,
            "namespace": owner.namespace,
            "pod": owner.name,
            "container": owner.container,
            "chip_indexes": chip_indexes,
            "device_paths": list(tpl["device_paths"]),
            "env": env,
        }

    def _sibling_specs(self, owner) -> List[Dict]:
        """Alloc-spec payloads already written for the SAME container by the
        other resource's plugin (a container normally requests both tpu-core
        and tpu-memory)."""
        info = self._storage.load(owner.namespace, owner.name)
        if info is None:
            return []
        out = []
        for resource, rec in info.allocations.get(owner.container, {}).items():
            if resource == self.resource:
                continue
            path = os.path.join(self._alloc_dir, f"{rec.device.hash}.json")
            try:
                with open(path) as f:
                    out.append(json.load(f))
            except (OSError, ValueError):
                continue
        return out

    def _write_alloc_spec(
        self,
        device: Device,
        owner,
        chip_indexes: List[int],
        annotations: Dict,
        pod: Optional[dict] = None,
    ) -> None:
        """Write the spec for the OCI hook — MERGED with any sibling
        resource's spec for the same container.

        A container requesting both tpu-core and tpu-memory receives two
        Allocate responses, each carrying ``TPU=<its own hash>``; kubelet
        merges container env in undefined order, so the hook resolves
        whichever hash happened to win. The reference had the same defect
        and injected only the winner's spec (gpushare.go:79-82/204-207:
        both set ``GPU=``, losing the loser's env). Here every spec file
        for a container carries the union (devices + env of both
        resources), so the hook's injection is identical no matter which
        hash survives the merge.
        """
        # Caller (_finish_bind) holds the owner's bind stripe across this
        # write and the storage save that makes the allocation visible to
        # siblings.
        os.makedirs(self._alloc_dir, exist_ok=True)
        payload = self._spec_payload(device, owner, chip_indexes, annotations, pod)
        # Pre-merge snapshot: lets a later single-resource release restore
        # the surviving sibling's spec to exactly this content instead of
        # leaving it naming the released allocation's chips/env.
        payload["own"] = {
            "chip_indexes": list(payload["chip_indexes"]),
            "device_paths": list(payload["device_paths"]),
            "env": dict(payload["env"]),
        }
        for sib in self._sibling_specs(owner):
            payload, merged_sib = _merge_spec_payloads(payload, sib)
            _write_json_atomic(
                os.path.join(self._alloc_dir, f"{merged_sib['hash']}.json"),
                merged_sib,
            )
        _write_json_atomic(
            os.path.join(self._alloc_dir, f"{device.hash}.json"), payload
        )

    def _restore_sibling_specs(self, owner, released_hash: str) -> None:
        """(owner's bind stripe held) Rewrite the container's surviving
        sibling specs from their pre-merge ``own`` snapshots, so the
        released allocation's devices/env stop appearing in them (the
        stale-union defect, ADVICE r2/r3)."""
        info = self._storage.load(owner.namespace, owner.name)
        siblings = info.allocations.get(owner.container, {}) if info else {}
        for rec in siblings.values():
            if rec.device.hash == released_hash:
                continue
            path = os.path.join(self._alloc_dir, f"{rec.device.hash}.json")
            try:
                with open(path) as f:
                    spec = json.load(f)
            except (OSError, ValueError):
                continue
            own = spec.get("own")
            if not own:
                continue
            restored = dict(spec)
            restored.update(own)
            restored["resources"] = [restored.get("resource", "")]
            _write_json_atomic(path, restored)

    def _remove_usage_report(self, alloc_hash: str) -> None:
        """Reclaim the allocation's sidecar files — the usage
        self-report AND the checkpoint ack — along with its spec. ONE
        subdir list (common.AllocSidecarSubdirs) shared with the
        reconciler's orphan-spec sweep: without this, pod churn grows
        the sidecar dirs without bound, and a stale ack under a reused
        hash would read as a fresh checkpoint acknowledgement."""
        from ..common import AllocSidecarSubdirs

        for subdir in AllocSidecarSubdirs:
            for suffix in (".json", ".json.tmp"):
                try:
                    os.unlink(
                        os.path.join(self._alloc_dir, subdir,
                                     f"{alloc_hash}{suffix}")
                    )
                except OSError:
                    pass

    def remove_alloc_spec(self, alloc_hash: str, owner=None) -> None:
        """Unlink an allocation's spec (and its usage self-report);
        when ``owner`` is given, also restore the container's surviving
        sibling specs to their own (unmerged) content."""
        if owner is None:
            try:
                os.unlink(
                    os.path.join(self._alloc_dir, f"{alloc_hash}.json")
                )
            except FileNotFoundError:
                pass
            self._remove_usage_report(alloc_hash)
            return
        with _BIND_LOCKS.acquire(owner.pod_key):
            self.remove_alloc_spec_locked(alloc_hash, owner)

    def remove_alloc_spec_locked(self, alloc_hash: str, owner) -> None:
        """remove_alloc_spec for a caller ALREADY holding the owner's
        bind stripe (the reconciler's intent rollback / drift repair —
        the stripes are not reentrant)."""
        try:
            os.unlink(os.path.join(self._alloc_dir, f"{alloc_hash}.json"))
        except FileNotFoundError:
            pass
        self._remove_usage_report(alloc_hash)
        self._restore_sibling_specs(owner, alloc_hash)

    def read_alloc_spec(self, alloc_hash: str) -> Optional[Dict]:
        """The on-disk alloc-spec payload for an allocation, or None
        when absent/corrupt (slice-divergence detection reads the
        stamped env through this)."""
        try:
            with open(
                os.path.join(self._alloc_dir, f"{alloc_hash}.json")
            ) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def restamp_spec_env_locked(
        self, owner, records: Dict, env_updates: Dict[str, str],
        remove_keys=(),
    ) -> int:
        """(owner's bind stripe held) Update env keys in EVERY on-disk
        spec of this container — the merged env and the pre-merge ``own``
        snapshot both, atomic per file — without re-running the bind.
        The slice reformer re-emits topology env at a new world size
        through this, and the drain orchestrator stamps (and, on cancel,
        removes via ``remove_keys``) the ELASTIC_TPU_DRAIN signal;
        devices/chips stay untouched, so the container's cgroup reality
        is never contradicted. Returns files restamped."""
        restamped = 0
        for record in records.values():
            path = os.path.join(
                self._alloc_dir, f"{record.device.hash}.json"
            )
            try:
                with open(path) as f:
                    spec = json.load(f)
            except (OSError, ValueError):
                continue
            targets = [spec.setdefault("env", {})]
            own = spec.get("own")
            if isinstance(own, dict):
                targets.append(own.setdefault("env", {}))
            changed = False
            for env in targets:
                for key, value in env_updates.items():
                    if env.get(key) != value:
                        env[key] = value
                        changed = True
                for key in remove_keys:
                    if env.pop(key, None) is not None:
                        changed = True
            if changed:
                _write_json_atomic(path, spec)
            # Crash-window failpoint (test-only): fires after each spec
            # file lands, so an armed die-thread kills the restamp
            # BETWEEN the sibling files of one container — the
            # torn-quota window the repartition crash-replay suite
            # proves recoverable.
            faults.fire("restamp.spec_file")
            # An already-correct spec still counts: callers (slice
            # reform, the drain's per-tick re-signal) treat the count
            # as "specs carrying the env", and the skip is what makes
            # repeating the stamp every tick cheap.
            restamped += 1
        return restamped

    def alloc_spec_exists(self, alloc_hash: str) -> bool:
        """Whether the OCI-hook spec file for an allocation is on disk
        (reconciler divergence check)."""
        return os.path.exists(
            os.path.join(self._alloc_dir, f"{alloc_hash}.json")
        )

    def rebind(self, owner, device: Device) -> None:
        """Reconciler entry point: run the full bind transaction for an
        already-located owner — journals its own intent, re-creates
        virtual nodes (idempotent), rewrites/merges the alloc spec and
        re-checkpoints. Used to replay a bind that kubelet's assignment
        proves happened but that a crash cut short, and to re-bind after
        a kubelet restart handed the container different device ids."""
        pod = self._lookup_pod(owner)
        if pod is None:
            raise LocateError(f"pod {owner.pod_key} not found anywhere")
        get_tracer().annotate(
            pod=f"{owner.namespace}/{owner.name}", container=owner.container
        )
        if self._timeline is not None:
            # Replay phase: the transaction below re-journals its own
            # intent/commit; this event marks that those happened as a
            # recovery replay, not a fresh kubelet-driven bind.
            self._timeline.emit(
                tl.KIND_BIND_REPLAY,
                keys=self._bind_keys(owner, device, []),
                resource=self.resource,
            )
        self._bind_located(device, owner, pod)


class TPUShareCorePlugin(_TPUSharePluginBase):
    """elasticgpu.io/tpu-core: 100 fake units per chip."""

    resource = ResourceTPUCore

    def _device_list(self) -> List[dp.Device]:
        out = []
        for chip in self._chips.values():
            health = self._chip_health(chip.index)
            if self._whole_chip:
                # One advertised device == one physical chip (the reference
                # no-op operator's shape, pkg/operator/nvidia.go:1-22).
                # Advertising 100 fractional units here would let kubelet
                # split one chip's units across two pods, each of which
                # would then receive the whole /dev/accelN — defeating the
                # mode's exclusivity promise (ADVICE r2/r3).
                out.append(
                    dp.Device(
                        ID=core_device_id(chip.index, 0), health=health
                    )
                )
                continue
            for unit in range(TPUPercentEachChip):
                out.append(
                    dp.Device(
                        ID=core_device_id(chip.index, unit), health=health
                    )
                )
        return out

    def _chips_for_request(self, n_ids: int) -> int:
        if self._whole_chip:
            return max(1, n_ids)  # whole-chip: one id == one chip
        return max(1, math.ceil(n_ids / TPUPercentEachChip))

    def _alloc_envs(self, device: Device, n_chips: int) -> Dict[str, str]:
        envs = super()._alloc_envs(device, n_chips)
        if self._whole_chip:
            # Whole-chip mode: the env must match the device specs, which
            # come from the id-encoded chips — not from ceil(units/100)
            # (kubelet may have split the ids across more chips than the
            # minimum packing, e.g. when preferred allocation was skipped).
            n_chips = len(
                [c for c in self._chips_from_ids(device) if c in self._chips]
            )
        visible = ",".join(str(p) for p in range(n_chips))
        envs[EnvTPUVisibleChips] = visible
        envs[EnvTPUVisibleDevices] = visible
        return envs

    def _alloc_device_specs(self, device: Device, n_chips: int) -> List[dp.DeviceSpec]:
        if self._whole_chip:
            # Whole-chip mode: the fake ids already name physical chips and
            # no symlink will be made at PreStart — hand out the real
            # chardev paths, densely renumbered in-container.
            known = [
                c for c in self._chips_from_ids(device) if c in self._chips
            ]
            return [
                dp.DeviceSpec(
                    container_path=f"/dev/accel{p}",
                    host_path=self._chips[c].device_path,
                    permissions="rwm",
                )
                for p, c in enumerate(known)
            ]
        # Virtual link -> dense in-container /dev/accel<p>. The runtime
        # resolves the symlink at container create (after PreStart made it).
        return [
            dp.DeviceSpec(
                container_path=f"/dev/accel{p}",
                host_path=f"/dev/elastic-tpu-{device.hash}-{p}",
                permissions="rwm",
            )
            for p in range(n_chips)
        ]

    def _qos_kwargs(self, device: Device) -> Dict:
        if self._whole_chip:
            # Whole-chip: one advertised id == one chip == 100% of it. The
            # qos contract ("core share in 1% units", qos.py) would
            # otherwise read an exclusive pod as a 1% share and a
            # duty-cycle-honoring runtime would throttle it to nothing.
            n = len(
                [c for c in self._chips_from_ids(device) if c in self._chips]
            ) or len(device.ids)
            return {"core_units": TPUPercentEachChip * n}
        return {"core_units": len(device.ids)}


class TPUShareMemoryPlugin(_TPUSharePluginBase):
    """elasticgpu.io/tpu-memory: 1 fake unit per MiB of HBM."""

    resource = ResourceTPUMemory

    def __init__(self, config: PluginConfig) -> None:
        super().__init__(config)
        chips = list(self._chips.values())
        self._mib_per_chip = (
            chips[0].hbm_bytes // BytesPerMemoryUnit if chips else 0
        )

    def _device_list(self) -> List[dp.Device]:
        out = []
        for chip in self._chips.values():
            health = self._chip_health(chip.index)
            units = chip.hbm_bytes // BytesPerMemoryUnit
            for unit in range(units):
                out.append(
                    dp.Device(
                        ID=mem_device_id(chip.index, unit), health=health
                    )
                )
        return out

    def _chips_for_request(self, n_ids: int) -> int:
        if self._mib_per_chip <= 0:
            return 1
        return max(1, math.ceil(n_ids / self._mib_per_chip))

    def _hbm_limit_bytes(self, device: Device) -> int:
        return len(device.ids) * BytesPerMemoryUnit

    def _qos_kwargs(self, device: Device) -> Dict:
        return {
            "hbm_limit_bytes": self._hbm_limit_bytes(device),
            "chip_hbm_bytes": self._mib_per_chip * BytesPerMemoryUnit,
        }

    def _spec_payload(self, device, owner, chip_indexes, annotations, pod=None):
        payload = super()._spec_payload(
            device, owner, chip_indexes, annotations, pod
        )
        payload["hbm_limit_bytes"] = self._hbm_limit_bytes(device)
        return payload


class TPUSharePlugin:
    """Bundle of the two per-resource servers + the GC loop
    (reference GPUSharePlugin, base.go:203-306)."""

    def __init__(self, config: PluginConfig) -> None:
        self._config = config
        self.core = TPUShareCorePlugin(config)
        self.memory = TPUShareMemoryPlugin(config)
        self.servers = [
            DevicePluginServer(
                self.core, ResourceTPUCore, CORE_ENDPOINT, config
            ),
            DevicePluginServer(
                self.memory, ResourceTPUMemory, MEM_ENDPOINT, config
            ),
        ]

    def locator_stats(self) -> Dict[str, Dict]:
        """Per-resource locator cache stats (debug/diagnostics surface)."""
        return {
            ResourceTPUCore: self.core.locator_stats(),
            ResourceTPUMemory: self.memory.locator_stats(),
        }

    def plugin_for_resource(self, resource: str):
        """The per-resource server handling ``resource`` (None when it
        is not one of ours — the reconciler skips foreign extended
        resources in kubelet's pod-resources dump)."""
        return {
            ResourceTPUCore: self.core,
            ResourceTPUMemory: self.memory,
        }.get(resource)

    def set_cordoned(self, flag: bool) -> None:
        """Drain cordon across BOTH resources (they must never disagree
        about schedulability, exactly like health)."""
        changed = bool(flag) != self.core.cordoned
        self.core.set_cordoned(flag)
        self.memory.set_cordoned(flag)
        timeline = getattr(self._config, "timeline", None)
        if changed and timeline is not None:
            timeline.emit(
                tl.KIND_CORDON,
                keys={"chips": sorted(self.core._chips)},
                cordoned=bool(flag),
            )

    @property
    def cordoned(self) -> bool:
        return self.core.cordoned

    def bind_stats(self) -> Dict:
        """Bind-pipeline introspection: in-flight binds, totals, the gRPC
        pool size each resource server runs, and bind-lock contention —
        the numbers that answer "is the bind path queueing?" from
        /debug/allocations or a doctor bundle."""
        return {
            "grpc_pool_size": self._config.grpc_pool_size,
            "bind_locks": bind_lock_stats(),
            "resources": {
                ResourceTPUCore: self.core.bind_stats(),
                ResourceTPUMemory: self.memory.bind_stats(),
            },
        }

    def run(self, stop: threading.Event) -> None:
        for server in self.servers:
            server.start(stop)

    # -- chip health (no reference analogue: NVML surfaced XIDs for free) -----

    HEALTH_PERIOD_S = 5.0

    # Optional policy hooks set by the manager: on_chips_failed is
    # called with (went_bad_chips, reasons) and on_chips_recovered with
    # (recovered_chips,) on health transitions (e.g. NRI-based eviction
    # of containers bound to the dead chips, and clearing the sticky
    # eviction set when a chip comes back).
    on_chips_failed = None
    on_chips_recovered = None

    def health_once(self) -> bool:
        """One health poll: probe the operator ONCE, apply the same view to
        both resources (they must never disagree about a chip), emit events
        + metrics on transitions. The utilization sampler's flags are
        folded in — a chip whose telemetry reads keep failing is degraded
        exactly like one the operator reports broken. Returns True when
        anything changed."""
        faults.fire("health.poll")
        try:
            healthy = set(self._config.operator.healthy_indexes())
        except Exception:  # noqa: BLE001 - a broken probe must not wedge
            logger.exception("health probe failed")
            return False
        sampler = self._config.sampler
        sampler_reasons: Dict[int, str] = {}
        if sampler is not None:
            try:
                flagged = sampler.unhealthy_chips()
                if flagged:
                    sampler_reasons = sampler.health_reasons()
                    healthy -= flagged
            except Exception:  # noqa: BLE001 - sampler is never load-bearing
                logger.exception("sampler health view failed")
        went_bad, recovered = self.core.apply_health(healthy)
        self.memory.apply_health(healthy)
        reasons = {}
        if went_bad or recovered:
            try:
                reasons = self._config.operator.health_reasons()
            except Exception:  # noqa: BLE001 - reasons are best-effort
                reasons = {}
            # Operator reasons win (they are more specific); the sampler
            # fills in for chips only it flagged.
            for idx, why in sampler_reasons.items():
                reasons.setdefault(idx, why)
        recorder = self._config.crd_recorder
        if recorder is not None:
            # Keep the CRD inventory truthful: a chip that died flips its
            # ElasticTPU object to Failed (with the specific reason) so
            # external schedulers stop placing onto it; recovery flips it
            # back to Available.
            for idx in sorted(went_bad):
                recorder.record_chip_health(
                    idx, False, reasons.get(idx, "reported unhealthy")
                )
            for idx in sorted(recovered):
                recorder.record_chip_health(idx, True)
        events = self._config.events
        if events is not None:
            for idx in sorted(went_bad):
                why = reasons.get(idx, "reported unhealthy by operator")
                events.node_event(
                    ReasonChipUnhealthy,
                    f"TPU chip {idx} unhealthy ({why}); "
                    "kubelet will stop placing units on it",
                    type_="Warning",
                )
            for idx in sorted(recovered):
                events.node_event(
                    ReasonChipHealthy, f"TPU chip {idx} recovered"
                )
            if went_bad:
                self._warn_bound_pods(events, went_bad)
        timeline = getattr(self._config, "timeline", None)
        if timeline is not None:
            if went_bad:
                timeline.emit(
                    tl.KIND_CHIP_HEALTH,
                    keys={"chips": sorted(went_bad)},
                    healthy=False,
                    reasons={
                        str(i): reasons[i] for i in sorted(went_bad)
                        if i in reasons
                    },
                )
            if recovered:
                timeline.emit(
                    tl.KIND_CHIP_HEALTH,
                    keys={"chips": sorted(recovered)},
                    healthy=True,
                )
        metrics = self._config.metrics
        if metrics is not None and hasattr(metrics, "healthy_chips"):
            metrics.healthy_chips.set(
                len(self.core._chips) - len(self.core._unhealthy_chips)
            )
        if self.on_chips_failed is not None and went_bad:
            try:
                self.on_chips_failed(set(went_bad), reasons)
            except Exception:  # noqa: BLE001 - policy must not wedge health
                logger.exception("chip-failure policy hook failed")
        if self.on_chips_recovered is not None and recovered:
            try:
                self.on_chips_recovered(set(recovered))
            except Exception:  # noqa: BLE001
                logger.exception("chip-recovery policy hook failed")
        return bool(went_bad or recovered)

    def _warn_bound_pods(self, events, went_bad: set) -> None:
        """Tell each pod bound to a newly-dead chip that its device is
        gone — `kubectl describe pod` should answer "why did my training
        job stall" without node access."""
        for _, info in list(self._config.storage.items()):
            for record in info.records():
                hit = sorted(set(record.chip_indexes) & went_bad)
                if hit:
                    events.pod_event(
                        info.namespace, info.name, ReasonChipUnhealthy,
                        f"TPU chip(s) {','.join(map(str, hit))} bound to "
                        "this pod became unhealthy",
                        type_="Warning",
                    )

    def health_loop(self, stop: threading.Event) -> None:
        # Poll immediately: a chip that died between operator discovery and
        # plugin start must not be advertised Healthy for a whole period.
        while True:
            try:
                self.health_once()
            except Exception:  # noqa: BLE001
                logger.exception("health poll failed")
            if stop.wait(self.HEALTH_PERIOD_S):
                return

    def start_health(self, stop: threading.Event) -> threading.Thread:
        t = threading.Thread(
            target=self.health_loop, args=(stop,), daemon=True,
            name="tpu-health",
        )
        t.start()
        return t

    # -- GC (reference: base.go:241-306, SURVEY.md §3.3) ----------------------

    def _pod_is_gone(self, namespace: str, name: str) -> bool:
        sitter = self._config.sitter
        if sitter.get_pod(namespace, name) is not None:
            return False
        try:
            return sitter.get_pod_from_api(namespace, name) is None
        except Exception as e:  # noqa: BLE001 - apiserver down: keep state
            logger.warning("GC: apiserver check failed for %s/%s: %s",
                           namespace, name, e)
            return False

    def gc_once(self) -> int:
        """Reclaim allocations of pods that no longer exist; returns count."""
        faults.fire("gc.sweep")
        with get_tracer().trace("gc_sweep") as tr:
            reclaimed = self._gc_sweep()
            tr.set(reclaimed=reclaimed)
            if reclaimed == 0:
                # the 60s tick fires forever; empty sweeps would churn
                # real allocation traces out of the bounded ring
                tr.discard()
        return reclaimed

    def _gc_sweep(self) -> int:
        reclaimed = 0
        storage = self._config.storage
        operator = self._config.operator
        for key, info in list(storage.items()):
            if not self._pod_is_gone(info.namespace, info.name):
                continue
            with get_tracer().span(
                "reclaim_pod", pod=f"{info.namespace}/{info.name}"
            ) as sp:
                get_tracer().annotate_pod(f"{info.namespace}/{info.name}")
                hashes = []
                for container, by_resource in info.allocations.items():
                    owner = PodContainer(info.namespace, info.name, container)
                    for record in by_resource.values():
                        hashes.append(record.device.hash)
                        for link_id in record.created_node_ids:
                            try:
                                operator.delete(link_id)
                            except Exception:  # noqa: BLE001
                                logger.warning(
                                    "GC: failed deleting node %s", link_id
                                )
                        # owner passed so a sibling that outlives this
                        # unlink (iteration order) never names the freed
                        # devices
                        self.core.remove_alloc_spec(record.device.hash, owner)
                        if self._config.crd_recorder is not None:
                            self._config.crd_recorder.record_released(
                                record.device.hash
                            )
                sp.set(hashes=hashes)
                storage.delete(info.namespace, info.name)
                timeline = getattr(self._config, "timeline", None)
                if timeline is not None:
                    timeline.emit(
                        tl.KIND_POD_RECLAIMED,
                        keys={"pod": key, "hash": hashes[0] if hashes else ""},
                        source="gc", hashes=hashes,
                    )
            reclaimed += 1
            events = self._config.events
            if events is not None:
                # The pod no longer exists, so the event lands on this Node.
                events.node_event(
                    ReasonReclaimed,
                    f"reclaimed TPU allocation(s) of deleted pod {key}",
                )
            logger.info("GC: reclaimed %s", key)
        metrics = self._config.metrics
        if metrics is not None:
            if reclaimed:
                metrics.gc_reclaimed.inc(reclaimed)
            metrics.bound_allocations.set(storage.count())
        return reclaimed

    def gc(self, gc_queue: "queue.Queue", stop: threading.Event) -> None:
        """Wake on pod-delete events, else every GC_PERIOD_S."""
        while not stop.is_set():
            try:
                gc_queue.get(timeout=GC_PERIOD_S)
            except queue.Empty:
                pass
            if stop.is_set():
                return
            try:
                self.gc_once()
            except Exception:  # noqa: BLE001
                logger.exception("GC pass failed")

    def start_gc(self, gc_queue: "queue.Queue", stop: threading.Event) -> threading.Thread:
        t = threading.Thread(
            target=self.gc, args=(gc_queue, stop), daemon=True, name="tpu-gc"
        )
        t.start()
        return t
