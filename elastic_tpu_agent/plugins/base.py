"""Device-plugin server lifecycle: serve, probe, register, re-register.

Capability parity with the reference's ``pkg/plugins/base.go``
(SURVEY.md §1 L3, §3.4): one gRPC server per extended resource on a unix
socket under the kubelet device-plugins dir; after serving, dial-probe the
socket, register with kubelet.sock, then watch for kubelet restarts
(socket re-creation) and run the whole restart loop again. Any error →
back off and retry (reference: ``goto restart``, base.go:117-127).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent import futures
from dataclasses import dataclass, field
from typing import Callable, Optional

import grpc

from .. import faults, rpc
from ..common import FileWatcher

logger = logging.getLogger(__name__)


@dataclass
class PluginConfig:
    """Wiring config for the plugin layer (reference GPUPluginConfig,
    base.go:32-43)."""

    node_name: str = ""
    device_plugin_dir: str = rpc.DEVICE_PLUGIN_DIR
    pod_resources_socket: str = rpc.POD_RESOURCES_SOCKET
    restart_backoff_s: float = 1.0
    # gRPC worker threads per resource server. Kubelet issues concurrent
    # Allocate/PreStartContainer pairs (one per container) and a node
    # restart re-binds every pod at once; size this to the expected bind
    # burst (CLI: --dp-pool-size). Surfaced via the plugin's bind_stats()
    # on /debug/allocations and in the doctor bundle.
    grpc_pool_size: int = 8
    # seams injected by the manager:
    operator: object = None
    sitter: object = None
    storage: object = None
    locator_factory: Optional[Callable[[str], object]] = None
    metrics: object = None
    # Optional ElasticTPU CRD publisher (crd_recorder.CRDRecorder); the
    # plugin treats it as fire-and-forget observability.
    crd_recorder: object = None
    # Optional k8s Event emitter (kube.events.EventRecorder); same
    # fire-and-forget contract.
    events: object = None
    # Optional UtilizationSampler (sampler.py): its chip-health view is
    # folded into the health poll so a chip whose telemetry is failing
    # degrades to Unhealthy in the ListAndWatch stream.
    sampler: object = None
    # Optional SliceRegistry (slices/registry.py): when set, PreStart
    # stamps the registry-derived slice env (deterministic worker
    # ordering, reform-aware world, slice name + epoch) instead of the
    # bare annotation-order slice_env_for_pod derivation.
    slice_registry: object = None
    # Optional lifecycle Timeline (timeline.py): bind transaction
    # phases, health/cordon flips and GC reclaims are journaled through
    # it. Fire-and-forget like every observability seam here.
    timeline: object = None
    extra: dict = field(default_factory=dict)


class DevicePluginServer:
    """Registration lifecycle for ONE extended resource.

    States per iteration: serve socket -> probe -> register -> watch.
    A kubelet restart (kubelet.sock re-created) or any serve/register error
    tears the server down and re-enters the loop after a short backoff.
    """

    def __init__(
        self,
        servicer: rpc.DevicePluginServicer,
        resource_name: str,
        endpoint: str,
        config: PluginConfig,
        pre_start_required: bool = True,
    ) -> None:
        self._servicer = servicer
        self._resource = resource_name
        self._endpoint = endpoint  # socket file name, e.g. elastic-tpushare-core.sock
        self._config = config
        self._pre_start_required = pre_start_required
        self._server: Optional[grpc.Server] = None
        self._thread: Optional[threading.Thread] = None
        self.registrations = 0  # observability: how many times we registered

    # -- single lifecycle steps ----------------------------------------------

    @property
    def resource_name(self) -> str:
        return self._resource

    @property
    def socket_path(self) -> str:
        return os.path.join(self._config.device_plugin_dir, self._endpoint)

    @property
    def kubelet_socket(self) -> str:
        return os.path.join(
            self._config.device_plugin_dir, rpc.KUBELET_SOCKET_NAME
        )

    def _serve(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)  # stale socket from a previous run
        # Named threads: a stack dump of a wedged bind burst must say
        # WHICH resource's pool it sits in.
        server = grpc.server(futures.ThreadPoolExecutor(
            max_workers=max(1, self._config.grpc_pool_size),
            thread_name_prefix=f"dp-grpc-{self._resource}",
        ))
        rpc.add_device_plugin_servicer(server, self._servicer)
        server.add_insecure_port(rpc.unix_target(self.socket_path))
        server.start()
        self._server = server

    def _stop_server(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None

    def _probe(self, timeout_s: float = 5.0) -> None:
        rpc.dial(self.socket_path, timeout_s).close()

    def _register(self) -> None:
        rpc.RegistrationClient(self.kubelet_socket).register(
            endpoint=self._endpoint,
            resource_name=self._resource,
            pre_start_required=self._pre_start_required,
        )
        self.registrations += 1
        logger.info(
            "registered %s via %s with kubelet", self._resource, self._endpoint
        )

    # -- the loop -------------------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        """Blocking serve/register/watch loop until ``stop`` is set.

        The finally matters under supervision: an exception escaping the
        loop (e.g. the watch phase) would otherwise leave self._server
        live while the supervisor re-enters run() and serves a SECOND
        gRPC server + thread pool on the re-created socket."""
        try:
            self._run_loop(stop)
        finally:
            self._stop_server()

    def _run_loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                # failpoint: raise-kind faults exercise the internal
                # serve/register retry below; die-thread kills the loop so
                # the supervisor's restart of a CRITICAL subsystem is
                # testable end to end.
                faults.fire("dp.run")
                self._serve()
                self._probe()
                # Snapshot the kubelet socket BEFORE registering: a kubelet
                # restart racing the Register call must still be detected,
                # else this server never re-registers.
                watcher = FileWatcher(self.kubelet_socket)
                self._register()
            except Exception as e:  # noqa: BLE001
                logger.warning(
                    "%s: serve/register failed (%s); retrying", self._resource, e
                )
                self._stop_server()
                stop.wait(self._config.restart_backoff_s)
                continue
            # Registered: watch for kubelet restarts.
            restarted = False
            while not stop.is_set():
                if watcher.changed():
                    logger.info(
                        "%s: kubelet socket changed; re-registering",
                        self._resource,
                    )
                    restarted = True
                    break
                stop.wait(1.0)
            self._stop_server()
            if restarted:
                stop.wait(self._config.restart_backoff_s)

    def start(self, stop: threading.Event) -> None:
        self._thread = threading.Thread(
            target=self.run, args=(stop,), daemon=True,
            name=f"dp-server-{self._resource}",
        )
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)


def plugin_factory(kind: str, config: PluginConfig):
    """Build the plugin bundle for ``kind`` (reference PluginFactory,
    base.go:52-62; its unsupported default "qgpu" defect is not replicated —
    unknown kinds fail loudly)."""
    from .tpushare import TPUSharePlugin

    if kind in ("tpushare", "gpushare"):
        return TPUSharePlugin(config)
    raise ValueError(f"unsupported plugin kind {kind!r} (want 'tpushare')")
