"""Goodput ledger: fleet-wide downtime attribution from the causal timeline.

The agent now *causes* most of a workload's non-productive time — drains,
slice reforms, QoS throttles/evictions, migrations, crash-replays — and
the durable timeline (timeline.py) plus the checkpoint handshake
(migration.py) record every transition. This module rolls those records
up into the number an operator actually runs a fleet by: **goodput**,
and seconds of downtime attributed to a cause. The edge-accelerator
characterization work (PAPERS.md) argues per-container productivity must
be *measured*, not assumed; FlexNPU makes the same point for co-location
interference — the repartition loop grows/shrinks quotas with no ledger
of what that cost the borrower or saved the donor. This is that ledger.

Semantics — for every pod the agent ever bound, wall time partitions
gap-free into exactly one of seven states:

==============  ==============================================================
state           meaning (and the journal evidence that claims it)
==============  ==============================================================
productive      no claim: the pod held its grant and nothing the agent did
                was in the way (refine with the flight-recorder sidecar's
                tokens/s — sampler.py — to see what it *achieved*)
queued          bind in flight: ``bind_intent`` .. ``bind_commit``
checkpointing   a drain/throttle/reform signal told the workload to save:
                signal .. the checkpoint ack (``migration`` action=recorded,
                or the ack sidecar's timestamp for reforms)
migrating       work moving between generations: source side from the
                consumed ack to the early reclaim; destination side from
                admission to the VERIFIED resume (action=completed)
draining        a drain signal is standing and the resident never acked —
                the un-saved tail the drain deadline exists for
throttled       QoS enforcement: ``throttle`` action=throttle .. unthrottle,
                and the evict window up to the reclaim
unattributed    time the ledger cannot explain: agent crash windows (the
                gap a mid-lifetime ``agent_started`` reveals), attributed
                to the boot event when one is visible
==============  ==============================================================

**Conservation invariant**: per pod, the intervals partition the pod's
known lifetime — they sum to it exactly, never overlap, and every
non-productive interval (unattributed excepted) carries a cause id
``(node, seq)`` resolvable in the timeline journal. The replay is a pure
function of the journal, so the invariant is property-testable with a
ManualClock and survives agent restarts for free; what does NOT survive
the ring trim — lifetime start anchors for long-lived pods whose bind
events were evicted — is journaled in ``agent_state`` (key ``goodput``)
and resumed like drain/migration state.

Surfaced four ways: bounded ``elastic_tpu_goodput_ratio{pod}`` + fleet
``elastic_tpu_downtime_seconds_total{cause}`` metrics, the loopback
``/debug/goodput`` endpoint, a schema-validated ``goodput`` doctor-bundle
block readable from a DEAD agent's db (``node-doctor goodput``), and
``FleetAggregator.fleet_goodput()`` so the bench legs report fleet
goodput %% and downtime-by-cause alongside their latency numbers.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from .common import SYSTEM_CLOCK
from . import timeline as tl

logger = logging.getLogger(__name__)

DEFAULT_PERIOD_S = 10.0
_STATE_KEY = "goodput"

# -- the seven states ---------------------------------------------------------

PRODUCTIVE = "productive"
CHECKPOINTING = "checkpointing"
MIGRATING = "migrating"
DRAINING = "draining"
THROTTLED = "throttled"
QUEUED = "queued"
UNATTRIBUTED = "unattributed"

STATES = (
    PRODUCTIVE, CHECKPOINTING, MIGRATING, DRAINING, THROTTLED, QUEUED,
    UNATTRIBUTED,
)

# When claims overlap (a drain signal lands on an already-throttled pod,
# an ack arrives mid-drain), the instant belongs to the HIGHEST-priority
# claim — each wall-clock second is counted exactly once. Productive is
# the absence of any claim.
_PRIORITY = {
    QUEUED: 60,        # nothing else can be true before the bind commits
    MIGRATING: 50,     # the handshake is the most specific explanation
    CHECKPOINTING: 40,
    THROTTLED: 30,
    DRAINING: 20,
    UNATTRIBUTED: 10,  # only claims what nothing else explains
}

# -- downtime cause categories (the {cause} label vocabulary) -----------------

CAUSE_MAINTENANCE = "maintenance_drain"
CAUSE_PREEMPTION = "preemption"
CAUSE_OPERATOR_DRAIN = "operator_drain"
CAUSE_QOS_THROTTLE = "qos_throttle"
CAUSE_QOS_EVICT = "qos_evict"
CAUSE_MIGRATION = "migration"
# Pre-copy migrations split the old blanket "migration" price: the
# streaming rounds run WHILE the workload trains (priced productive,
# surfaced as precopy_s on the migrations list), and only the residual
# pause→final-delta→restore window is downtime, under its own label.
CAUSE_MIGRATION_PRECOPY = "migration_precopy"
CAUSE_MIGRATION_CUTOVER = "migration_cutover"
CAUSE_SLICE_REFORM = "slice_reform"
CAUSE_AGENT_RESTART = "agent_restart"
CAUSE_BIND_QUEUE = "bind_queue"
CAUSE_UNATTRIBUTED = "unattributed"

CAUSES = (
    CAUSE_MAINTENANCE, CAUSE_PREEMPTION, CAUSE_OPERATOR_DRAIN,
    CAUSE_QOS_THROTTLE, CAUSE_QOS_EVICT, CAUSE_MIGRATION,
    CAUSE_MIGRATION_PRECOPY, CAUSE_MIGRATION_CUTOVER,
    CAUSE_SLICE_REFORM, CAUSE_AGENT_RESTART, CAUSE_BIND_QUEUE,
    CAUSE_UNATTRIBUTED,
)


def _drain_category(trigger: str) -> str:
    trigger = str(trigger or "")
    if trigger.startswith("maintenance"):
        return CAUSE_MAINTENANCE
    if trigger.startswith("preemption"):
        return CAUSE_PREEMPTION
    return CAUSE_OPERATOR_DRAIN


def cause_category(event: Optional[dict]) -> str:
    """The {cause} label a claim's triggering journal event rolls up
    under — derived from the event, never free-typed, so the metric's
    label set stays a closed vocabulary (CAUSES)."""
    if event is None:
        return CAUSE_UNATTRIBUTED
    kind = event.get("kind")
    attrs = event.get("attrs", {}) or {}
    if kind == tl.KIND_DRAIN_TRANSITION:
        return _drain_category(attrs.get("trigger"))
    if kind == tl.KIND_THROTTLE:
        return (
            CAUSE_QOS_EVICT if attrs.get("action") == "evict"
            else CAUSE_QOS_THROTTLE
        )
    if kind == tl.KIND_MIGRATION:
        if attrs.get("action") in ("precopy_round", "cutover_signaled"):
            return CAUSE_MIGRATION_PRECOPY
        if attrs.get("action") == "cutover":
            # the replay-synthesized anchor for the pause→final-delta→
            # ack residual of a pre-copy migration; the surrounding
            # MIGRATING window stays plain "migration"
            return CAUSE_MIGRATION_CUTOVER
        return CAUSE_MIGRATION
    if kind == tl.KIND_SLICE_REFORMED:
        return CAUSE_SLICE_REFORM
    if kind == tl.KIND_AGENT_STARTED:
        return CAUSE_AGENT_RESTART
    if kind in (tl.KIND_BIND_INTENT, tl.KIND_BIND_COMMIT,
                tl.KIND_BIND_REPLAY):
        return CAUSE_BIND_QUEUE
    return CAUSE_UNATTRIBUTED


def _cause_ref(event: Optional[dict]) -> Optional[dict]:
    """The resolvable id a non-productive interval carries: the
    triggering event's (node, seq) plus enough context to read it
    without a second lookup."""
    if event is None:
        return None
    return {
        "node": event.get("keys", {}).get("node", ""),
        "seq": event.get("seq"),
        "kind": event.get("kind"),
        "category": cause_category(event),
    }


# -- replay internals ---------------------------------------------------------


class _Claim:
    """One open-or-closed assertion that [start, end) of a pod's life
    was in ``state`` because of ``cause`` (a journal event)."""

    __slots__ = ("state", "start", "end", "cause")

    def __init__(self, state, start, cause, end=None) -> None:
        self.state = state
        self.start = start
        self.end = end  # None = still open
        self.cause = cause


class _Life:
    """One incarnation of a pod key: bind (or anchor) to reclaim."""

    __slots__ = ("start", "end", "committed", "claims", "queue_cause",
                 "slices", "anchored", "precopy_s")

    def __init__(self, start, committed, queue_cause=None,
                 anchored=False) -> None:
        self.start = start
        self.end: Optional[float] = None
        self.committed = committed
        self.claims: List[_Claim] = []
        self.queue_cause = queue_cause
        self.slices: set = set()
        self.anchored = anchored
        # seconds of pre-copy streaming priced PRODUCTIVE (cutover
        # re-anchoring; see the KIND_MIGRATION "recorded" branch)
        self.precopy_s = 0.0

    def open_claim(self, state, start, cause) -> _Claim:
        claim = _Claim(state, start, cause)
        self.claims.append(claim)
        return claim

    def open_of(self, state) -> Optional[_Claim]:
        for claim in self.claims:
            if claim.state == state and claim.end is None:
                return claim
        return None

    def close_state(self, state, ts) -> None:
        for claim in self.claims:
            if claim.state == state and claim.end is None:
                claim.end = ts


def _partition(life: _Life, asof: float) -> List[dict]:
    """Sweep one life's claims into a gap-free, non-overlapping interval
    list — conservation holds by construction: every elementary segment
    between two boundary points gets exactly one state (highest-priority
    active claim, else productive)."""
    start = life.start
    end = life.end if life.end is not None else asof
    if end < start:
        end = start
    claims = []
    for claim in life.claims:
        s = max(start, claim.start)
        e = min(end, claim.end if claim.end is not None else end)
        if e > s:
            claims.append((s, e, claim))
    points = {start, end}
    for s, e, _ in claims:
        points.add(s)
        points.add(e)
    bounds = sorted(points)
    out: List[dict] = []
    for a, b in zip(bounds, bounds[1:]):
        best = None
        for s, e, claim in claims:
            if s <= a and b <= e:
                if best is None or (
                    _PRIORITY[claim.state] > _PRIORITY[best.state]
                ):
                    best = claim
        state = best.state if best is not None else PRODUCTIVE
        cause = _cause_ref(best.cause) if best is not None else None
        if out and out[-1]["state"] == state and out[-1]["cause"] == cause:
            out[-1]["end"] = b  # merge adjacent same-state segments
        else:
            out.append({
                "state": state, "start": a, "end": b, "cause": cause,
            })
    return out


def replay_goodput(
    rows: List[dict],
    asof: float,
    anchors: Optional[dict] = None,
    acks: Optional[Dict[str, float]] = None,
) -> dict:
    """Pure replay: journal rows (one node's, or a ts-merged fleet view
    — every row carries its node in keys) -> per-pod goodput ledgers.

    ``anchors`` is the agent_state-journaled {"pods": {pod: {"start":
    ts}}, "last_alive_ts": ts} block: lifetime starts for pods whose
    bind events the ring has evicted, plus the heartbeat that bounds a
    crash window when the journal went quiet before the crash. ``acks``
    is {pod: latest checkpoint-ack ts} (the migration coordinator's
    view, or read from the ack sidecars) — it closes reform-triggered
    checkpointing claims, the one transition with no journal event of
    its own.
    """
    anchors = anchors or {}
    acks = acks or {}
    by_node: Dict[str, List[dict]] = {}
    for row in rows:
        by_node.setdefault(row.get("keys", {}).get("node", ""), []).append(
            row
        )
    if not by_node and anchors.get("pods"):
        by_node[""] = []
    pods_out: Dict[str, dict] = {}
    migrations: List[dict] = []
    # Anchors belong to ONE node's ledger (they ride its agent_state);
    # in a merged multi-node replay they seed only their own node.
    anchor_node = anchors.get("node")
    for node, node_rows in by_node.items():
        lives: Dict[str, _Life] = {}
        done: Dict[str, List[_Life]] = {}
        seed_anchors = (
            len(by_node) == 1
            or (anchor_node is not None and node == anchor_node)
        )
        # Anchored pods pre-seed their lives: the ring may have trimmed
        # their bind events, but the ledger journaled where they began.
        for pod, anchor in (
            (anchors.get("pods") or {}) if seed_anchors else {}
        ).items():
            try:
                lives[pod] = _Life(
                    float(anchor["start"]), True, anchored=True
                )
            except (KeyError, TypeError, ValueError):
                continue
        drain_open: Optional[dict] = None  # the standing drain event
        last_alive = anchors.get("last_alive_ts")
        prev_ts: Optional[float] = None

        def _end_life(pod: str, ts: float) -> None:
            life = lives.pop(pod, None)
            if life is None:
                return
            life.end = ts
            for claim in life.claims:
                if claim.end is None:
                    claim.end = ts
            done.setdefault(pod, []).append(life)

        for ev in node_rows:
            ts = ev.get("ts", 0.0)
            kind = ev.get("kind")
            keys = ev.get("keys", {}) or {}
            attrs = ev.get("attrs", {}) or {}
            pod = keys.get("pod")
            if kind == tl.KIND_BIND_INTENT:
                if pod:
                    prior = lives.get(pod)
                    if prior is not None and prior.anchored:
                        # The ring still holds this incarnation's bind
                        # events (or the anchor is stale across a
                        # trimmed reclaim): the real events supersede
                        # the journaled anchor, or tick N would lose
                        # the queued window tick 1 priced.
                        if ts > prior.start:
                            _end_life(pod, ts)
                        else:
                            del lives[pod]
                    if pod not in lives:
                        lives[pod] = _Life(ts, False, queue_cause=ev)
                    if keys.get("slice"):
                        lives[pod].slices.add(keys["slice"])
            elif kind in (tl.KIND_BIND_COMMIT, tl.KIND_BIND_REPLAY):
                if not pod:
                    pass
                elif pod not in lives:
                    life = lives[pod] = _Life(ts, True)
                    if drain_open is not None:
                        life.open_claim(DRAINING, ts, drain_open)
                else:
                    life = lives[pod]
                    if not life.committed:
                        life.committed = True
                        life.open_claim(
                            QUEUED, life.start, life.queue_cause or ev
                        ).end = ts
                        if drain_open is not None:
                            life.open_claim(DRAINING, ts, drain_open)
                if pod and keys.get("slice"):
                    lives[pod].slices.add(keys["slice"])
            elif kind == tl.KIND_BIND_ROLLBACK:
                if pod in lives and not lives[pod].committed:
                    life = lives[pod]
                    life.open_claim(
                        QUEUED, life.start, life.queue_cause or ev
                    ).end = ts
                    _end_life(pod, ts)
            elif kind == tl.KIND_POD_RECLAIMED:
                if pod:
                    _end_life(pod, ts)
            elif kind == tl.KIND_RECONCILE_REPAIR:
                if pod and attrs.get("class") == "reclaimed_pod":
                    _end_life(pod, ts)
            elif kind == tl.KIND_DRAIN_TRANSITION:
                state = attrs.get("state")
                if state == "draining":
                    drain_open = ev
                    for life in lives.values():
                        if life.committed and life.open_of(DRAINING) is None:
                            life.open_claim(DRAINING, ts, ev)
                elif state in ("active", "drained", "reclaimed"):
                    # cancel, or every resident already left: the signal
                    # no longer claims anyone still alive (checkpointing
                    # claims need no closing here — they are always
                    # created with their ack-derived end already set)
                    for life in lives.values():
                        life.close_state(DRAINING, ts)
                    drain_open = None
            elif kind == tl.KIND_THROTTLE:
                action = attrs.get("action")
                if pod in lives:
                    life = lives[pod]
                    if action == "throttle":
                        if life.open_of(THROTTLED) is None:
                            life.open_claim(THROTTLED, ts, ev)
                    elif action == "unthrottle":
                        life.close_state(THROTTLED, ts)
                    elif action == "evict":
                        life.close_state(THROTTLED, ts)
                        # evict window: clamp stays until the reclaim
                        life.open_claim(THROTTLED, ts, ev)
            elif kind == tl.KIND_MIGRATION:
                action = attrs.get("action")
                if action == "recorded" and pod in lives:
                    life = lives[pod]
                    signal = (
                        life.open_of(DRAINING) or life.open_of(THROTTLED)
                    )
                    if signal is not None and ts > signal.start:
                        # the checkpoint the signal asked for: signal ..
                        # ack, attributed to the TRIGGER (maintenance,
                        # preemption, throttle), not to the handshake
                        ck_start, ck_cause = signal.start, signal.cause
                        cut_ts = attrs.get("cutover_ts")
                        if (
                            attrs.get("mode") == "precopy"
                            and isinstance(cut_ts, (int, float))
                        ):
                            # pre-copy streamed WHILE training: the
                            # window before cutover stays PRODUCTIVE
                            # (the drain claim re-anchors at cutover);
                            # only the residual pause→final-delta→ack
                            # is downtime, under migration_cutover
                            cut = min(
                                max(float(cut_ts), signal.start), ts
                            )
                            life.precopy_s += cut - signal.start
                            signal.start = cut
                            ck_start = cut
                            ck_cause = dict(
                                ev, attrs={**attrs, "action": "cutover"}
                            )
                        life.open_claim(
                            CHECKPOINTING, ck_start, ck_cause
                        ).end = ts
                    if life.open_of(MIGRATING) is None:
                        life.open_claim(MIGRATING, ts, ev)
                elif action == "early_reclaim" and pod:
                    if pod in lives and lives[pod].open_of(MIGRATING) is None:
                        lives[pod].open_claim(MIGRATING, ts, ev)
                    _end_life(pod, ts)
                elif action == "restore_stamped" and pod in lives:
                    life = lives[pod]
                    if life.open_of(MIGRATING) is None:
                        # the whole admission-to-resume window is the
                        # migration's: the replacement was restoring
                        life.open_claim(MIGRATING, life.start, ev)
                elif action == "completed" and pod in lives:
                    lives[pod].close_state(MIGRATING, ts)
                    migrations.append({
                        "pod": pod,
                        "node": node,
                        "completed_ts": ts,
                        "source_node": attrs.get("source_node"),
                        "coordinator_downtime_s": attrs.get("downtime_s"),
                        "step": attrs.get("step"),
                        "mode": attrs.get("mode", "full"),
                        "precopy": attrs.get("precopy"),
                    })
            elif kind == tl.KIND_SLICE_REFORMED:
                if pod in lives:
                    life = lives[pod]
                    if keys.get("slice"):
                        life.slices.add(keys["slice"])
                    ack_ts = acks.get(pod)
                    if ack_ts is not None and ack_ts > ts:
                        life.open_claim(CHECKPOINTING, ts, ev).end = min(
                            ack_ts, asof
                        )
            elif kind == tl.KIND_AGENT_STARTED:
                if prev_ts is not None:
                    gap_start = prev_ts
                    if (
                        isinstance(last_alive, (int, float))
                        and prev_ts < last_alive < ts
                    ):
                        gap_start = float(last_alive)
                    for life in lives.values():
                        if life.committed and gap_start < ts:
                            life.open_claim(
                                UNATTRIBUTED, max(gap_start, life.start),
                                ev,
                            ).end = ts
            prev_ts = ts
        # Close the books at asof.
        for pod, life in list(lives.items()):
            done.setdefault(pod, []).append(life)
        for pod, pod_lives in done.items():
            entry = pods_out.setdefault(pod, {
                "node": node,
                "intervals": [],
                "states": {s: 0.0 for s in STATES},
                "lifetime_s": 0.0,
                "live": False,
                "live_start": None,
                "slices": set(),
                "anchored": False,
                "precopy_s": 0.0,
            })
            for life in pod_lives:
                intervals = _partition(life, asof)
                entry["intervals"].extend(intervals)
                for itv in intervals:
                    entry["states"][itv["state"]] += (
                        itv["end"] - itv["start"]
                    )
                end = life.end if life.end is not None else asof
                entry["lifetime_s"] += max(0.0, end - life.start)
                if life.end is None:
                    entry["live"] = True
                    entry["live_start"] = life.start
                entry["slices"] |= life.slices
                entry["anchored"] = entry["anchored"] or life.anchored
                entry["precopy_s"] += life.precopy_s
    downtime: Dict[str, float] = {}
    for pod, entry in pods_out.items():
        entry["slices"] = sorted(entry["slices"])
        entry["states"] = {
            s: round(v, 6) for s, v in entry["states"].items()
        }
        lifetime = entry["lifetime_s"]
        entry["lifetime_s"] = round(lifetime, 6)
        entry["precopy_s"] = round(entry["precopy_s"], 6)
        entry["goodput_ratio"] = (
            round(entry["states"][PRODUCTIVE] / lifetime, 6)
            if lifetime > 0 else None
        )
        for itv in entry["intervals"]:
            if itv["state"] == PRODUCTIVE:
                continue
            cat = (
                itv["cause"]["category"] if itv["cause"]
                else CAUSE_UNATTRIBUTED
            )
            downtime[cat] = (
                downtime.get(cat, 0.0) + itv["end"] - itv["start"]
            )
    return {
        "asof": asof,
        "pods": pods_out,
        "downtime_by_cause": {
            k: round(v, 6) for k, v in sorted(downtime.items())
        },
        "migrations": migrations,
        "events_replayed": len(rows),
    }


def verify_conservation(
    result: dict, rows: Optional[List[dict]] = None
) -> List[str]:
    """The invariant the property tests and the goodput smoke pin;
    returns problems (empty = conservation holds):

    - per pod, interval durations sum to the pod's lifetime (gap-free);
    - intervals never overlap (each is strictly after the previous);
    - every non-productive interval except ``unattributed`` carries a
      cause, and when ``rows`` is given every cause (node, seq)
      resolves to a surviving journal event.
    """
    problems: List[str] = []
    known = None
    if rows is not None:
        # the same (node, seq) identity timeline.event_by_ref resolves —
        # set-built here because this check runs over EVERY interval
        known = {
            (e.get("keys", {}).get("node", ""), e.get("seq"))
            for e in rows
        }
    for pod, entry in result.get("pods", {}).items():
        covered = 0.0
        prev_end = None
        for itv in entry["intervals"]:
            if itv["end"] < itv["start"]:
                problems.append(
                    f"{pod}: negative interval {itv['start']}..{itv['end']}"
                )
            if prev_end is not None and itv["start"] < prev_end - 1e-9:
                problems.append(
                    f"{pod}: interval overlap at {itv['start']} "
                    f"(previous ends {prev_end})"
                )
            prev_end = max(prev_end or itv["end"], itv["end"])
            covered += itv["end"] - itv["start"]
            if itv["state"] in (PRODUCTIVE, UNATTRIBUTED):
                continue
            cause = itv.get("cause")
            if cause is None:
                problems.append(
                    f"{pod}: {itv['state']} interval at {itv['start']} "
                    "carries no cause"
                )
            elif known is not None and (
                (cause.get("node", ""), cause.get("seq")) not in known
            ):
                problems.append(
                    f"{pod}: cause seq {cause.get('seq')} on "
                    f"{cause.get('node')!r} does not resolve in the "
                    "journal"
                )
        if abs(covered - entry["lifetime_s"]) > 1e-6:
            problems.append(
                f"{pod}: intervals cover {covered:.6f}s of a "
                f"{entry['lifetime_s']:.6f}s lifetime"
            )
    return problems


# -- the agent-side ledger ----------------------------------------------------


class GoodputLedger:
    """Supervised (DEGRADED) replay loop over the node's own journal.

    Each tick re-derives the partition from the durable timeline — the
    journal is the single source of truth, so a restarted agent's first
    tick reproduces the same ledger — then journals its anchors
    (lifetime starts + a last-alive heartbeat) into ``agent_state`` so
    eviction and crashes cannot orphan long-lived pods' lifetimes, and
    exports ``elastic_tpu_goodput_ratio{pod}`` plus
    ``elastic_tpu_downtime_seconds_total{cause}``.
    """

    def __init__(
        self,
        storage,
        node_name: str = "",
        metrics=None,
        migration=None,
        period_s: float = DEFAULT_PERIOD_S,
        clock=None,
        lag_tracker=None,
    ) -> None:
        self._storage = storage
        self._node = node_name
        self._metrics = metrics
        self._migration = migration
        self.period_s = period_s
        self._clock = clock if clock is not None else SYSTEM_CLOCK
        self._lock = threading.Lock()
        self._anchors: dict = {}
        self._last: Optional[dict] = None
        self._exported_pods: set = set()
        self.ticks_total = 0
        # DetectionLagTracker (latency.py): the ledger's event source is
        # the journal itself — lag = newest row's ts to the tick that
        # replayed it, watermarked so each row generation counts once.
        self._lag = lag_tracker
        self._row_watermark = float("-inf")

    # -- restart durability ---------------------------------------------------

    def resume(self) -> None:
        """Reload journaled anchors (boot path, before the first tick):
        pods whose bind events the ring already trimmed keep the
        lifetime starts the previous process learned."""
        try:
            state = self._storage.load_state(_STATE_KEY)
        except Exception:  # noqa: BLE001 - observability, never fatal
            logger.exception("goodput: anchor resume failed")
            return
        if isinstance(state, dict):
            with self._lock:
                self._anchors = state

    def _journal_anchors(self, result: dict, asof: float) -> None:
        anchors = {
            "node": self._node,
            "pods": {},
            "last_alive_ts": asof,
        }
        for pod, entry in result["pods"].items():
            if not entry["live"]:
                continue
            start = entry.get("live_start")
            if start is not None:
                anchors["pods"][pod] = {"start": start}
        with self._lock:
            self._anchors = anchors
        try:
            self._storage.save_state(_STATE_KEY, anchors)
        except Exception:  # noqa: BLE001 - the ledger must never wedge
            logger.warning("goodput: anchor journal write failed")

    # -- one tick -------------------------------------------------------------

    def tick(self) -> dict:
        asof = self._clock.time()
        rows = self._storage.timeline_rows()
        if self._lag is not None and rows:
            try:
                newest = max(float(e.get("ts", 0.0)) for e in rows)
                if newest > self._row_watermark:
                    self._row_watermark = newest
                    self._lag.handled(
                        "goodput", "journal_replay", origin_ts=newest
                    )
            except Exception:  # noqa: BLE001 - accounting never breaks
                pass
        acks: Dict[str, float] = {}
        if self._migration is not None:
            try:
                acks = dict(self._migration.acked_pods())
            except Exception:  # noqa: BLE001 - acks only refine reforms
                acks = {}
        with self._lock:
            anchors = dict(self._anchors)
        result = replay_goodput(rows, asof, anchors=anchors, acks=acks)
        self._journal_anchors(result, asof)
        self._export(result)
        with self._lock:
            self._last = result
            self.ticks_total += 1
        return result

    def _export(self, result: dict) -> None:
        m = self._metrics
        if m is None:
            return
        try:
            live = set()
            for pod, entry in result["pods"].items():
                if entry["goodput_ratio"] is None or not entry["live"]:
                    continue
                live.add(pod)
                if hasattr(m, "goodput_ratio"):
                    m.goodput_ratio.set(entry["goodput_ratio"], pod=pod)
            if hasattr(m, "goodput_ratio"):
                for gone in self._exported_pods - live:
                    m.goodput_ratio.remove(pod=gone)
            self._exported_pods = live
            if hasattr(m, "downtime_seconds"):
                for cause in CAUSES:
                    m.downtime_seconds.labels(cause=cause).set(
                        result["downtime_by_cause"].get(cause, 0.0)
                    )
        except Exception:  # noqa: BLE001 - metrics never break the ledger
            logger.exception("goodput metrics export failed")

    # -- read surfaces --------------------------------------------------------

    def status(
        self, pod: Optional[str] = None, since: Optional[float] = None
    ) -> dict:
        """The ``goodput`` block shared by /debug/goodput, the doctor
        bundle and the fleet aggregator. Computes a fresh replay when no
        tick has run yet (endpoint attached before the loop started)."""
        with self._lock:
            result = self._last
        if result is None:
            try:
                result = self.tick()
            except Exception as e:  # noqa: BLE001 - a read must not raise
                return {
                    "node": self._node, "error": str(e), "pods": {},
                    "downtime_by_cause": {}, "migrations": [],
                    # every caller indexes these; the failed tick must
                    # surface as ITS error, not a KeyError downstream
                    "conservation_problems": [
                        f"ledger tick failed: {e}"
                    ],
                    "ticks_total": self.ticks_total,
                    "anchored_pods": 0,
                }
        payload = select_pods(result, pod=pod, since=since)
        payload["node"] = self._node
        payload["conservation_problems"] = verify_conservation(payload)
        with self._lock:
            payload["ticks_total"] = self.ticks_total
            payload["anchored_pods"] = len(
                (self._anchors.get("pods") or {})
            )
        return payload

    # -- the supervised loop --------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        import random

        while not stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - supervised: log and retick
                logger.exception("goodput tick failed")
            if stop.wait(self.period_s * (0.75 + 0.5 * random.random())):
                return


def select_pods(
    result: dict,
    pod: Optional[str] = None,
    slice_id: Optional[str] = None,
    since: Optional[float] = None,
) -> dict:
    """Filter one replay result to an entity/time window, recomputing
    the downtime rollup over what survives. ``pod`` accepts bare names
    like the trace and timeline filters do; ``since`` keeps pods whose
    lifetime reaches past the bound (their full partition is kept — a
    clipped partition would break conservation)."""
    pods = {}
    for key, entry in result.get("pods", {}).items():
        if pod is not None and key != pod and (
            key.rpartition("/")[2] != pod
        ):
            continue
        if slice_id is not None and slice_id not in entry.get(
            "slices", []
        ):
            continue
        if since is not None:
            last_end = (
                entry["intervals"][-1]["end"] if entry["intervals"]
                else None
            )
            if last_end is None or last_end < since:
                continue
        pods[key] = entry
    downtime: Dict[str, float] = {}
    for entry in pods.values():
        for itv in entry["intervals"]:
            if itv["state"] == PRODUCTIVE:
                continue
            cat = (
                itv["cause"]["category"] if itv["cause"]
                else CAUSE_UNATTRIBUTED
            )
            downtime[cat] = (
                downtime.get(cat, 0.0) + itv["end"] - itv["start"]
            )
    return {
        "asof": result.get("asof"),
        "pods": pods,
        "downtime_by_cause": {
            k: round(v, 6) for k, v in sorted(downtime.items())
        },
        "migrations": [
            m for m in result.get("migrations", [])
            if pod is None or m.get("pod") == pod
            or str(m.get("pod", "")).rpartition("/")[2] == pod
        ],
        "events_replayed": result.get("events_replayed"),
    }


def build_goodput_block(
    storage,
    asof: Optional[float] = None,
    pod: Optional[str] = None,
    slice_id: Optional[str] = None,
    since: Optional[float] = None,
) -> dict:
    """The dead-agent read path (node-doctor, doctor bundle): replay the
    db's journal + journaled anchors with NO live process. ``asof``
    defaults to the ledger's knowledge horizon — the later of the last
    journal event and the last anchor heartbeat — so a dead agent's
    silent hours never count as productive time."""
    rows = storage.timeline_rows()
    try:
        anchors = storage.load_state(_STATE_KEY) or {}
    except Exception:  # noqa: BLE001 - a bundle beats no bundle
        anchors = {}
    if asof is None:
        candidates = [e.get("ts", 0.0) for e in rows]
        if isinstance(anchors.get("last_alive_ts"), (int, float)):
            candidates.append(float(anchors["last_alive_ts"]))
        asof = max(candidates) if candidates else 0.0
    result = replay_goodput(rows, asof, anchors=anchors)
    payload = select_pods(
        result, pod=pod, slice_id=slice_id, since=since
    )
    payload["conservation_problems"] = verify_conservation(payload, rows)
    payload["anchored_pods"] = len((anchors.get("pods") or {}))
    return payload


def validate_goodput_block(block: dict) -> List[str]:
    """Schema check for the ``goodput`` doctor-bundle block (consumed by
    sampler.validate_bundle); returns problems (empty = valid)."""
    problems: List[str] = []
    if not isinstance(block, dict):
        return ["goodput must be an object"]
    for field in ("asof", "pods", "downtime_by_cause"):
        if field not in block:
            problems.append(f"goodput missing {field!r}")
    pods = block.get("pods")
    if not isinstance(pods, dict):
        problems.append("goodput.pods must be an object")
        pods = {}
    for key, entry in pods.items():
        if not isinstance(entry, dict):
            problems.append(f"goodput.pods[{key!r}] must be an object")
            continue
        for field in ("intervals", "states", "lifetime_s",
                      "goodput_ratio", "live"):
            if field not in entry:
                problems.append(
                    f"goodput.pods[{key!r}] missing {field!r}"
                )
        states = entry.get("states")
        if isinstance(states, dict):
            for s in STATES:
                if s not in states:
                    problems.append(
                        f"goodput.pods[{key!r}].states missing {s!r}"
                    )
        for i, itv in enumerate(entry.get("intervals") or []):
            if not isinstance(itv, dict):
                problems.append(
                    f"goodput.pods[{key!r}].intervals[{i}] must be an "
                    "object"
                )
                continue
            if itv.get("state") not in STATES:
                problems.append(
                    f"goodput.pods[{key!r}].intervals[{i}].state "
                    f"{itv.get('state')!r} is not a goodput state"
                )
            for field in ("start", "end"):
                if not isinstance(itv.get(field), (int, float)):
                    problems.append(
                        f"goodput.pods[{key!r}].intervals[{i}].{field} "
                        "must be a number"
                    )
    causes = block.get("downtime_by_cause")
    if not isinstance(causes, dict):
        problems.append("goodput.downtime_by_cause must be an object")
    else:
        for cause, seconds in causes.items():
            if cause not in CAUSES:
                problems.append(
                    f"goodput.downtime_by_cause key {cause!r} is not a "
                    "known cause"
                )
            if not isinstance(seconds, (int, float)):
                problems.append(
                    f"goodput.downtime_by_cause[{cause!r}] must be a "
                    "number"
                )
    return problems
