"""Automatic cross-request prefix cache over the serving block pool.

``register_prefix`` (serving.py) shares a prefix's KV blocks only when
the CALLER names the prefix explicitly. Real traffic doesn't: millions
of requests arrive carrying the same system prompt / few-shot header as
plain tokens, and every admission re-prefills it. This module makes the
sharing automatic: every FULL token block a prefill writes is published
into a cache keyed by a hash chain over the block's tokens (hash_j =
H(hash_{j-1}, tokens of block j) — the vLLM automatic-prefix-caching
shape), and admission walks the chain of the incoming prompt to find
the longest cached block prefix. Those blocks are ``share()``d into the
new request's table (refcounted, copy-free, exactly the explicit-prefix
machinery) and only the tail is prefilled.

Why a hash CHAIN and not per-block hashes: block j's KV entries depend
on every token before it (attention is causal), so a block is reusable
only when its entire token history matches. Chaining the parent digest
into each block's key makes "same hash" mean "same full history" by
construction.

Eviction: the cache holds one refcount on every published block. A
block whose refcount is exactly 1 is held by NOBODY but the cache, and
is reclaimable. Under pool pressure (``BlockAllocator.alloc`` finding
an empty free list) the allocator's reclaim hook asks the cache to
evict least-recently-USED entries — every lookup hit refreshes recency
— until the allocation can proceed. Blocks with refcount > 1 are live
in some request's table (or a registered prefix) and are NEVER touched;
in-flight requests cannot lose cached history mid-decode.

Evicting a mid-chain block makes its descendants unreachable (a lookup
stops at the first miss); they stop being refreshed and age out of the
same LRU order under continued pressure, so stranding is transient by
construction.

Correctness: a hit re-maps the exact K/V bytes the original prefill
wrote — the same forward, not a recompute — so cached-path streams are
bit-identical to uncached ones (pinned in tests/test_prefix_cache.py).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

# Digest size for the chain keys: 16 bytes of blake2b — collision odds
# are negligible at any realistic cache size, and short keys keep the
# OrderedDict cheap at tens of thousands of entries.
_DIGEST_SIZE = 16
_ROOT = b"\x00" * _DIGEST_SIZE


def chain_hashes(tokens, block_size: int) -> List[bytes]:
    """Hash-chain keys for every FULL block of ``tokens``: entry j keys
    the block holding positions [j*bs, (j+1)*bs) AND its entire token
    history (the parent digest is folded in)."""
    arr = np.asarray(tokens, np.int32).reshape(-1)
    out: List[bytes] = []
    prev = _ROOT
    for j in range(len(arr) // block_size):
        h = hashlib.blake2b(prev, digest_size=_DIGEST_SIZE)
        h.update(arr[j * block_size:(j + 1) * block_size].tobytes())
        prev = h.digest()
        out.append(prev)
    return out


class PrefixCache:
    """Block-granular prefix cache over a refcounted BlockAllocator.

    The cache owns ONE reference on each published block (taken via
    ``allocator.share`` at insert, dropped at evict). Request tables
    layer their own refcounts on top, so block lifetime is the max of
    "some request still maps it" and "the cache still remembers it".
    """

    def __init__(
        self,
        allocator,
        block_size: int,
        max_blocks: Optional[int] = None,
    ) -> None:
        self._alloc = allocator
        self.block_size = block_size
        # hard cap on cached blocks (None = bounded only by pool
        # pressure through the allocator's reclaim hook)
        self.max_blocks = max_blocks
        # chain digest -> physical block id; insertion/refresh order IS
        # the LRU order (oldest first)
        self._entries: "OrderedDict[bytes, int]" = OrderedDict()
        self.hits = 0            # admissions that reused >= 1 block
        self.misses = 0          # admissions that reused none
        self.evictions = 0       # blocks dropped (pressure or cap)
        self.hit_tokens = 0      # prompt tokens NOT re-prefilled
        self.inserted_blocks = 0

    # -- lookup -------------------------------------------------------

    def lookup(self, tokens) -> Tuple[List[int], int]:
        """Longest cached block-chain prefix of ``tokens``: returns
        (physical block ids, token count covered). Only full blocks
        participate; the walk stops at the first unknown digest. Every
        hit block's entry is refreshed to most-recently-used. Counters
        are NOT touched here — the caller reports the admission's fate
        through record_admission, so a lookup whose admission then
        fails (no free slot, pool exhausted) can't skew the hit
        rate."""
        blocks: List[int] = []
        for digest in chain_hashes(tokens, self.block_size):
            bid = self._entries.get(digest)
            if bid is None:
                break
            self._entries.move_to_end(digest)
            blocks.append(bid)
        return blocks, len(blocks) * self.block_size

    def record_admission(self, covered_tokens: int) -> None:
        """Count one SUCCESSFUL admission against the cache (its slot
        and blocks are claimed): covered > 0 is a hit."""
        if covered_tokens > 0:
            self.hits += 1
            self.hit_tokens += covered_tokens
        else:
            self.misses += 1

    # -- publish ------------------------------------------------------

    def insert(self, tokens, table_blocks) -> int:
        """Publish the full blocks of ``tokens`` (physical ids in
        ``table_blocks``, logical order) into the cache. Blocks whose
        chain digest is already cached are skipped — the existing entry
        keeps serving (and keeps its recency). Returns the number of
        newly published blocks."""
        new = 0
        digests = chain_hashes(tokens, self.block_size)
        for j, digest in enumerate(digests):
            if digest in self._entries:
                continue
            bid = int(table_blocks[j])
            self._alloc.share(bid)
            self._entries[digest] = bid
            self._entries.move_to_end(digest)
            new += 1
            self.inserted_blocks += 1
        if (
            self.max_blocks is not None
            and len(self._entries) > self.max_blocks
        ):
            # best-effort: entries a live table still maps can't be
            # trimmed now; the next insert (or pressure) retries
            self.reclaim(len(self._entries) - self.max_blocks)
        return new

    # -- eviction -----------------------------------------------------

    def reclaim(self, n_blocks: int = 1) -> int:
        """Pool-pressure hook (BlockAllocator.reclaim): free up to
        ``n_blocks`` pool blocks by evicting LRU entries whose block
        the cache is the SOLE holder of (refcount exactly 1); anything
        a live table or registered prefix still maps is skipped.
        Returns how many were actually freed. One ordered scan per
        call — not per block — so a pressure event over a mostly-live
        cache costs O(cache size) once."""
        freed = 0
        for digest in list(self._entries):
            if freed >= n_blocks:
                break
            bid = self._entries[digest]
            if int(self._alloc._ref[bid]) != 1:
                continue  # live in a request's table — never touched
            del self._entries[digest]
            self._alloc.drop(bid)
            self.evictions += 1
            freed += 1
        return freed

    def clear(self) -> int:
        """Drop every evictable entry (refcount-1 only); returns the
        count. Entries shared with live tables stay until their
        requests release."""
        return self.reclaim(len(self._entries))

    # -- introspection ------------------------------------------------

    @property
    def cached_blocks(self) -> int:
        return len(self._entries)

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {
            "cached_blocks": len(self._entries),
            "max_blocks": self.max_blocks,
            "block_size": self.block_size,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hits / total, 4) if total else None,
            "hit_tokens": self.hit_tokens,
            "evictions": self.evictions,
            "inserted_blocks": self.inserted_blocks,
        }
