from .transformer import (
    ModelConfig,
    forward,
    init_params,
    make_mesh,
    make_train_step,
    param_shardings,
)

__all__ = [
    "ModelConfig",
    "forward",
    "init_params",
    "make_mesh",
    "make_train_step",
    "param_shardings",
]
