from .moe import init_moe_params, moe_mlp, moe_param_shardings
from .transformer import (
    ModelConfig,
    forward,
    forward_with_aux,
    init_params,
    make_mesh,
    make_train_step,
    param_shardings,
)

__all__ = [
    "ModelConfig",
    "forward",
    "forward_with_aux",
    "init_moe_params",
    "init_params",
    "make_mesh",
    "make_train_step",
    "moe_mlp",
    "moe_param_shardings",
    "param_shardings",
]
