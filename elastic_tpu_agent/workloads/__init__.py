from .beam import beam_search
from .generate import KVCache, decode_shardings, generate
from .lora import (
    init_lora_params,
    make_lora_train_step,
    merge_lora,
)
from .moe import init_moe_params, moe_mlp, moe_param_shardings
from .quantize import dequantize_params, quantize_params
from .serving import ServingEngine
from .speculative import SpecStats, speculative_generate
from .streaming import streaming_generate
from .pipeline import (
    make_pipeline_mesh,
    make_pipeline_train_step,
    pipeline_apply,
)
from .transformer import (
    ModelConfig,
    forward,
    forward_with_aux,
    init_params,
    make_mesh,
    make_train_step,
    param_shardings,
)

__all__ = [
    "KVCache",
    "ModelConfig",
    "ServingEngine",
    "SpecStats",
    "TrainCheckpointer",
    "beam_search",
    "decode_shardings",
    "dequantize_params",
    "export_checkpoint",
    "forward",
    "forward_with_aux",
    "generate",
    "init_lora_params",
    "init_moe_params",
    "init_params",
    "load_artifact",
    "make_lora_train_step",
    "merge_lora",
    "make_mesh",
    "make_pipeline_mesh",
    "make_pipeline_train_step",
    "make_train_step",
    "moe_mlp",
    "moe_param_shardings",
    "param_shardings",
    "pipeline_apply",
    "quantize_params",
    "save_artifact",
    "speculative_generate",
    "streaming_generate",
]


def __getattr__(name):
    # Lazy: checkpointing/export pull in orbax, which plain
    # training/bench paths (and images without orbax) must not require.
    if name == "TrainCheckpointer":
        from .checkpointing import TrainCheckpointer

        return TrainCheckpointer
    if name in ("save_artifact", "load_artifact", "export_checkpoint"):
        from . import export

        return getattr(export, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
