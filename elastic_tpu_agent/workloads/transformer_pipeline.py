"""Pipeline-parallel flagship transformer: GPipe and 1F1B schedules.

Puts the real decoder-only LM (transformer.py) — not a toy block — through
the "pp" ppermute pipeline (pipeline.py):

- The layer stack is split into ``pp`` equal stage groups whose weights
  are STACKED with a leading [pp] dim and sharded over the "pp" mesh
  axis; each stage scans its ``n_layers/pp`` local layers.
- Embedding runs before the pipeline region and the LM head after it, as
  plain GSPMD ops (XLA keeps them where their consumers/producers are);
  the pipeline region itself is a shard_map whose only collectives are
  the stage-to-stage ``ppermute`` hops over ICI.
- ``schedule="gpipe"``: the differentiable forward scan from
  pipeline.pipeline_apply; reverse-mode AD derives the backward pipeline
  (all-forward-then-all-backward — activation live set grows with the
  microbatch count m).
- ``schedule="1f1b"``: one-forward-one-backward interleaving written
  with explicit ``jax.vjp`` per tick. Each combined tick performs a
  forward for one microbatch and the backward for an earlier one; stage
  inputs are stashed in a 2·pp-slot ring buffer and the stage forward is
  RECOMPUTED inside the tick's vjp, so the live activation set is
  O(pp) stage-inputs per device instead of GPipe's O(m) — the property
  that lets long microbatch streams train in fixed memory. The loss head
  runs masked on every stage (SPMD traces one program; only the last
  stage's value survives), which costs one head evaluation per tick.

The reference repo has no parallelism code at all (SURVEY.md §2
"Parallelism-strategy inventory: NONE present"); this is the TPU-first
capability build, not a translation.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .attention import auto_flash_config, flash_attention
from .transformer import ModelConfig, _rmsnorm, rope


# -- parameters ---------------------------------------------------------------


def init_pipeline_params(cfg: ModelConfig, key: jax.Array, pp: int) -> Dict:
    """Transformer params with the layer stack stacked [pp, L/pp, ...].

    embed/pos/head stay unstacked (they run outside the pipeline region).
    MoE layers are not supported under pp (dense stages only).
    """
    assert cfg.n_layers % pp == 0, (
        f"n_layers {cfg.n_layers} must divide into pp={pp} stages"
    )
    assert cfg.moe_experts == 0, "MoE + pipeline not supported"
    assert not cfg.is_gqa, (
        "GQA + pipeline not supported: the pipeline stages use fused "
        "wqkv projections (n_kv_heads must equal n_heads)"
    )
    assert cfg.pos in ("learned", "rope"), cfg.pos
    lpp = cfg.n_layers // pp
    init = jax.nn.initializers.normal(0.02)
    keys = jax.random.split(key, 9)

    def dense(k, shape):
        return init(k, shape, jnp.float32)

    out = {
        "embed": dense(keys[0], (cfg.vocab, cfg.d_model)),
        "final_norm_scale": jnp.ones((cfg.d_model,), jnp.float32),
        "lm_head": dense(keys[2], (cfg.d_model, cfg.vocab)),
    }
    if cfg.pos == "learned":
        out["pos_embed"] = dense(keys[1], (cfg.max_seq, cfg.d_model))
    return out | {
        "stages": {
            "ln1_scale": jnp.ones((pp, lpp, cfg.d_model), jnp.float32),
            "wqkv": dense(
                keys[3],
                (pp, lpp, cfg.d_model, 3, cfg.n_heads, cfg.head_dim),
            ),
            "wo": dense(
                keys[4],
                (pp, lpp, cfg.n_heads, cfg.head_dim, cfg.d_model),
            ),
            "ln2_scale": jnp.ones((pp, lpp, cfg.d_model), jnp.float32),
            "w1": dense(keys[5], (pp, lpp, cfg.d_model, cfg.d_ff)),
            "w2": dense(keys[6], (pp, lpp, cfg.d_ff, cfg.d_model)),
        },
    }


def _pipeline_shardings(mesh: Mesh, params_struct: Dict) -> Dict:
    def leaf_shard(path, leaf):
        keys = tuple(str(k) for k in path)
        if "['stages']" in keys:
            return NamedSharding(
                mesh, P("pp", *([None] * (leaf.ndim - 1)))
            )
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(leaf_shard, params_struct)


# -- stage computation --------------------------------------------------------


def _stage_fn(stage_params: Dict, x: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Apply this stage's L/pp transformer layers. x: [mb, s, d]."""
    fc = auto_flash_config(
        x.shape[1], interpret=jax.default_backend() != "tpu"
    )
    if cfg.window > 0:
        fc = dataclasses.replace(fc, window=cfg.window)

    def one_layer(x, lp):
        h = _rmsnorm(x, lp["ln1_scale"])
        qkv = jnp.einsum(
            "bsd,dcnh->bcsnh", h, lp["wqkv"].astype(cfg.dtype)
        )
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        if cfg.pos == "rope":
            # pipeline stages see the full (unsharded) sequence, so
            # local indices ARE the global positions
            positions = jnp.arange(x.shape[1])
            q = rope(q, positions, cfg.rope_theta)
            k = rope(k, positions, cfg.rope_theta)
        # flash_attention falls back to the einsum oracle off-gate
        attn = flash_attention(q, k, v, fc)
        x = x + jnp.einsum(
            "bsnh,nhd->bsd", attn, lp["wo"].astype(cfg.dtype)
        )
        h = _rmsnorm(x, lp["ln2_scale"])
        h = jax.nn.gelu(
            jnp.einsum("bsd,df->bsf", h, lp["w1"].astype(cfg.dtype))
        )
        x = x + jnp.einsum("bsf,fd->bsd", h, lp["w2"].astype(cfg.dtype))
        return x, None

    x, _ = lax.scan(one_layer, x, stage_params)
    return x


def _embed_fn(params: Dict, tokens: jax.Array, cfg: ModelConfig) -> jax.Array:
    """tokens [m, mb, s] -> activations [m, mb, s, d]."""
    s = tokens.shape[-1]
    x = params["embed"].astype(cfg.dtype)[tokens]
    if cfg.pos == "learned":
        x = x + params["pos_embed"].astype(cfg.dtype)[:s][None, None]
    return x


def _head_loss(
    y: jax.Array, head: Dict, targets: jax.Array, cfg: ModelConfig
) -> jax.Array:
    """Final norm + LM head + mean token cross-entropy for one microbatch.
    y: [mb, s, d]; targets: [mb, s]."""
    h = _rmsnorm(y, head["final_norm_scale"])
    logits = jnp.einsum(
        "bsd,dv->bsv", h, head["lm_head"].astype(cfg.dtype)
    ).astype(jnp.float32)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    )


# -- 1F1B schedule ------------------------------------------------------------


def pipeline_1f1b_grads(
    mesh: Mesh,
    cfg: ModelConfig,
    stage_params: Dict,
    head_params: Dict,
    xs: jax.Array,
    targets: jax.Array,
) -> Tuple[Dict, Dict, jax.Array, jax.Array]:
    """One-forward-one-backward pipeline pass with explicit vjp.

    xs: [m, mb, s, d] microbatched stage-0 inputs (post-embedding);
    targets: [m, mb, s]. Returns (stage_grads [pp,...], head_grads,
    dxs [m, mb, s, d] — the cotangent the caller feeds into the embedding
    vjp — and the mean loss).

    Tick math (combined tick = one fwd + one bwd per stage): stage p runs
    the forward of microbatch i at tick i+p and its backward at tick
    i + 2·pp − 2 − p; the last stage therefore backs up each microbatch
    the same tick it finishes it, and cotangents ride the reverse
    ppermute one stage per tick. In-flight stage inputs are bounded by
    2(pp−1)+1 < 2·pp ring-buffer slots.
    """
    pp = mesh.shape["pp"]
    stage = functools.partial(_stage_fn, cfg=cfg)

    def body(stage_params, head_params, xs, targets):
        sp_local = jax.tree.map(lambda a: a[0], stage_params)
        idx = lax.axis_index("pp")
        m = xs.shape[0]
        slots = 2 * pp
        n_ticks = m + 2 * pp - 2
        fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
        bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]

        def tick(carry, t):
            fwd_buf, bwd_buf, stash, dxs, g_stage, g_head, loss_sum = carry
            f = t - idx
            b = t - (2 * pp - 2 - idx)
            f_ok = (f >= 0) & (f < m)
            b_ok = (b >= 0) & (b < m)
            f_ix = jnp.clip(f, 0, m - 1)
            b_ix = jnp.clip(b, 0, m - 1)

            # ---- forward half ----
            x_in = jnp.where(idx == 0, xs[f_ix], fwd_buf)
            y = stage(sp_local, x_in)
            slot = f_ix % slots
            stash = stash.at[slot].set(
                jnp.where(f_ok, x_in, stash[slot])
            )
            fwd_buf = lax.ppermute(y, "pp", fwd_perm)

            # ---- backward half ----
            x_b = stash[b_ix % slots]
            y_b, vjp = jax.vjp(lambda p, x: stage(p, x), sp_local, x_b)
            tgt = targets[b_ix]
            # Loss head: evaluated (masked) on every stage — SPMD traces
            # one program; only the last stage's seed/grads survive.
            loss_b, (dy_loss, g_head_b) = jax.value_and_grad(
                lambda y, hp: _head_loss(y, hp, tgt, cfg), argnums=(0, 1)
            )(y_b, head_params)
            seed = jnp.where(idx == pp - 1, dy_loss, bwd_buf)
            g_sp_b, g_x = vjp(seed)

            use_b = b_ok  # scalar mask for this tick's backward
            g_stage = jax.tree.map(
                lambda acc, g: acc + jnp.where(use_b, g, 0.0).astype(acc.dtype),
                g_stage, g_sp_b,
            )
            last_mask = use_b & (idx == pp - 1)
            g_head = jax.tree.map(
                lambda acc, g: acc
                + jnp.where(last_mask, g, 0.0).astype(acc.dtype),
                g_head, g_head_b,
            )
            loss_sum = loss_sum + jnp.where(last_mask, loss_b, 0.0)
            first_mask = use_b & (idx == 0)
            dxs = dxs.at[b_ix].set(
                jnp.where(first_mask, g_x, dxs[b_ix])
            )
            bwd_buf = lax.ppermute(g_x, "pp", bwd_perm)
            return (
                fwd_buf, bwd_buf, stash, dxs, g_stage, g_head, loss_sum
            ), None

        mb_shape = xs.shape[1:]
        zeros_act = jnp.zeros(mb_shape, xs.dtype)
        carry0 = (
            zeros_act,                                   # fwd_buf
            zeros_act,                                   # bwd_buf
            jnp.zeros((slots,) + mb_shape, xs.dtype),    # stash
            jnp.zeros_like(xs),                          # dxs
            jax.tree.map(
                lambda a: jnp.zeros(a.shape, jnp.float32), sp_local
            ),                                           # g_stage
            jax.tree.map(
                lambda a: jnp.zeros(a.shape, jnp.float32), head_params
            ),                                           # g_head
            jnp.float32(0.0),                            # loss_sum
        )
        (fwd_buf, bwd_buf, stash, dxs, g_stage, g_head, loss_sum), _ = (
            lax.scan(tick, carry0, jnp.arange(n_ticks))
        )
        # Reductions: loss/head grads live on the last stage only (masked
        # already) -> psum over pp makes them uniform; everything is
        # data-parallel-averaged over dp; dxs is per-example (dp-sharded).
        loss = lax.pmean(lax.psum(loss_sum, "pp") / m, "dp")
        g_head = jax.tree.map(
            lambda g: lax.pmean(lax.psum(g, "pp") / m, "dp"), g_head
        )
        g_stage = jax.tree.map(
            lambda g: lax.pmean(g / m, "dp")[None], g_stage
        )
        # Only stage 0 wrote real values (psum over pp is the cheap mask);
        # per-example cotangents carry the same 1/(m·dp) factor the global
        # mean applies to each microbatch loss.
        dp_size = lax.psum(1, "dp")
        dxs = lax.psum(dxs, "pp") / (m * dp_size)
        return g_stage, g_head, dxs, loss

    stage_specs = jax.tree.map(lambda _: P("pp"), stage_params)
    head_specs = jax.tree.map(lambda _: P(), head_params)
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_specs, head_specs, P(None, "dp"), P(None, "dp")),
        out_specs=(stage_specs, head_specs, P(None, "dp"), P()),
        check_vma=False,
    )(stage_params, head_params, xs, targets)


# -- train steps --------------------------------------------------------------


def make_pipeline_transformer_step(
    cfg: ModelConfig,
    mesh: Mesh,
    n_micro: int,
    schedule: str = "gpipe",
    learning_rate: float = 1e-3,
):
    """(params, opt_state, tokens [n_micro, mb, s+1]) ->
    (params, opt_state, loss) with the layer stack pipelined over the
    mesh "pp" axis and microbatches data-parallel over "dp" (mb must be
    divisible by dp). Tokens arrive pre-microbatched so no sharded-axis
    reshape happens under jit."""
    assert schedule in ("gpipe", "1f1b"), schedule
    pp = mesh.shape["pp"]
    optimizer = optax.adamw(learning_rate)
    params_struct = jax.eval_shape(
        lambda k: init_pipeline_params(cfg, k, pp), jax.random.key(0)
    )
    p_shard = _pipeline_shardings(mesh, params_struct)
    repl = NamedSharding(mesh, P())
    data_shard = NamedSharding(mesh, P(None, "dp"))

    def split_head(params):
        head = {
            "final_norm_scale": params["final_norm_scale"],
            "lm_head": params["lm_head"],
        }
        return head

    if schedule == "gpipe":
        from .pipeline import pipeline_apply

        def loss_fn(params, toks):
            xs = _embed_fn(params, toks[:, :, :-1], cfg)
            ys = pipeline_apply(
                mesh,
                functools.partial(_stage_fn, cfg=cfg),
                params["stages"],
                xs,
            )
            head = split_head(params)
            losses = jax.vmap(
                lambda y, t: _head_loss(y, head, t, cfg)
            )(ys, toks[:, :, 1:])
            return jnp.mean(losses)

        def step(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

    else:  # 1f1b

        def step(params, opt_state, toks):
            head = split_head(params)
            embed_params = {
                k: params[k] for k in ("embed", "pos_embed")
                if k in params  # no pos_embed under pos="rope"
            }
            xs, embed_vjp = jax.vjp(
                lambda ep: _embed_fn(ep, toks[:, :, :-1], cfg),
                embed_params,
            )
            g_stage, g_head, dxs, loss = pipeline_1f1b_grads(
                mesh, cfg, params["stages"], head, xs, toks[:, :, 1:]
            )
            (g_embed,) = embed_vjp(dxs.astype(xs.dtype))
            grads = {
                "embed": g_embed["embed"],
                "final_norm_scale": g_head["final_norm_scale"],
                "lm_head": g_head["lm_head"],
                "stages": g_stage,
            }
            if "pos_embed" in g_embed:
                grads["pos_embed"] = g_embed["pos_embed"]
            grads = jax.tree.map(
                lambda g, p: g.astype(p.dtype), grads, params
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

    # Optimizer state: param-shaped leaves follow the param shardings.
    opt_struct = jax.eval_shape(optimizer.init, params_struct)
    flat_pshard = {
        tuple(str(k) for k in path): s
        for path, s in jax.tree_util.tree_flatten_with_path(p_shard)[0]
    }

    def opt_leaf(path, leaf):  # noqa: ARG001
        keys = tuple(str(k) for k in path)
        for ppath, shard in flat_pshard.items():
            if len(keys) >= len(ppath) and keys[-len(ppath):] == ppath:
                return shard
        return repl

    o_shard = jax.tree_util.tree_map_with_path(opt_leaf, opt_struct)

    def init_all(key):
        params = jax.jit(
            lambda k: init_pipeline_params(cfg, k, pp), out_shardings=p_shard
        )(key)
        opt_state = jax.jit(optimizer.init, out_shardings=o_shard)(params)
        return params, opt_state

    train_step = jax.jit(
        step,
        in_shardings=(p_shard, o_shard, data_shard),
        out_shardings=(p_shard, o_shard, repl),
        donate_argnums=(0, 1),
    )
    return train_step, init_all
