"""Beam-search decoding over the KV cache.

TPU-first shape discipline: the beam dimension IS the batch dimension
of one shared KV cache [L, beam, max_len, g, h] — prefill runs once
and broadcasts, then every step is (1) one batched single-token
forward for all beams, (2) a top-k over the flattened
[beam * vocab] continuation scores, (3) a gather that reorders the
cache rows to each survivor's parent. Everything is ONE lax.scan
under jit; no per-beam Python, no dynamic shapes.

EOS handling (optional): a finished beam is frozen — it proposes
exactly one continuation (itself, padded with eos, score unchanged) —
so live and finished hypotheses compete in the same top-k, the
standard "beam closing" formulation.

Length normalization: each hypothesis's score divides by
(5 + its_generated_len)^alpha / 6^alpha (the GNMT rule) when
``length_penalty`` = alpha > 0; 0 disables. A hypothesis's length
stops growing at its first eos, so with eos enabled short and long
finished beams genuinely rerank. Applied at the FINAL ranking;
in-search comparisons stay on raw cumulative logprobs (the common
simplification — frozen beams compete at unchanged score).

No reference counterpart (the reference agent has no model code);
TPU workload stack, same family as generate.py.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .attention import NEG_INF
from .generate import KVCache, _forward_chunk
from .transformer import ModelConfig


def beam_search(
    params: Dict,
    prompt: jax.Array,
    cfg: ModelConfig,
    max_new_tokens: int,
    beam_size: int = 4,
    length_penalty: float = 0.0,
    eos_id: Optional[int] = None,
    max_len: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """prompt [1, p] -> (sequences [beam, p + max_new_tokens],
    scores [beam]), best beam first.

    Scores are total token logprobs (length-normalized when
    length_penalty > 0). beam_size=1 is exactly greedy decoding.
    MoE models decode drop-free per step (generate's policy).
    """
    assert prompt.shape[0] == 1, "beam search expands ONE prompt"
    b, p = prompt.shape
    total = p + max_new_tokens
    max_len = max_len or total
    assert max_len >= total, (max_len, total)
    if cfg.pos == "learned":
        assert cfg.max_seq >= max_len
    if max_new_tokens == 0:
        return (
            jnp.broadcast_to(prompt, (beam_size, p)),
            jnp.zeros((beam_size,), jnp.float32),
        )
    run = _build_beam_run(
        cfg, p, max_new_tokens, beam_size, length_penalty,
        -1 if eos_id is None else int(eos_id), max_len,
    )
    return run(params, prompt)


@functools.lru_cache(maxsize=32)
def _build_beam_run(
    cfg: ModelConfig, p: int, max_new_tokens: int, beam_size: int,
    length_penalty: float, eos_id: int, max_len: int,
):
    k = beam_size
    total = p + max_new_tokens
    has_eos = eos_id >= 0

    def norm(scores, n_generated):
        if length_penalty <= 0.0:
            return scores
        denom = ((5.0 + n_generated) ** length_penalty) / (
            6.0 ** length_penalty
        )
        return scores / denom

    @jax.jit
    def run(params, prompt):
        cache = KVCache.empty(cfg, 1, max_len)
        logits, cache = _forward_chunk(params, prompt, cache, cfg)
        logp0 = jax.nn.log_softmax(
            logits[0, -1].astype(jnp.float32)
        )

        # beam 0..k-1 start as the top-k first tokens
        scores, first = jax.lax.top_k(logp0, k)          # [k], [k]
        cache = KVCache(
            k=jnp.broadcast_to(
                cache.k, (cfg.n_layers, k) + cache.k.shape[2:]
            ),
            v=jnp.broadcast_to(
                cache.v, (cfg.n_layers, k) + cache.v.shape[2:]
            ),
            length=cache.length,
        )
        buf = jnp.zeros((k, total), jnp.int32)
        buf = buf.at[:, :p].set(prompt[0])
        buf = buf.at[:, p].set(first)
        finished = (
            first == eos_id if has_eos
            else jnp.zeros((k,), bool)
        )
        gen_len = jnp.ones((k,), jnp.float32)  # tokens incl. any eos

        def step(carry, i):
            cache, buf, scores, last, finished, gen_len = carry
            logits, cache = _forward_chunk(
                params, last[:, None], cache, cfg, moe_drop_free=True
            )
            logp = jax.nn.log_softmax(
                logits[:, 0].astype(jnp.float32)
            )  # [k, v]
            vocab = logp.shape[-1]
            if has_eos:
                # frozen beams propose exactly one child: themselves
                # padded with eos at unchanged score
                only_eos = jnp.full(
                    (vocab,), NEG_INF, jnp.float32
                ).at[eos_id].set(0.0)
                logp = jnp.where(finished[:, None], only_eos, logp)
            cand = scores[:, None] + logp                 # [k, v]
            flat_scores, flat_idx = jax.lax.top_k(
                cand.reshape(-1), k
            )
            parent = flat_idx // vocab                    # [k]
            token = (flat_idx % vocab).astype(jnp.int32)  # [k]

            # reorder every per-beam row to its parent
            cache = KVCache(
                k=cache.k[:, parent], v=cache.v[:, parent],
                length=cache.length,
            )
            buf = buf[parent].at[:, p + 1 + i].set(token)
            was_finished = finished[parent]
            # eos padding on an already-finished beam isn't length
            gen_len = gen_len[parent] + jnp.where(was_finished, 0.0, 1.0)
            if has_eos:
                finished = was_finished | (token == eos_id)
            return (
                (cache, buf, flat_scores, token, finished, gen_len),
                None,
            )

        (cache, buf, scores, _, finished, gen_len), _ = jax.lax.scan(
            step,
            (cache, buf, scores, first, finished, gen_len),
            jnp.arange(max_new_tokens - 1),
        )

        final = norm(scores, gen_len)
        order = jnp.argsort(-final)
        return buf[order], final[order]

    return run
