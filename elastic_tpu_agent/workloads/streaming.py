"""Streaming decode: unbounded-length generation for sliding-window
models with a KV cache of FIXED size — HBM use is O(window), not
O(generated length).

A window-attention model (ModelConfig.window > 0) only ever attends
its last ``window`` positions, so keys older than that are dead
weight. The cache here is a ring buffer of exactly ``window`` slots:
position P writes slot P % window, overwriting the key that just
slid out of every future query's reach. A slot-to-absolute-position
map feeds the causal/window mask (generate._cached_attention's
``key_positions``), and RoPE keeps rotating by absolute position, so
the stream is EXACTLY the computation a full cache would do — pinned
by tests against generate() at lengths where both fit, then run far
past any full-cache budget.

The decode loop is one lax.scan; the ring state (cache, slot map) is
scan carry. Static shapes throughout: generation length only changes
the scan's trip count, never a buffer size.

No reference counterpart (the reference agent has no model code);
TPU workload stack, same family as generate.py.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from .generate import KVCache, _forward_chunk, _sample
from .transformer import ModelConfig

# Unwritten ring slots: an absolute position no real query reaches,
# so `cols <= rows` masks them out everywhere. A plain Python int —
# creating a jnp scalar here would initialize the JAX backend at
# IMPORT time, before callers (runner.main, tests' conftest) have
# pinned the platform, and a wedged TPU plugin then hangs the import.
_UNWRITTEN = 2**30


def streaming_generate(
    params: Dict,
    prompt: jax.Array,
    cfg: ModelConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """prompt [b, p] -> [b, p + max_new_tokens], with cache HBM fixed
    at window size regardless of max_new_tokens.

    Requires cfg.window > 0 (the model must be window-trained — with
    full attention, evicting old keys would CHANGE the computation,
    not just bound it) and cfg.pos == "rope" (a learned position table
    is itself O(max position), defeating unboundedness). The prompt
    must fit the window; MoE decodes drop-free per generate's policy.
    """
    assert cfg.window > 0, (
        "streaming decode needs a sliding-window model (cfg.window)"
    )
    assert cfg.pos == "rope", (
        "streaming decode needs rope (a learned position table bounds "
        "the stream at cfg.max_seq)"
    )
    b, p = prompt.shape
    ring_len = cfg.window
    assert p <= ring_len, (
        f"prompt ({p}) must fit the attention window ({ring_len})"
    )
    if key is None:
        key = jax.random.key(0)
    if max_new_tokens == 0:
        return prompt
    run = _build_stream_run(
        cfg, b, p, max_new_tokens, temperature, top_k, top_p
    )
    return run(params, prompt, key)


@functools.lru_cache(maxsize=32)
def _build_stream_run(
    cfg: ModelConfig, b: int, p: int, max_new_tokens: int,
    temperature: float, top_k: int, top_p: float,
):
    ring_len = cfg.window

    @jax.jit
    def run(params, prompt, key):
        # prefill: p <= ring_len, no wrap — the plain path IS the ring
        # path here (slot j == position j), so reuse it verbatim
        cache = KVCache.empty(cfg, b, ring_len)
        logits, cache = _forward_chunk(params, prompt, cache, cfg)
        first = _sample(logits[:, -1], key, temperature, top_k, top_p)
        key_pos = jnp.where(
            jnp.arange(ring_len) < p,
            jnp.arange(ring_len, dtype=jnp.int32),
            _UNWRITTEN,
        )

        def step(carry, _):
            cache, key_pos, pos, tok, key = carry
            key, sub = jax.random.split(key)
            slot = pos % ring_len
            key_pos = key_pos.at[slot].set(pos)
            # cache.length carries the ABSOLUTE position (rope, mask
            # rows); the ring triple redirects the write + mask cols
            logits, cache = _forward_chunk(
                params, tok[:, None],
                KVCache(k=cache.k, v=cache.v, length=pos),
                cfg, moe_drop_free=True, ring=(slot, key_pos),
            )
            nxt = _sample(logits[:, -1], sub, temperature, top_k, top_p)
            return (cache, key_pos, pos + 1, nxt, key), nxt

        # prefill's sample is token 1; N-1 scan steps emit tokens 2..N
        # (no final forward whose sample would be discarded)
        init = (cache, key_pos, jnp.int32(p), first, key)
        _, toks = jax.lax.scan(init=init, f=step, xs=None,
                               length=max_new_tokens - 1)
        return jnp.concatenate(
            [prompt, first[:, None], jnp.moveaxis(toks, 0, 1)], axis=1
        )

    return run
