"""Preemption-tolerant checkpoint/resume for workloads (orbax).

Cloud TPU pods are preemptible: maintenance events and elastic
rescheduling (the whole point of fractional/elastic allocation) can kill
a training pod at any step. The agent side already checkpoints its
bindings (storage/); this module is the workload side: sharded,
async-capable checkpoints of (params, opt_state, step) via orbax, with
restore that honors the live mesh shardings — arrays come back on the
same mesh axes they were saved from, so resume works under any
dp/sp/tp/ep layout.

The reference has no workload code at all (SURVEY.md §2); its
"checkpoint/resume" heading (§5.4) covered only the agent's BoltDB map.
"""

from __future__ import annotations

import logging
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)


def _as_abstract(tree: Any) -> Any:
    """ShapeDtypeStruct mirror of a pytree, preserving shardings so
    orbax lays restored arrays out on the same mesh."""
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=getattr(a, "sharding", None),
        ),
        tree,
    )


class TrainCheckpointer:
    """CheckpointManager wrapper: save/restore (params, opt_state) at a
    step, keeping the newest ``keep`` checkpoints."""

    def __init__(self, directory: str, keep: int = 3) -> None:
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )

    @property
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def save(
        self, step: int, params: Any, opt_state: Any, ema: Any = None,
    ) -> None:
        """``ema``: the EMA tree (transformer.ema_params(opt_state)) as
        its OWN item. It already lives inside opt_state for resume;
        the separate item lets export/serving restore it with a plain
        params template, independent of the optimizer's structure."""
        items = {
            "params": ocp.args.StandardSave(params),
            "opt_state": ocp.args.StandardSave(opt_state),
        }
        if ema is not None:
            items["ema"] = ocp.args.StandardSave(ema)
        self._mgr.save(step, args=ocp.args.Composite(**items))

    def restore(
        self, params_like: Any, opt_state_like: Any,
        step: Optional[int] = None,
    ) -> Tuple[Any, Any, int]:
        """Restore (params, opt_state, step). ``*_like`` are live arrays or
        jax.ShapeDtypeStruct trees carrying the target shardings — orbax
        lays the restored arrays out on the same mesh."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint present")

        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardRestore(_as_abstract(params_like)),
                opt_state=ocp.args.StandardRestore(
                    _as_abstract(opt_state_like)
                ),
            ),
        )
        return restored["params"], restored["opt_state"], step

    def restore_params(
        self, params_like: Any, step: Optional[int] = None,
        item: str = "params",
    ) -> Tuple[Any, int]:
        """Params-only restore for consumers that discard the optimizer
        (export, decode): a PARTIAL orbax restore of just one
        param-shaped item — the opt_state is never read, so its
        structure (which varies with how the training run passed its
        learning rate) cannot matter and no template guessing is
        needed. ``item='ema'`` restores the EMA weights saved by
        save(..., ema=...)."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint present")
        # item presence is checked UP FRONT (orbax writes one subdir
        # per item) so a real restore failure — wrong preset template,
        # corrupt data — surfaces as itself, not as "item missing".
        # self._mgr.directory is an epath.Path: the / operator and
        # exists() work on remote stores (gs://) too, where
        # os.path.isdir would be False for every existing item.
        item_dir = self._mgr.directory / str(step) / item
        if not item_dir.exists():
            raise FileNotFoundError(
                f"checkpoint step {step} has no {item!r} item"
                + (
                    " (train with --ema-decay to save EMA weights)"
                    if item == "ema" else ""
                )
            )
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(**{
                item: ocp.args.StandardRestore(
                    _as_abstract(params_like)
                ),
            }),
        )
        return restored[item], step

    def wait(self) -> None:
        """Block until any async save has committed (call before exit)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
