"""Preemption-tolerant checkpoint/resume for workloads (orbax), plus
block-chunked, digest-chained DELTA checkpoints for sub-second
migration.

Cloud TPU pods are preemptible: maintenance events and elastic
rescheduling (the whole point of fractional/elastic allocation) can kill
a training pod at any step. The agent side already checkpoints its
bindings (storage/); this module is the workload side: sharded,
async-capable checkpoints of (params, opt_state, step) via orbax, with
restore that honors the live mesh shardings — arrays come back on the
same mesh axes they were saved from, so resume works under any
dp/sp/tp/ep layout.

The second half is the pre-copy transport (ROADMAP item 4; Funky's FPGA
checkpoint/restore lifecycle in PAPERS.md gives the reference
semantics): :class:`DeltaCheckpointer` chunks a state payload into
fixed-size blocks, digests each with blake2b, and ships only the blocks
whose digest changed since the last committed snapshot — the prefix
cache's chain-hash pattern (workloads/prefix_cache.py) applied to
parameter/optimizer bytes. Blocks are content-addressed files, the
per-round manifest is written atomically (temp + rename), and the
manifest carries the running digest CHAIN so a destination can verify
the reassembled state byte-for-byte before declaring the migration
complete. A drain's downtime becomes the final delta, not the full
state.

The reference has no workload code at all (SURVEY.md §2); its
"checkpoint/resume" heading (§5.4) covered only the agent's BoltDB map.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)

# Delta-checkpoint block size: 256 KiB balances dedup granularity (an
# optimizer step dirties most of the state, but EMA/frozen/embedding
# regions stay byte-stable) against per-block file overhead on the
# shared 'PVC'.
DELTA_BLOCK_SIZE = 256 * 1024
_DELTA_DIGEST_SIZE = 16
_DELTA_CHAIN_ROOT = b"\x00" * _DELTA_DIGEST_SIZE
_MANIFEST_PREFIX = "manifest-"


def _as_abstract(tree: Any) -> Any:
    """ShapeDtypeStruct mirror of a pytree, preserving shardings so
    orbax lays restored arrays out on the same mesh."""
    return jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=getattr(a, "sharding", None),
        ),
        tree,
    )


class TrainCheckpointer:
    """CheckpointManager wrapper: save/restore (params, opt_state) at a
    step, keeping the newest ``keep`` checkpoints."""

    def __init__(self, directory: str, keep: int = 3) -> None:
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )

    @property
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def save(
        self, step: int, params: Any, opt_state: Any, ema: Any = None,
    ) -> None:
        """``ema``: the EMA tree (transformer.ema_params(opt_state)) as
        its OWN item. It already lives inside opt_state for resume;
        the separate item lets export/serving restore it with a plain
        params template, independent of the optimizer's structure."""
        items = {
            "params": ocp.args.StandardSave(params),
            "opt_state": ocp.args.StandardSave(opt_state),
        }
        if ema is not None:
            items["ema"] = ocp.args.StandardSave(ema)
        self._mgr.save(step, args=ocp.args.Composite(**items))

    def restore(
        self, params_like: Any, opt_state_like: Any,
        step: Optional[int] = None,
    ) -> Tuple[Any, Any, int]:
        """Restore (params, opt_state, step). ``*_like`` are live arrays or
        jax.ShapeDtypeStruct trees carrying the target shardings — orbax
        lays the restored arrays out on the same mesh."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint present")

        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardRestore(_as_abstract(params_like)),
                opt_state=ocp.args.StandardRestore(
                    _as_abstract(opt_state_like)
                ),
            ),
        )
        return restored["params"], restored["opt_state"], step

    def restore_params(
        self, params_like: Any, step: Optional[int] = None,
        item: str = "params",
    ) -> Tuple[Any, int]:
        """Params-only restore for consumers that discard the optimizer
        (export, decode): a PARTIAL orbax restore of just one
        param-shaped item — the opt_state is never read, so its
        structure (which varies with how the training run passed its
        learning rate) cannot matter and no template guessing is
        needed. ``item='ema'`` restores the EMA weights saved by
        save(..., ema=...)."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint present")
        # item presence is checked UP FRONT (orbax writes one subdir
        # per item) so a real restore failure — wrong preset template,
        # corrupt data — surfaces as itself, not as "item missing".
        # self._mgr.directory is an epath.Path: the / operator and
        # exists() work on remote stores (gs://) too, where
        # os.path.isdir would be False for every existing item.
        item_dir = self._mgr.directory / str(step) / item
        if not item_dir.exists():
            raise FileNotFoundError(
                f"checkpoint step {step} has no {item!r} item"
                + (
                    " (train with --ema-decay to save EMA weights)"
                    if item == "ema" else ""
                )
            )
        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(**{
                item: ocp.args.StandardRestore(
                    _as_abstract(params_like)
                ),
            }),
        )
        return restored[item], step

    def wait(self) -> None:
        """Block until any async save has committed (call before exit)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


# -- incremental delta checkpoints (pre-copy transport) -----------------------


def _block_digest(block: bytes) -> str:
    return hashlib.blake2b(block, digest_size=_DELTA_DIGEST_SIZE).hexdigest()


def chain_block_digests(digests: List[str]) -> str:
    """The running digest chain over an ordered block-digest list:
    ``chain_j = H(chain_{j-1} || digest_j)`` — the prefix cache's
    chain-hash construction (workloads/prefix_cache.chain_hashes), so
    the FINAL link identifies the whole reassembled state, order
    included. A destination recomputing this from the blocks it read
    proves it holds exactly the bytes the source acked."""
    chain = _DELTA_CHAIN_ROOT
    for d in digests:
        h = hashlib.blake2b(digest_size=_DELTA_DIGEST_SIZE)
        h.update(chain)
        h.update(bytes.fromhex(d))
        chain = h.digest()
    return chain.hex()


class DeltaCheckpointer:
    """Block-chunked, digest-chained delta checkpoints on a shared dir.

    Layout under ``directory``::

        blocks/<digest>.bin     content-addressed block payloads
        manifest-<step>.json    atomic per-round manifest: ordered block
                                digests + the running chain + delta stats

    :meth:`save` chunks the payload, writes only blocks not already
    present (content addressing makes re-writes idempotent and torn
    block files impossible to mistake for good ones — a partial write
    under a temp name never becomes addressable), then commits the
    manifest with temp-name + rename. A crash mid-round leaves the
    previous manifest fully restorable; a torn manifest file is
    unreadable JSON and skipped by :meth:`latest_step`.

    Dependency-free in operation (hashlib/json/os only): the sim
    workloads and the in-pod runner share this exact transport.
    """

    def __init__(
        self, directory: str, block_size: int = DELTA_BLOCK_SIZE
    ) -> None:
        self.directory = directory
        self.block_size = max(1, int(block_size))
        self._blocks_dir = os.path.join(directory, "blocks")
        # digests of the last manifest COMMITTED BY THIS INSTANCE — the
        # "since the last acked snapshot" baseline for delta accounting
        # (an instance resuming over existing state re-reads it lazily).
        self._last_digests: Optional[List[str]] = None

    # -- writing --------------------------------------------------------------

    def _manifest_path(self, step: int) -> str:
        return os.path.join(
            self.directory, f"{_MANIFEST_PREFIX}{int(step):012d}.json"
        )

    def _load_baseline(self) -> List[str]:
        if self._last_digests is not None:
            return self._last_digests
        step = self.latest_step
        if step is None:
            self._last_digests = []
        else:
            m = self.read_manifest(step)
            self._last_digests = list(m.get("blocks", [])) if m else []
        return self._last_digests

    def save(self, step: int, payload: bytes, round_: int = 0) -> Dict:
        """Commit one delta round: write changed blocks + the manifest.
        Returns the round summary (total/delta bytes, block counts, the
        chain digest) — what the workload's ``kind="precopy"`` ack and
        the final cutover ack carry."""
        os.makedirs(self._blocks_dir, exist_ok=True)
        prior = set(self._load_baseline())
        digests: List[str] = []
        delta_blocks = 0
        delta_bytes = 0
        view = memoryview(payload)
        for off in range(0, max(1, len(payload)), self.block_size):
            block = bytes(view[off:off + self.block_size])
            d = _block_digest(block)
            digests.append(d)
            path = os.path.join(self._blocks_dir, f"{d}.bin")
            changed = d not in prior
            if changed:
                delta_blocks += 1
                delta_bytes += len(block)
            if changed and not os.path.exists(path):
                tmp = f"{path}.tmp"
                with open(tmp, "wb") as f:
                    f.write(block)
                os.replace(tmp, path)
        chain = chain_block_digests(digests)
        manifest = {
            "step": int(step),
            "round": int(round_),
            "block_size": self.block_size,
            "total_bytes": len(payload),
            "n_blocks": len(digests),
            "delta_blocks": delta_blocks,
            "delta_bytes": delta_bytes,
            "blocks": digests,
            "chain": chain,
        }
        path = self._manifest_path(step)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, path)
        self._last_digests = digests
        return {
            "step": int(step),
            "round": int(round_),
            "total_bytes": len(payload),
            "delta_bytes": delta_bytes,
            "delta_blocks": delta_blocks,
            "n_blocks": len(digests),
            "chain": chain,
        }

    # -- reading --------------------------------------------------------------

    @property
    def latest_step(self) -> Optional[int]:
        """Highest step with a READABLE manifest (torn manifests are
        skipped — the previous round stands)."""
        best = None
        try:
            names = os.listdir(self.directory)
        except OSError:
            return None
        for name in names:
            if not (
                name.startswith(_MANIFEST_PREFIX)
                and name.endswith(".json")
            ):
                continue
            try:
                step = int(name[len(_MANIFEST_PREFIX):-len(".json")])
            except ValueError:
                continue
            if (best is None or step > best) and self.read_manifest(
                step
            ) is not None:
                best = step
        return best

    def read_manifest(self, step: int) -> Optional[Dict]:
        try:
            with open(self._manifest_path(step)) as f:
                m = json.load(f)
        except (OSError, ValueError):
            return None
        return m if isinstance(m, dict) and "blocks" in m else None

    def verify(self, step: Optional[int] = None) -> Dict:
        """Verify the digest CHAIN of one round's reassembled state:
        every block present, every block's content matching its digest,
        and the recomputed chain equal to the manifest's. This is what
        the destination agent runs before deleting the migration record
        — ``{"ok": bool, "chain": ..., "problems": [...]}``."""
        if step is None:
            step = self.latest_step
        if step is None:
            return {"ok": False, "problems": ["no manifest present"]}
        m = self.read_manifest(step)
        if m is None:
            return {"ok": False, "problems": [f"manifest {step} unreadable"]}
        problems: List[str] = []
        for d in m["blocks"]:
            path = os.path.join(self._blocks_dir, f"{d}.bin")
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                problems.append(f"block {d} missing")
                continue
            if _block_digest(data) != d:
                problems.append(f"block {d} corrupt")
        chain = chain_block_digests(m["blocks"])
        if chain != m.get("chain"):
            problems.append(
                f"chain mismatch: recomputed {chain}, manifest "
                f"{m.get('chain')}"
            )
        return {
            "ok": not problems,
            "step": int(step),
            "chain": chain,
            "n_blocks": len(m["blocks"]),
            "total_bytes": m.get("total_bytes"),
            "problems": problems,
        }

    def load(self, step: Optional[int] = None) -> Tuple[bytes, Dict]:
        """Reassemble one round's full payload, verifying each block and
        the chain on the way (raises ValueError on a torn/corrupt
        chain — the caller falls back to an earlier round or the full
        checkpoint, never restores half a state)."""
        if step is None:
            step = self.latest_step
        if step is None:
            raise FileNotFoundError("no delta checkpoint present")
        m = self.read_manifest(step)
        if m is None:
            raise FileNotFoundError(f"delta manifest {step} unreadable")
        parts: List[bytes] = []
        for d in m["blocks"]:
            path = os.path.join(self._blocks_dir, f"{d}.bin")
            with open(path, "rb") as f:
                data = f.read()
            if _block_digest(data) != d:
                raise ValueError(f"delta block {d} corrupt")
            parts.append(data)
        payload = b"".join(parts)[:m["total_bytes"]]
        if chain_block_digests(m["blocks"]) != m.get("chain"):
            raise ValueError("delta digest chain mismatch")
        return payload, m

    def gc(self, keep_steps: int = 2) -> int:
        """Drop manifests beyond the newest ``keep_steps`` and any block
        no surviving manifest references; returns blocks removed. Cheap
        and crash-safe (a re-run converges)."""
        steps = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        for name in names:
            if name.startswith(_MANIFEST_PREFIX) and name.endswith(".json"):
                try:
                    steps.append(int(name[len(_MANIFEST_PREFIX):-5]))
                except ValueError:
                    continue
        steps.sort()
        live: set = set()
        for s in steps[-max(1, keep_steps):]:
            m = self.read_manifest(s)
            if m:
                live.update(m["blocks"])
        removed = 0
        for s in steps[:-max(1, keep_steps)]:
            try:
                os.unlink(self._manifest_path(s))
            except OSError:
                pass
        try:
            blocks = os.listdir(self._blocks_dir)
        except OSError:
            return 0
        for name in blocks:
            if name.endswith(".bin") and name[:-4] not in live:
                try:
                    os.unlink(os.path.join(self._blocks_dir, name))
                    removed += 1
                except OSError:
                    pass
        return removed


def tree_to_bytes(tree: Any) -> bytes:
    """Serialize a pytree of arrays into one deterministic byte stream
    (leaves in jax.tree flatten order, each fully replicated to host) —
    the payload :class:`DeltaCheckpointer` chunks. Pre-copy rounds of
    the SAME structure diff block-by-block because the layout is
    positional and stable."""
    import numpy as np

    parts: List[bytes] = []
    for leaf in jax.tree.leaves(tree):
        parts.append(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return b"".join(parts)


def bytes_to_tree(payload: bytes, like: Any) -> Any:
    """Inverse of :func:`tree_to_bytes`: rebuild the pytree from the
    byte stream using ``like`` (live arrays or ShapeDtypeStructs) as the
    shape/dtype template. Raises ValueError when the stream does not
    exactly cover the template — a truncated restore must never
    silently zero-fill."""
    import numpy as np

    leaves, treedef = jax.tree.flatten(like)
    out = []
    off = 0
    for leaf in leaves:
        shape = tuple(leaf.shape)
        dtype = np.dtype(leaf.dtype)
        n = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape \
            else dtype.itemsize
        chunk = payload[off:off + n]
        if len(chunk) != n:
            raise ValueError(
                f"delta payload truncated: wanted {n} bytes at offset "
                f"{off}, got {len(chunk)}"
            )
        out.append(np.frombuffer(chunk, dtype=dtype).reshape(shape))
        off += n
    if off != len(payload):
        raise ValueError(
            f"delta payload has {len(payload) - off} trailing bytes "
            "beyond the template"
        )
    return jax.tree.unflatten(treedef, out)
