"""Preemption-tolerant checkpoint/resume for workloads (orbax).

Cloud TPU pods are preemptible: maintenance events and elastic
rescheduling (the whole point of fractional/elastic allocation) can kill
a training pod at any step. The agent side already checkpoints its
bindings (storage/); this module is the workload side: sharded,
async-capable checkpoints of (params, opt_state, step) via orbax, with
restore that honors the live mesh shardings — arrays come back on the
same mesh axes they were saved from, so resume works under any
dp/sp/tp/ep layout.

The reference has no workload code at all (SURVEY.md §2); its
"checkpoint/resume" heading (§5.4) covered only the agent's BoltDB map.
"""

from __future__ import annotations

import logging
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)


class TrainCheckpointer:
    """CheckpointManager wrapper: save/restore (params, opt_state) at a
    step, keeping the newest ``keep`` checkpoints."""

    def __init__(self, directory: str, keep: int = 3) -> None:
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True
            ),
        )

    @property
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def save(self, step: int, params: Any, opt_state: Any) -> None:
        self._mgr.save(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(params),
                opt_state=ocp.args.StandardSave(opt_state),
            ),
        )

    def restore(
        self, params_like: Any, opt_state_like: Any,
        step: Optional[int] = None,
    ) -> Tuple[Any, Any, int]:
        """Restore (params, opt_state, step). ``*_like`` are live arrays or
        jax.ShapeDtypeStruct trees carrying the target shardings — orbax
        lays the restored arrays out on the same mesh."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint present")

        def as_abstract(tree):
            return jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(
                    a.shape, a.dtype,
                    sharding=getattr(a, "sharding", None),
                ),
                tree,
            )

        restored = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardRestore(as_abstract(params_like)),
                opt_state=ocp.args.StandardRestore(
                    as_abstract(opt_state_like)
                ),
            ),
        )
        return restored["params"], restored["opt_state"], step

    def restore_params(
        self, params_like: Any, step: Optional[int] = None,
    ) -> Tuple[Any, int]:
        """Params-only restore for consumers that discard the optimizer
        (export, decode). StandardRestore matches STRUCTURE, and the
        adamw opt_state's structure depends on how the training run
        passed its learning rate — a float builds an empty ScaleState,
        a schedule builds ScaleByScheduleState(count) — so try a
        template of each form; the restored opt values are thrown away
        either way."""
        import optax

        last_err: Optional[Exception] = None
        for make_opt in (
            lambda: optax.adamw(1e-3),
            lambda: optax.adamw(optax.constant_schedule(1e-3)),
        ):
            opt_tmpl = make_opt().init(params_like)
            try:
                params, _, got = self.restore(
                    params_like, opt_tmpl, step
                )
                return params, got
            except FileNotFoundError:
                raise
            except Exception as e:  # noqa: BLE001 - structure mismatch
                last_err = e
        raise last_err

    def wait(self) -> None:
        """Block until any async save has committed (call before exit)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
