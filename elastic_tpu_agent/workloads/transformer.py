"""Flagship JAX workload: a sharded decoder-only transformer LM.

This is the e2e *workload* side of the agent (BASELINE configs 2-5): the
JAX program a pod runs after the agent injects its chips/env. It is also
the bench/graft-entry model. TPU-first design:

- bfloat16 matmuls sized for the MXU; static shapes; no Python control
  flow under jit.
- GSPMD sharding over a 3-axis Mesh ("dp", "sp", "tp"):
    * params: attention heads + MLP hidden sharded on "tp" (tensor
      parallelism), replicated over "dp"/"sp";
    * activations: batch on "dp", sequence on "sp" (sequence/context
      parallelism for long-context — XLA inserts the all-gathers /
      reduce-scatters over ICI as needed);
    * optimizer state follows params.
- collectives are never written by hand: shardings are declared with
  NamedSharding / with_sharding_constraint and XLA's SPMD partitioner
  lowers them onto ICI (the scaling-book recipe).

The reference repo contains no model code at all (SURVEY.md §2: its
"workload" was any CUDA container); this package is what makes the TPU
agent's graded configs actually runnable and measurable.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab: int = 32768
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq: int = 1024
    dtype: Any = jnp.bfloat16
    # Grouped-query attention: number of shared k/v heads (0 = MHA, i.e.
    # n_kv_heads == n_heads). Cuts kv projection weights and kv-cache by
    # n_heads/n_kv_heads; the attention core still runs at full q-head
    # width (kv heads are repeated into their groups before the kernel).
    n_kv_heads: int = 0
    # Position encoding: "learned" (table added to embeddings, bounded by
    # max_seq) or "rope" (rotary embeddings applied to q/k — extrapolates
    # past max_seq and composes with sequence sharding because rotation
    # is per-position elementwise, applied BEFORE the attention core).
    pos: str = "learned"
    rope_theta: float = 10000.0
    # Sliding-window attention: each token attends only the last
    # ``window`` positions (0 = full causal). Served by the flash
    # kernels with block skipping (compute O(window) per query) or the
    # windowed reference path; not supported together with ring/sp
    # sharding.
    window: int = 0
    # Attention core: "auto" picks ring when the sequence axis is sharded
    # (sp>1), the Pallas flash kernel on TPU when tiles align, and the
    # materialized-scores einsum otherwise. "flash"/"ring"/"reference"
    # force an implementation.
    attn: str = "auto"
    # Rematerialize each layer in backward (jax.checkpoint): trades ~33%
    # more matmul FLOPs for O(n_layers) fewer saved activations — the
    # standard HBM-for-FLOPs trade that unlocks larger batches.
    remat: bool = False
    # Mixture-of-Experts: with moe_experts > 0, every ``moe_every``-th
    # layer replaces its dense MLP with an expert-parallel MoE layer
    # (workloads/moe.py; experts sharded over the mesh "ep" axis).
    moe_experts: int = 0
    moe_every: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def is_gqa(self) -> bool:
        return self.kv_heads != self.n_heads

    def is_moe_layer(self, i: int) -> bool:
        return self.moe_experts > 0 and i % self.moe_every == (
            self.moe_every - 1
        )


# -- parameters ---------------------------------------------------------------


def init_params(cfg: ModelConfig, key: jax.Array) -> Dict:
    """Plain pytree params; names chosen so shardings map cleanly."""
    initializer = jax.nn.initializers.normal(0.02)

    def dense(key, shape):
        return initializer(key, shape, jnp.float32)

    assert cfg.pos in ("learned", "rope"), cfg.pos
    if cfg.pos == "rope":
        assert cfg.head_dim % 2 == 0, "rope needs an even head_dim"
    keys = jax.random.split(key, 4 + cfg.n_layers)
    params = {
        "embed": dense(keys[0], (cfg.vocab, cfg.d_model)),
        "final_norm_scale": jnp.ones((cfg.d_model,), jnp.float32),
        "lm_head": dense(keys[2], (cfg.d_model, cfg.vocab)),
        "layers": [],
    }
    if cfg.pos == "learned":
        params["pos_embed"] = dense(keys[1], (cfg.max_seq, cfg.d_model))
    if cfg.is_gqa:
        assert cfg.n_heads % cfg.kv_heads == 0, (
            f"n_heads {cfg.n_heads} must be a multiple of n_kv_heads "
            f"{cfg.kv_heads}"
        )
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[3 + i], 6)
        layer = {
            "ln1_scale": jnp.ones((cfg.d_model,), jnp.float32),
            "wo": dense(k[1], (cfg.n_heads, cfg.head_dim, cfg.d_model)),
            "ln2_scale": jnp.ones((cfg.d_model,), jnp.float32),
        }
        if cfg.is_gqa:
            layer["wq"] = dense(
                k[0], (cfg.d_model, cfg.n_heads, cfg.head_dim)
            )
            layer["wkv"] = dense(
                k[4], (cfg.d_model, 2, cfg.kv_heads, cfg.head_dim)
            )
        else:
            layer["wqkv"] = dense(
                k[0], (cfg.d_model, 3, cfg.n_heads, cfg.head_dim)
            )
        if cfg.is_moe_layer(i):
            from .moe import init_moe_params

            layer["moe"] = init_moe_params(
                k[2], cfg.d_model, cfg.d_ff, cfg.moe_experts
            )
        else:
            layer["w1"] = dense(k[2], (cfg.d_model, cfg.d_ff))
            layer["w2"] = dense(k[3], (cfg.d_ff, cfg.d_model))
        params["layers"].append(layer)
    return params


def param_shardings(mesh: Mesh, cfg: Optional[ModelConfig] = None) -> Dict:
    """NamedSharding pytree matching init_params for ``cfg`` (default:
    a dense MHA config): tensor-parallel over "tp", replicated over
    "dp"/"sp". The layer dict carries exactly the attention projection
    keys that config's params carry (fused wqkv for MHA, wq+wkv for
    GQA) so it is usable directly as jit shardings."""
    cfg = cfg or ModelConfig()

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    layer = {
        "ln1_scale": ns(),
        "wo": ns("tp", None, None),           # shard heads
        "ln2_scale": ns(),
        "w1": ns(None, "tp"),                 # shard FF hidden
        "w2": ns("tp", None),                 # shard FF hidden
    }
    if cfg.is_gqa:
        layer["wq"] = ns(None, "tp", None)    # shard q heads
        layer["wkv"] = ns(None, None, "tp", None)  # shard kv heads
    else:
        layer["wqkv"] = ns(None, None, "tp", None)  # shard heads
    out = {
        "embed": ns(None, None),
        "final_norm_scale": ns(),
        "lm_head": ns(None, "tp"),            # shard vocab
        "layers": [layer],  # broadcast over the layer list by tree prefix
    }
    if cfg.pos == "learned":
        out["pos_embed"] = ns()
    return out


def _full_param_shardings(mesh: Mesh, cfg: ModelConfig) -> Dict:
    if cfg.is_gqa:
        tp = mesh.shape.get("tp", 1)
        assert cfg.kv_heads % tp == 0, (
            f"GQA kv_heads {cfg.kv_heads} must be divisible by tp={tp} "
            "(wkv shards its kv-head axis over tp); use a smaller tp or "
            "more kv heads"
        )
    base = param_shardings(mesh, cfg)
    dense_layer = base["layers"][0]
    layers = []
    for i in range(cfg.n_layers):
        if cfg.is_moe_layer(i):
            from .moe import moe_param_shardings

            layers.append(
                {
                    k: v for k, v in dense_layer.items()
                    if k not in ("w1", "w2")
                }
                | {"moe": moe_param_shardings(mesh)}
            )
        else:
            layers.append(dense_layer)
    return {
        **{k: v for k, v in base.items() if k != "layers"},
        "layers": layers,
    }


# -- model --------------------------------------------------------------------


def rope(
    x: jax.Array, positions: jax.Array, theta: float = 10000.0
) -> jax.Array:
    """Rotary position embedding. x: [b, s, n, h] (h even), positions:
    [s] global token positions shared across the batch, or [b, s]
    per-row positions (continuous-batching decode, where each slot
    sits at its own depth). Pairs (x[2i], x[2i+1]) rotate by
    pos·theta^(-2i/h); elementwise per position, so it shards trivially
    over any sequence partitioning (the ring/sp layouts included)."""
    h = x.shape[-1]
    freqs = theta ** (
        -jnp.arange(0, h, 2, dtype=jnp.float32) / h
    )  # [h/2]
    angles = (
        positions[..., None].astype(jnp.float32) * freqs
    )  # [s, h/2] or [b, s, h/2]
    cos = jnp.cos(angles)[..., None, :]   # [..., s, 1, h/2]
    sin = jnp.sin(angles)[..., None, :]
    if positions.ndim == 1:
        cos, sin = cos[None], sin[None]   # broadcast over batch
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    out = jnp.stack(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).reshape(x.shape)
    return out.astype(x.dtype)


def _rmsnorm(x: jax.Array, scale: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale.astype(
        x.dtype
    )


def _attention_core(
    q: jax.Array, k: jax.Array, v: jax.Array, cfg: ModelConfig,
    mesh: Optional[Mesh],
) -> jax.Array:
    """Dispatch the attention core ([b,s,n,h]³ → [b,s,n,h])."""
    from .attention import (
        auto_flash_config,
        flash_attention,
        reference_attention,
        supports_flash,
    )
    from .ring_attention import ring_attention_sharded

    s, h = q.shape[1], q.shape[3]
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    platform = jax.devices()[0].platform
    impl = cfg.attn
    if impl == "auto":
        if sp > 1:
            impl = "ring"
        elif platform == "tpu" and supports_flash(
            s, h, auto_flash_config(s)
        ):
            impl = "flash"
        else:
            impl = "reference"
    if impl == "ring":
        if cfg.window > 0:
            raise ValueError(
                "sliding-window attention is not supported with ring/sp "
                "sharding; use sp=1 (flash handles long windows with "
                "O(window) compute per query)"
            )
        if mesh is None:
            raise ValueError("ring attention needs a mesh (sp axis)")
        return ring_attention_sharded(q, k, v, mesh)
    if impl == "flash":
        if sp > 1:
            raise ValueError(
                "flash attention cannot span a sharded sequence axis; "
                "use ring (attn='ring'/'auto') when sp > 1"
            )
        fc = auto_flash_config(s, interpret=(platform != "tpu"))
        if cfg.window > 0:
            fc = dataclasses.replace(fc, window=cfg.window)
        if mesh is None:
            return flash_attention(q, k, v, fc)
        # Under GSPMD, XLA cannot auto-partition a pallas_call: pin the
        # per-device view with shard_map (b on dp, heads on tp) and run
        # the kernel on local shards.
        spec = P("dp", "sp", "tp", None)
        return jax.shard_map(
            lambda q, k, v: flash_attention(q, k, v, fc),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v)
    return reference_attention(q, k, v, causal=True, window=cfg.window)


def _attention(
    x: jax.Array, layer: Dict, cfg: ModelConfig,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    rotate = None
    if cfg.pos == "rope":
        # Global token positions: under GSPMD this op sees the GLOBAL
        # sequence, so positions are correct for any sp sharding (the
        # rotation is per-position elementwise and happens BEFORE the
        # sharded attention core / ring).
        positions = jnp.arange(x.shape[1])

        def rotate(t):
            return rope(t, positions, cfg.rope_theta)

    if "wq" in layer:  # GQA: separate q and shared-kv projections
        q = jnp.einsum("bsd,dnh->bsnh", x, layer["wq"].astype(cfg.dtype))
        kv = jnp.einsum(
            "bsd,dcgh->bcsgh", x, layer["wkv"].astype(cfg.dtype)
        )
        k0, v0 = kv[:, 0], kv[:, 1]
        if rotate is not None:
            q, k0 = rotate(q), rotate(k0)  # rotate at kv width, cheaper
        groups = cfg.n_heads // cfg.kv_heads
        # repeat each kv head across its q-head group; XLA folds the
        # repeat into the consumer matmuls (no materialized copy when the
        # core is the einsum path; the kernels read it tiled either way)
        k = jnp.repeat(k0, groups, axis=2)
        v = jnp.repeat(v0, groups, axis=2)
    else:
        qkv = jnp.einsum(
            "bsd,dcnh->bcsnh", x, layer["wqkv"].astype(cfg.dtype)
        )
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [b, s, n, h]
        if rotate is not None:
            q, k = rotate(q), rotate(k)
    out = _attention_core(q, k, v, cfg, mesh)
    return jnp.einsum("bsnh,nhd->bsd", out, layer["wo"].astype(cfg.dtype))


def _mlp(x: jax.Array, layer: Dict, cfg: ModelConfig) -> jax.Array:
    h = jnp.einsum("bsd,df->bsf", x, layer["w1"].astype(cfg.dtype))
    h = jax.nn.gelu(h)
    return jnp.einsum("bsf,fd->bsd", h, layer["w2"].astype(cfg.dtype))


def forward_with_aux(
    params: Dict, tokens: jax.Array, cfg: ModelConfig,
    activation_sharding: Optional[NamedSharding] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(token logits, summed MoE aux loss — 0.0 for dense models).
    ``activation_sharding`` (NamedSharding of P("dp","sp",None)) pins the
    batch/sequence layout so XLA partitions activations — and inserts the
    ICI collectives — over the mesh."""
    _, s = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    if cfg.pos == "learned":
        x = x + params["pos_embed"].astype(cfg.dtype)[:s][None]
    mesh = (
        activation_sharding.mesh if activation_sharding is not None else None
    )
    if activation_sharding is not None:
        x = jax.lax.with_sharding_constraint(x, activation_sharding)

    def layer_fn(x, layer):
        from .moe import moe_mlp

        x = x + _attention(
            _rmsnorm(x, layer["ln1_scale"]), layer, cfg, mesh
        )
        h = _rmsnorm(x, layer["ln2_scale"])
        if "moe" in layer:
            y, aux = moe_mlp(
                h, layer["moe"], cfg.moe_capacity_factor, mesh
            )
        else:
            y, aux = _mlp(h, layer, cfg), jnp.float32(0.0)
        return x + y, aux

    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn)
    aux_total = jnp.float32(0.0)
    for layer in params["layers"]:
        x, aux = layer_fn(x, layer)
        aux_total = aux_total + aux
    x = _rmsnorm(x, params["final_norm_scale"])
    logits = jnp.einsum(
        "bsd,dv->bsv", x, params["lm_head"].astype(cfg.dtype)
    )
    return logits, aux_total


def forward(
    params: Dict, tokens: jax.Array, cfg: ModelConfig,
    activation_sharding: Optional[NamedSharding] = None,
) -> jax.Array:
    """Token logits (aux loss discarded; see forward_with_aux)."""
    return forward_with_aux(params, tokens, cfg, activation_sharding)[0]


def make_mesh(
    n_devices: Optional[int] = None,
    dp: Optional[int] = None,
    sp: int = 1,
    tp: Optional[int] = None,
    ep: int = 1,
) -> Mesh:
    """4-axis mesh over the visible devices: data, sequence, tensor, and
    expert parallelism. Defaults: tp = min(n, 4) (keeps tensor-parallel
    collectives on the fastest ICI ring), sp = ep = 1, dp = remainder.
    Axes a model doesn't use simply stay size 1 — PartitionSpecs refer to
    axes by name, so dense and MoE models share one mesh shape."""
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]
    if tp is None:
        rest = n // (sp * ep)
        tp = 4 if rest % 4 == 0 and rest >= 4 else (2 if rest % 2 == 0 else 1)
    if dp is None:
        dp = n // (tp * sp * ep)
    assert dp * sp * tp * ep == n, f"mesh {dp}x{sp}x{tp}x{ep} != {n} devices"
    arr = np.array(devices).reshape(dp, sp, tp, ep)
    return Mesh(arr, axis_names=("dp", "sp", "tp", "ep"))


# -- training step ------------------------------------------------------------


class EmaState(NamedTuple):
    """Optimizer-chain stage holding the parameter EMA. Living inside
    opt_state means checkpointing, sharding (opt_leaf_sharding maps the
    param-shaped subtree to the param's sharding), and donation all
    come for free — no train-step signature change."""

    ema: Any


def _ema_transform(decay: float):
    def init_fn(params):
        return EmaState(ema=params)

    def update_fn(updates, state, params=None):
        new_params = optax.apply_updates(params, updates)
        ema = jax.tree_util.tree_map(
            lambda e, p: decay * e + (1.0 - decay) * p,
            state.ema, new_params,
        )
        return updates, EmaState(ema=ema)

    return optax.GradientTransformation(init_fn, update_fn)


def ema_params(opt_state) -> Optional[Any]:
    """The EMA tree from an opt_state built with ema_decay > 0 (None
    when EMA wasn't enabled)."""
    if isinstance(opt_state, EmaState):
        return opt_state.ema
    if isinstance(opt_state, tuple):
        for s in opt_state:
            found = ema_params(s)
            if found is not None:
                return found
    return None


def make_train_step(
    cfg: ModelConfig, mesh: Mesh, learning_rate: float = 1e-3,
    accum_steps: int = 1, ema_decay: float = 0.0,
    master_weights: bool = False, zero1: bool = False,
):
    """(params, opt_state, tokens) -> (params, opt_state, loss), jit'd over
    the mesh with real dp/sp/tp shardings.

    accum_steps > 1 enables gradient accumulation: tokens gain a leading
    micro-batch axis [accum, batch, seq+1], a lax.scan runs the
    forward/backward per micro-batch summing f32 gradients, and ONE
    optimizer update applies their mean — the effective batch grows
    accum× while activation HBM stays at one micro-batch (the grad
    accumulator costs one extra f32 param copy). For dense models the
    result equals the fused batch up to summation order (pinned by
    test); MoE models route/cap per micro-batch, so the aux loss and
    capacity drops are micro-batch-local by construction.

    learning_rate may be a float or any optax schedule (a callable
    step -> lr), e.g. optax.warmup_cosine_decay_schedule — adamw
    threads it through; the step count lives in the optimizer state,
    so checkpoint resume continues the schedule where it left off.

    ema_decay > 0 keeps an exponential moving average of the params
    inside the optimizer state (extract with ema_params(opt_state);
    serve/export the smoothed weights). Costs one param-shaped f32
    tree of HBM.

    master_weights=True stores the LIVE params in cfg.dtype (bf16 on
    TPU) and keeps f32 masters inside opt_state: the forward/backward
    read half the weight HBM and the per-step f32->bf16 weight casts
    disappear (the compute path already ran in cfg.dtype via wdense —
    storing rounded weights reads the same values the casts produced).
    The optimizer updates the f32 masters, then the step re-rounds
    them into the live tree; opt_state becomes (inner_state, masters).

    zero1=True shards the optimizer state — adamw moments, masters,
    EMA — over the "dp" mesh axis (ZeRO-1): each dp rank keeps 1/dp of
    the optimizer HBM and XLA's partitioner turns the elementwise
    update into shard-local math plus an all-gather of the fresh
    params. Gradients are already dp-replicated by the psum, so the
    math is unchanged — pinned by a loss-equality test."""
    optimizer = optax.adamw(learning_rate)
    if not 0.0 <= ema_decay < 1.0:
        # decay == 1.0 would freeze the EMA at init forever; validate
        # unconditionally (an assert vanishes under python -O and the
        # frozen EMA would silently export untrained weights)
        raise ValueError(f"ema_decay must be in [0, 1), got {ema_decay}")
    if ema_decay > 0.0:
        optimizer = optax.chain(
            optimizer, _ema_transform(ema_decay)
        )
    p_shard = _full_param_shardings(mesh, cfg)
    # Input tokens carry seq_len+1 (targets are the shift-by-one), which is
    # rarely divisible by sp — shard them on dp only; the activation
    # constraint below shards the model-visible seq_len over sp.
    data_shard = NamedSharding(
        mesh,
        P("dp", None) if accum_steps == 1 else P(None, "dp", None),
    )
    act_shard = NamedSharding(mesh, P("dp", "sp", None))
    repl = NamedSharding(mesh, P())

    def loss_fn(params, tokens):
        logits, aux = forward_with_aux(params, tokens[:, :-1], cfg,
                                       activation_sharding=act_shard)
        targets = tokens[:, 1:]
        # optax computes the stable logsumexp-minus-target form, which
        # avoids materializing a full fp32 log-softmax over the vocab
        # (measured ~2% step time on v5e at vocab 32k).
        nll = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), targets
        )
        return jnp.mean(nll) + cfg.moe_aux_coef * aux

    def step(params, opt_state, tokens):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        else:
            def micro(carry, mtokens):
                gsum, lsum = carry
                mloss, grads = jax.value_and_grad(loss_fn)(
                    params, mtokens
                )
                gsum = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), gsum, grads
                )
                return (gsum, lsum + mloss), None

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
            (gsum, lsum), _ = jax.lax.scan(
                micro, (zeros, jnp.float32(0.0)), tokens
            )
            # Under master_weights the f32 accumulator feeds the f32
            # optimizer DIRECTLY — rounding it through the bf16 live
            # dtype here would throw away exactly the small summed
            # components the accumulator exists to keep. Otherwise
            # cast back to each param's dtype (no-op for f32 params)
            # so the opt_state avals stay stable.
            if master_weights:
                grads = jax.tree_util.tree_map(
                    lambda g: g / accum_steps, gsum
                )
            else:
                grads = jax.tree_util.tree_map(
                    lambda g, pp: (g / accum_steps).astype(pp.dtype),
                    gsum, params,
                )
            loss = lsum / accum_steps
        if master_weights:
            inner, masters = opt_state
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads
            )
            updates, inner = optimizer.update(grads, inner, masters)
            masters = optax.apply_updates(masters, updates)
            # re-round the masters into the live (cfg.dtype) tree —
            # the ONLY f32->bf16 traffic in the step
            params = jax.tree_util.tree_map(
                lambda m, p: m.astype(p.dtype), masters, params
            )
            opt_state = (inner, masters)
        else:
            updates, opt_state = optimizer.update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def stored(p):
        """Live-tree dtype policy: cfg.dtype under master_weights."""
        return p.astype(cfg.dtype) if master_weights else p

    def opt_init(params):
        """Full optimizer state for the stored params: plain optax
        state, or (inner_state, f32 masters) under master_weights."""
        if not master_weights:
            return optimizer.init(params)
        masters = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32), params
        )
        return (optimizer.init(masters), masters)

    # Optimizer-state shardings must be pinned explicitly: with
    # out_shardings=None XLA may re-shard a replicated param's moment (or
    # the param itself) between steps, and the next call's in_shardings
    # then mismatch. The state embeds param-shaped subtrees (adamw's
    # mu/nu, the EMA, the f32 masters), so map each opt leaf whose
    # key-path *ends with* a param path to that param's sharding —
    # further sharded over "dp" when zero1 is on — everything else
    # (step counts) replicated.
    params_struct = jax.eval_shape(
        lambda k: jax.tree_util.tree_map(
            stored, init_params(cfg, k)
        ),
        jax.random.key(0),
    )
    opt_struct = jax.eval_shape(opt_init, params_struct)
    param_paths = {
        tuple(str(k) for k in path): shard
        for path, shard in jax.tree_util.tree_flatten_with_path(p_shard)[0]
    }
    dp_size = mesh.shape.get("dp", 1)

    def zero1_shard(shard, shape):
        """Add "dp" to the first unsharded axis the dp size divides;
        a leaf with no such axis stays at the param's sharding (its
        HBM is then replicated — logged nowhere because the big
        2D/3D weights always have one)."""
        parts = list(shard.spec) + [None] * (
            len(shape) - len(shard.spec)
        )
        for i, (ax, dim) in enumerate(zip(parts, shape)):
            if ax is None and dim % dp_size == 0 and dim >= dp_size:
                parts[i] = "dp"
                return NamedSharding(mesh, P(*parts))
        return shard

    def opt_leaf_sharding(path, leaf):
        keys = tuple(str(k) for k in path)
        for ppath, shard in param_paths.items():
            if len(keys) >= len(ppath) and keys[-len(ppath):] == ppath:
                if zero1 and dp_size > 1:
                    return zero1_shard(shard, leaf.shape)
                return shard
        return repl

    o_shard = jax.tree_util.tree_map_with_path(opt_leaf_sharding, opt_struct)

    def init_all(key):
        params = jax.jit(
            lambda k: jax.tree_util.tree_map(
                stored, init_params(cfg, k)
            ),
            out_shardings=p_shard,
        )(key)
        opt_state = jax.jit(opt_init, out_shardings=o_shard)(params)
        return params, opt_state

    train_step = jax.jit(
        step,
        in_shardings=(p_shard, o_shard, data_shard),
        out_shardings=(p_shard, o_shard, repl),
        donate_argnums=(0, 1),
    )
    return train_step, init_all, optimizer


def make_eval_fn(cfg: ModelConfig, mesh: Mesh):
    """(params, tokens [b, seq+1]) -> mean NLL, jit'd over the mesh.

    Pure next-token cross-entropy — no optimizer, no MoE aux term (aux
    is a ROUTING regularizer; quoting it in an eval number would let
    router balance shifts masquerade as modeling progress)."""
    p_shard = _full_param_shardings(mesh, cfg)
    data_shard = NamedSharding(mesh, P("dp", None))
    act_shard = NamedSharding(mesh, P("dp", "sp", None))
    repl = NamedSharding(mesh, P())

    def eval_loss(params, tokens):
        logits, _ = forward_with_aux(
            params, tokens[:, :-1], cfg, activation_sharding=act_shard
        )
        nll = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), tokens[:, 1:]
        )
        return jnp.mean(nll)

    return jax.jit(
        eval_loss,
        in_shardings=(p_shard, data_shard),
        out_shardings=repl,
    )


def make_forward(cfg: ModelConfig):
    """Single-device jittable forward (graft entry())."""

    def fn(params, tokens):
        return forward(params, tokens, cfg)

    return fn
