"""Weight-only int8 quantization for the decode path.

Decode throughput on TPU is bound by HBM bandwidth: every generated
token re-reads the full parameter set, so bytes-per-weight is the
denominator of tokens/s. Storing the big matmul weights as int8 with a
per-output-channel f32 scale halves the read traffic vs bfloat16 while
keeping the matmul itself in bf16 on the MXU. The dequant is
weight-side (`q -> f32 * scale -> bf16` feeding the einsum); the
int8-sized HBM read relies on XLA fusing that convert+multiply into
the matmul's operand pipeline rather than materializing the
dequantized weight — the standard XLA weight-only pattern.

Design:
- Symmetric per-channel quantization (no zero point), scale on the
  OUTPUT feature axis of each matmul (the finest granularity that
  keeps one scale per accumulator column).
- Quantized params mirror the float pytree exactly, with each selected
  weight leaf replaced by ``{"q": int8, "s": f32}``; every other leaf
  (norm scales, embeddings' position table) passes through untouched.
  ``wdense`` resolves either form, so forward code handles both pytrees
  with one accessor.
- The token embedding table is quantized per-row (vocab axis): a gather
  of int8 rows + scale is exact the same way.

No reference counterpart: the reference agent
(/root/reference/pkg/...) has no model/inference code; this is part of
the TPU-side workload stack (SURVEY.md §5.7's long-context/workload
enabler family).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

# Leaf names eligible for quantization, with the axis index (or indices)
# of the OUTPUT features in that weight's einsum. Everything else (norm
# scales, pos_embed) stays float.
#   wqkv [d, 3, n, h] -> out axes (1, 2, 3)
#   wq   [d, n, h]    -> out axes (1, 2)
#   wkv  [d, 2, g, h] -> out axes (1, 2, 3)
#   wo   [n, h, d]    -> out axis 2
#   w1   [d, f]       -> out axis 1
#   w2   [f, d]       -> out axis 1
#   lm_head [d, v]    -> out axis 1
#   embed [v, d]      -> per-row (axis 0 is the gather axis)
_OUT_AXES = {
    "wqkv": (1, 2, 3),
    "wq": (1, 2),
    "wkv": (1, 2, 3),
    "wo": (2,),
    "w1": (1,),
    "w2": (1,),
    "lm_head": (1,),
    "embed": (0,),
}

# The MoE subtree (layer["moe"], moe.init_moe_params) nests under its
# own key with 3-D expert stacks. The router ``wg`` stays float: its
# argmax decides expert assignment, and quantization noise there flips
# routing decisions rather than perturbing activations smoothly.
#   w1 [E, d, f] -> per (expert, out-col)
#   w2 [E, f, d] -> per (expert, out-col)
_MOE_OUT_AXES = {
    "w1": (0, 2),
    "w2": (0, 2),
}


def quantize_weight(w: jax.Array, out_axes) -> Dict[str, jax.Array]:
    """Symmetric int8 over the non-out axes; scale shaped to out axes."""
    w = w.astype(jnp.float32)
    reduce_axes = tuple(
        a for a in range(w.ndim) if a not in out_axes
    )
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale.astype(jnp.float32)}


def dequantize_weight(qw: Dict[str, jax.Array], dtype=jnp.bfloat16):
    """int8 + scale -> dtype. The convert+multiply fuses into the
    consuming einsum under jit; the HBM read stays int8-sized."""
    return (qw["q"].astype(jnp.float32) * qw["s"]).astype(dtype)


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and set(leaf) == {"q", "s"}


def wdense(container: Dict, name: str, dtype=jnp.bfloat16) -> jax.Array:
    """Resolve a weight from either a float or quantized params tree."""
    leaf = container[name]
    if is_quantized(leaf):
        return dequantize_weight(leaf, dtype)
    return leaf.astype(dtype)


def embed_lookup(params: Dict, tokens: jax.Array, dtype=jnp.bfloat16):
    """Token-embedding gather for either params form. Quantized: gather
    the int8 rows and their per-row scales, multiply after the gather —
    HBM reads stay int8-sized and the result is exact per-row dequant."""
    leaf = params["embed"]
    if is_quantized(leaf):
        rows = leaf["q"][tokens].astype(jnp.float32)
        scales = leaf["s"][tokens]  # [..., 1] keepdims broadcast
        return (rows * scales).astype(dtype)
    return leaf.astype(dtype)[tokens]


def quantize_kv(x: jax.Array) -> Dict[str, jax.Array]:
    """Symmetric int8 quantization of K/V cache entries, one f32 scale
    per POSITION (amax over the trailing head_dim axis). The serving
    engine's paged pool stores ``{"q": int8 [..., h], "s": f32
    [..., 1]}`` per pool entry: reads shrink ~4x (f32 models) and the
    per-position scale keeps the dequant a fused gather+multiply, the
    same shape as embed_lookup's row dequant."""
    x = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


def dequantize_kv(qkv: Dict[str, jax.Array]) -> jax.Array:
    """Inverse of quantize_kv (f32 out; exact per-position dequant)."""
    return qkv["q"].astype(jnp.float32) * qkv["s"]


def quantize_params(params: Dict) -> Dict:
    """Quantize every eligible leaf of a transformer params tree
    (init_params shape, transformer.py). Returns a new tree; the input
    is not modified."""

    def qleaf(name: str, leaf, axes_table):
        axes = axes_table.get(name)
        if axes is None or not hasattr(leaf, "ndim"):
            return leaf
        return quantize_weight(leaf, axes)

    def qlayer(layer: Dict) -> Dict:
        out = {k: qleaf(k, v, _OUT_AXES) for k, v in layer.items()}
        if "moe" in layer:
            out["moe"] = {
                k: qleaf(k, v, _MOE_OUT_AXES)
                for k, v in layer["moe"].items()
            }
        return out

    out: Dict[str, Any] = {}
    for name, leaf in params.items():
        if name == "layers":
            out["layers"] = [qlayer(layer) for layer in leaf]
        else:
            out[name] = qleaf(name, leaf, _OUT_AXES)
    return out


def dequantize_params(qparams: Dict, dtype=jnp.float32) -> Dict:
    """Inverse of quantize_params for any tree shape: every quantized
    leaf back to dtype, everything else passed through."""
    return jax.tree_util.tree_map(
        lambda leaf: (
            dequantize_weight(leaf, dtype) if is_quantized(leaf) else leaf
        ),
        qparams,
        is_leaf=is_quantized,
    )


def quantized_bytes(params: Dict) -> int:
    """Total parameter bytes as stored (int8 leaves count 1B + scales)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * leaf.dtype.itemsize
    return total
