"""Mixture-of-Experts layer with expert parallelism (the "ep" mesh axis).

TPU-first MoE, the GShard/Switch recipe rebuilt for GSPMD:

- **Static shapes end to end.** Routing is top-1 with a fixed per-expert
  capacity ``C = ceil(T * capacity_factor / E)``; overflow tokens are
  dropped (their residual stream passes through). No gather/scatter with
  data-dependent shapes — dispatch and combine are one-hot einsums the MXU
  eats directly and XLA can partition.
- **Expert parallelism by sharding, not by hand.** Expert weights are
  sharded over the mesh's "ep" axis (optionally also "tp" on the hidden
  dim); the dispatched activations [E, C, d] carry a
  with_sharding_constraint on "ep". XLA's SPMD partitioner inserts the
  token all-to-alls over ICI — no collective is written here.
- **Router in fp32** (softmax stability), matmuls in the model dtype
  (bfloat16 on TPU).

The reference repo has no model code at all (SURVEY.md §2); this module
exists so the agent's graded multi-host configs have a first-class
expert-parallel workload to schedule.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .quantize import wdense


def init_moe_params(
    key: jax.Array, d_model: int, d_ff: int, n_experts: int
) -> Dict:
    """{"wg": [d,E], "w1": [E,d,ff], "w2": [E,ff,d]} in fp32."""
    kg, k1, k2 = jax.random.split(key, 3)
    init = jax.nn.initializers.normal(0.02)
    return {
        "wg": init(kg, (d_model, n_experts), jnp.float32),
        "w1": init(k1, (n_experts, d_model, d_ff), jnp.float32),
        "w2": init(k2, (n_experts, d_ff, d_model), jnp.float32),
    }


def moe_param_shardings(mesh: Mesh) -> Dict:
    """Experts over "ep"; expert-hidden over "tp" (composable ep x tp)."""

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        "wg": ns(),                      # router: small, replicated
        "w1": ns("ep", None, "tp"),
        "w2": ns("ep", "tp", None),
    }


def expert_capacity(
    n_tokens: int, n_experts: int, capacity_factor: float
) -> int:
    return max(1, math.ceil(n_tokens * capacity_factor / n_experts))


class MoeRoutingStats:
    """Host-side routing observability for the MoE layer.

    ``moe_mlp`` is a pure function on the compiled path, so routing
    counters cannot live inside it without polluting the jaxpr.
    Instead, callers hand each batch to ``observe()`` which re-runs the
    (cheap, fp32, host-side) top-1 router on the SAME inputs and
    accumulates expert load, capacity-overflow drops, and the load
    imbalance — the ledger ``ServingEngine.stats()['moe']`` and the
    ``elastic_tpu_serving_moe_*`` gauges read. Attach an instance as
    ``engine.moe_stats`` (or call directly from a bench loop).
    """

    def __init__(self) -> None:
        self.batches = 0
        self.tokens_routed = 0
        self.dropped_tokens = 0
        self._expert_load: Optional[np.ndarray] = None
        self._aux_loss_sum = 0.0

    def observe(
        self,
        x: jax.Array,
        params: Dict,
        capacity_factor: float,
        aux_loss: Optional[float] = None,
    ) -> None:
        """Recompute the top-1 routing decision for one batch [b, s, d]
        (or [t, d]) and fold it into the ledgers."""
        xt = np.asarray(x, dtype=np.float32)
        if xt.ndim == 3:
            xt = xt.reshape(-1, xt.shape[-1])
        wg = np.asarray(params["wg"], dtype=np.float32)
        n_experts = wg.shape[1]
        t = xt.shape[0]
        cap = expert_capacity(t, n_experts, capacity_factor)
        logits = xt @ wg
        expert_index = np.argmax(logits, axis=-1)
        load = np.bincount(expert_index, minlength=n_experts)
        if self._expert_load is None:
            self._expert_load = np.zeros(n_experts, dtype=np.int64)
        self._expert_load[: len(load)] += load
        self.batches += 1
        self.tokens_routed += t
        self.dropped_tokens += int(np.maximum(load - cap, 0).sum())
        if aux_loss is not None:
            self._aux_loss_sum += float(aux_loss)

    def stats(self) -> Dict:
        load = self._expert_load
        imbalance = None
        if load is not None and load.sum() > 0:
            imbalance = float(load.max() / max(load.mean(), 1e-9))
        return {
            "experts": 0 if load is None else int(len(load)),
            "batches": self.batches,
            "tokens_routed": self.tokens_routed,
            "dropped_tokens": self.dropped_tokens,
            "drop_rate": (
                round(self.dropped_tokens / self.tokens_routed, 4)
                if self.tokens_routed else None
            ),
            "imbalance": (
                round(imbalance, 4) if imbalance is not None else None
            ),
            "expert_load": (
                [] if load is None else [int(v) for v in load]
            ),
            "aux_loss_mean": (
                round(self._aux_loss_sum / self.batches, 4)
                if self.batches else None
            ),
        }


def moe_mlp(
    x: jax.Array,
    params: Dict,
    capacity_factor: float,
    mesh: Mesh = None,
) -> Tuple[jax.Array, jax.Array]:
    """[b, s, d] -> ([b, s, d], aux_loss).

    aux_loss is the Switch load-balancing term
    ``E * sum_e(f_e * p_e)`` (fraction routed * mean router prob); it is 1.0
    at perfect balance and must be added to the training loss with a small
    coefficient or the router collapses onto one expert.
    """
    b, s, d = x.shape
    n_experts = params["wg"].shape[1]
    dtype = x.dtype
    xt = x.reshape(b * s, d)
    t = b * s
    cap = expert_capacity(t, n_experts, capacity_factor)

    # -- router (fp32) --
    logits = jnp.einsum(
        "td,de->te", xt.astype(jnp.float32), params["wg"]
    )
    probs = jax.nn.softmax(logits, axis=-1)                       # [T, E]
    expert_index = jnp.argmax(probs, axis=-1)                     # [T]
    expert_mask = jax.nn.one_hot(expert_index, n_experts,
                                 dtype=jnp.float32)               # [T, E]

    # Switch aux loss: fraction of tokens vs mean prob per expert.
    density = jnp.mean(expert_mask, axis=0)                       # [E]
    density_prob = jnp.mean(probs, axis=0)                        # [E]
    aux_loss = n_experts * jnp.sum(density * density_prob)

    # -- capacity assignment (static C; overflow drops) --
    # Slot bookkeeping runs in int32: a float32 cumsum loses exactness once
    # token counts approach 2^24, silently colliding slots at huge b*s.
    imask = expert_mask.astype(jnp.int32)
    position = jnp.cumsum(imask, axis=0) * imask                  # [T, E] 1-idx
    within = position <= cap
    imask = imask * within
    expert_mask = imask.astype(jnp.float32)
    gate = jnp.sum(probs * expert_mask, axis=-1)                  # [T]
    slot = jnp.sum((position - 1) * imask, axis=-1)               # [T] 0-idx
    slot_hot = jax.nn.one_hot(slot, cap, dtype=jnp.float32)       # [T, C]
    dispatch = (expert_mask[:, :, None] * slot_hot[:, None, :])   # [T, E, C]
    combine = (dispatch * gate[:, None, None]).astype(dtype)
    dispatch = dispatch.astype(dtype)

    # -- expert compute ([E, C, d] sharded on ep; XLA inserts all-to-all) --
    xin = jnp.einsum("tec,td->ecd", dispatch, xt)
    if mesh is not None:
        xin = jax.lax.with_sharding_constraint(
            xin, NamedSharding(mesh, P("ep", None, None))
        )
    h = jnp.einsum("ecd,edf->ecf", xin, wdense(params, "w1", dtype))
    h = jax.nn.gelu(h)
    out = jnp.einsum("ecf,efd->ecd", h, wdense(params, "w2", dtype))
    if mesh is not None:
        out = jax.lax.with_sharding_constraint(
            out, NamedSharding(mesh, P("ep", None, None))
        )
    y = jnp.einsum("tec,ecd->td", combine, out)
    # Dropped tokens contribute zero here; the caller's residual connection
    # carries their stream through unchanged (standard Switch behavior).
    return y.reshape(b, s, d), aux_loss.astype(jnp.float32)
