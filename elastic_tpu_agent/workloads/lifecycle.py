"""In-pod lifecycle watcher: the workload's half of the migration handshake.

The agent *signals* checkpoint-restore everywhere — ``ELASTIC_TPU_DRAIN``
/``_DEADLINE`` restamped into alloc specs on a drain, a bumped
``ELASTIC_TPU_SLICE_EPOCH`` on slice reform, ``ELASTIC_TPU_THROTTLE``
deadlines on QoS escalation — but until this module nothing inside the
pod *listened*: the runner only checkpointed on SIGTERM or a step
schedule, and the agent reclaimed blind at the deadline. Funky's
cloud-native FPGA orchestration (PAPERS.md) makes the
cordon→checkpoint→migrate→reclaim sequence a runtime-owned lifecycle;
this watcher is the pod-side participant that turns signal-and-hope into
a verified handshake:

1. :class:`LifecycleWatcher` polls the pod's own **alloc-spec file**
   (``<alloc dir>/<TPU hash>.json`` — the same hostPath-shared surface
   the usage self-reports ride) for drain signals, throttle deadlines
   and slice-epoch bumps. The env *file* the OCI hook wrote at container
   start is a boot-time snapshot; mid-run restamps only ever land in the
   spec, so the spec is what a live workload must watch.
2. On a signal edge the caller checkpoints (runner: a
   ``TrainCheckpointer`` save; serving: drain in-flight requests via
   :func:`drain_serving`).
3. :func:`write_checkpoint_ack` publishes an atomic
   ``<alloc dir>/ack/<TPU hash>.json`` — checkpoint step, directory
   digest, wall time — with the same fixed-temp-name rename pattern as
   the usage reports, so the agent's MigrationCoordinator can complete
   the drain *early* (reclaim the moment the checkpoint is durable
   instead of at the deadline) and publish a MigrationRecord the
   replacement pod restores from.
4. A replacement pod finds ``ELASTIC_TPU_RESTORE_DIR``/``_RESTORE_STEP``
   stamped by the destination agent, restores, and acks again
   (``kind="resume"``) so the destination can *verify* the resume
   (step ≥ acked step, world size == current slice).

Dependency-free (json/os/time only) and never load-bearing: every file
operation swallows errors — a full disk must not fail a train step.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Callable, Dict, Optional

logger = logging.getLogger(__name__)

# Env fallbacks for the watcher's identity: the allocation hash the
# agent injected (TPU, with the legacy GPU spelling accepted like the
# native hook does) and the shared alloc dir (the native hook's own
# override env, hostPath-mounted into cooperating pods).
ENV_ALLOC_DIR = "ELASTIC_TPU_ALLOC_DIR"

DEFAULT_POLL_INTERVAL_S = 1.0

# Signal kinds, in escalation order.
SIGNAL_DRAIN = "drain"        # ELASTIC_TPU_DRAIN appeared/changed
SIGNAL_CUTOVER = "cutover"    # ELASTIC_TPU_CUTOVER stamped (pre-copy end)
SIGNAL_THROTTLE = "throttle"  # ELASTIC_TPU_THROTTLE deadline armed
SIGNAL_REFORM = "reform"      # ELASTIC_TPU_SLICE_EPOCH bumped


class Signal:
    """One observed lifecycle signal edge."""

    __slots__ = ("kind", "value", "deadline_ts", "epoch", "env")

    def __init__(self, kind, value="", deadline_ts=None, epoch=None,
                 env=None):
        self.kind = kind
        self.value = value
        self.deadline_ts = deadline_ts
        self.epoch = epoch
        self.env = dict(env or {})

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Signal(kind={self.kind!r}, value={self.value!r}, "
                f"deadline_ts={self.deadline_ts}, epoch={self.epoch})")


def _env_float(env: Dict[str, str], key: str) -> Optional[float]:
    try:
        return float(env[key])
    except (KeyError, TypeError, ValueError):
        return None


def world_size_of(env: Dict[str, str]) -> int:
    """The slice world size this pod's stamped env describes (hosts in
    ``TPU_WORKER_HOSTNAMES``, 1 when unset) — what a resume ack reports
    so the agent can verify the restart happened at the CURRENT world."""
    hosts = [h for h in (env.get("TPU_WORKER_HOSTNAMES") or "").split(",")
             if h]
    return max(1, len(hosts))


def checkpoint_digest(directory: str, max_files: int = 4096) -> str:
    """Stable content-identity digest of a checkpoint directory: a
    blake2b over the sorted (relative path, size) listing. Cheap (no
    data reads — orbax files are GBs), dependency-free, and enough for
    the handshake's purpose: the destination can detect that the
    directory it restores from is the one the source acked, not a
    half-written or later-mutated tree."""
    h = hashlib.blake2b(digest_size=16)
    entries = []
    try:
        for root, dirs, files in os.walk(directory):
            dirs.sort()
            for name in sorted(files):
                path = os.path.join(root, name)
                try:
                    size = os.stat(path).st_size
                except OSError:
                    size = -1
                entries.append((os.path.relpath(path, directory), size))
                if len(entries) >= max_files:
                    raise StopIteration
    except StopIteration:
        pass
    except OSError:
        return ""
    for rel, size in entries:
        h.update(rel.encode("utf-8", "replace"))
        h.update(str(size).encode())
        h.update(b"\0")
    return h.hexdigest()


def write_checkpoint_ack(
    alloc_spec_dir: str,
    alloc_hash: str,
    step: Optional[int],
    checkpoint_dir: str = "",
    kind: str = "checkpoint",
    signal: str = "",
    world_size: Optional[int] = None,
    epoch: Optional[int] = None,
    digest: Optional[str] = None,
    ts: Optional[float] = None,
    extra: Optional[Dict] = None,
) -> bool:
    """Publish the workload's checkpoint acknowledgement to the agent.

    The durable half of the handshake: written only AFTER the checkpoint
    is committed (``TrainCheckpointer.wait()`` returned, or the serving
    engine drained), so an ack on disk means the work is safe and the
    agent may reclaim the chips. Atomic (fixed temp name + rename, the
    usage-report pattern — one writer per hash, crash debris reclaimed
    by the next write and the spec GC), never raises. Returns True when
    the ack landed.

    ``extra`` merges additional JSON-safe fields into the payload
    without shadowing the contract keys — the pre-copy protocol rides
    here (``round``/``delta_bytes``/``total_bytes`` on ``kind="precopy"``
    acks, ``precopy_rounds``/``full_bytes``/``cutover_ms`` on the final
    cutover ack).
    """
    from ..common import AckSubdir

    ack_dir = os.path.join(alloc_spec_dir, AckSubdir)
    path = os.path.join(ack_dir, f"{alloc_hash}.json")
    tmp = f"{path}.tmp"
    payload = {}
    if extra:
        payload.update({
            k: v for k, v in extra.items() if isinstance(k, str)
        })
    payload.update({
        "ts": time.time() if ts is None else ts,
        "kind": kind,
        "step": step,
        "checkpoint_dir": checkpoint_dir,
        "digest": (
            digest if digest is not None
            else (checkpoint_digest(checkpoint_dir) if checkpoint_dir
                  else "")
        ),
    })
    if signal:
        payload["signal"] = signal
    if world_size is not None:
        payload["world_size"] = int(world_size)
    if epoch is not None:
        payload["epoch"] = int(epoch)
    try:
        os.makedirs(ack_dir, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return True
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def read_checkpoint_ack(
    alloc_spec_dir: str, alloc_hash: str
) -> Optional[dict]:
    """The agent-side reader (MigrationCoordinator): the pod's newest
    ack, or None when absent/torn."""
    from ..common import AckSubdir

    try:
        with open(os.path.join(
            alloc_spec_dir, AckSubdir, f"{alloc_hash}.json"
        )) as f:
            ack = json.load(f)
    except (OSError, ValueError):
        return None
    return ack if isinstance(ack, dict) else None


class LifecycleWatcher:
    """Poll the pod's alloc-spec env for checkpoint-restore signals.

    ``alloc_spec_dir``/``alloc_hash`` default from the environment
    (``ELASTIC_TPU_ALLOC_DIR`` and the agent-injected ``TPU`` hash, with
    the legacy ``GPU`` spelling accepted); a pod outside the agent
    contract simply gets an inert watcher (``enabled`` False, ``poll``
    always None) so callers can weave it in unconditionally.

    ``checkpoint_fn(signal) -> (step, checkpoint_dir)`` is optional: when
    set, :meth:`poll` handles a signal end-to-end (checkpoint + ack) and
    the caller only decides whether to exit. Without it the caller
    checkpoints itself and calls :meth:`ack`.

    Edge semantics: each distinct drain trigger, throttle value and
    slice epoch fires ONCE (the agent re-asserts the stamp every tick;
    re-reading the same value must not re-checkpoint every poll).
    """

    def __init__(
        self,
        alloc_spec_dir: Optional[str] = None,
        alloc_hash: Optional[str] = None,
        checkpoint_fn: Optional[Callable[[Signal], tuple]] = None,
        poll_interval_s: float = DEFAULT_POLL_INTERVAL_S,
        time_fn: Callable[[], float] = time.monotonic,
    ) -> None:
        from ..common import (
            EnvAllocationHash,
            EnvAllocationHashCompat,
        )

        self.alloc_spec_dir = (
            alloc_spec_dir if alloc_spec_dir is not None
            else os.environ.get(ENV_ALLOC_DIR, "")
        )
        self.alloc_hash = (
            alloc_hash if alloc_hash is not None
            else (os.environ.get(EnvAllocationHash)
                  or os.environ.get(EnvAllocationHashCompat, ""))
        )
        self.checkpoint_fn = checkpoint_fn
        self.poll_interval_s = poll_interval_s
        self._time = time_fn
        self._next_poll = 0.0
        self._seen_drain: Optional[str] = None
        self._drain_active = False  # env carries a drain stamp NOW
        self._seen_cutover: Optional[str] = None
        self._seen_throttle: Optional[str] = None
        self._seen_epoch: Optional[int] = None
        self._epoch_armed = False  # first sighting sets the baseline
        self.signals_seen = 0
        self.acks_written = 0
        self.last_signal: Optional[Signal] = None

    @property
    def enabled(self) -> bool:
        return bool(self.alloc_spec_dir and self.alloc_hash)

    @property
    def draining(self) -> bool:
        """True while the spec env CARRIES a drain stamp (as of the
        last poll); a ServingEngine built with ``lifecycle=`` refuses
        new admissions while this holds. Deliberately NOT derived from
        ``last_signal``: a later throttle or reform edge must not
        reopen admissions on a node whose chips are going away — only
        the drain stamp actually clearing (cancelled drain) does."""
        return self._drain_active

    # -- reading the contract surfaces ----------------------------------------

    def read_env(self) -> Dict[str, str]:
        """The pod's CURRENT stamped env: the alloc-spec file's env map
        (mid-run restamps land there), {} when unreadable."""
        if not self.enabled:
            return {}
        try:
            with open(os.path.join(
                self.alloc_spec_dir, f"{self.alloc_hash}.json"
            )) as f:
                spec = json.load(f)
        except (OSError, ValueError):
            return {}
        env = spec.get("env") if isinstance(spec, dict) else None
        return dict(env) if isinstance(env, dict) else {}

    def restore_request(self) -> Optional[dict]:
        """The destination agent's restore stamp, if any:
        {"checkpoint_dir", "step", "trace"} from
        ELASTIC_TPU_RESTORE_DIR/_STEP/_TRACE (spec env first, ambient
        env fallback for the boot snapshot the hook applied)."""
        from ..common import EnvRestoreDir, EnvRestoreStep, EnvRestoreTrace

        env = self.read_env()
        directory = env.get(EnvRestoreDir) or os.environ.get(
            EnvRestoreDir, ""
        )
        if not directory:
            return None
        step_raw = env.get(EnvRestoreStep) or os.environ.get(
            EnvRestoreStep, ""
        )
        try:
            step = int(step_raw)
        except (TypeError, ValueError):
            step = None
        return {
            "checkpoint_dir": directory,
            "step": step,
            "trace": env.get(EnvRestoreTrace)
            or os.environ.get(EnvRestoreTrace, ""),
        }

    # -- polling --------------------------------------------------------------

    def _detect(self, env: Dict[str, str]) -> Optional[Signal]:
        from ..common import (
            EnvCutover,
            EnvDrain,
            EnvDrainDeadline,
            EnvSliceEpoch,
            EnvThrottle,
            EnvThrottleDeadline,
        )

        drain = env.get(EnvDrain)
        self._drain_active = bool(drain)
        if drain and drain != self._seen_drain:
            self._seen_drain = drain
            return Signal(
                SIGNAL_DRAIN, value=drain,
                deadline_ts=_env_float(env, EnvDrainDeadline), env=env,
            )
        if not drain:
            self._seen_drain = None  # cancelled drain re-arms the edge
        # Cutover outranks everything below: it arrives only mid-drain
        # (the drain edge already fired) and ends the pre-copy stream —
        # the workload must pause, ship the final delta and ack NOW.
        cutover = env.get(EnvCutover)
        if cutover and cutover != self._seen_cutover:
            self._seen_cutover = cutover
            return Signal(
                SIGNAL_CUTOVER, value=cutover,
                deadline_ts=_env_float(env, EnvDrainDeadline), env=env,
            )
        if not cutover:
            self._seen_cutover = None  # cancelled drain re-arms the edge
        throttle = env.get(EnvThrottle)
        if throttle and throttle != self._seen_throttle:
            self._seen_throttle = throttle
            return Signal(
                SIGNAL_THROTTLE, value=throttle,
                deadline_ts=_env_float(env, EnvThrottleDeadline), env=env,
            )
        if not throttle:
            self._seen_throttle = None
        epoch_raw = env.get(EnvSliceEpoch)
        if epoch_raw is not None:
            try:
                epoch = int(epoch_raw)
            except (TypeError, ValueError):
                epoch = None
            if epoch is not None:
                if not self._epoch_armed:
                    # The epoch the pod STARTED at is its baseline, not
                    # a reform: only a bump after first sight signals.
                    self._epoch_armed = True
                    self._seen_epoch = epoch
                elif self._seen_epoch is not None and epoch > self._seen_epoch:
                    self._seen_epoch = epoch
                    return Signal(
                        SIGNAL_REFORM, value=str(epoch), epoch=epoch,
                        env=env,
                    )
                else:
                    self._seen_epoch = epoch
        return None

    def poll(self, force: bool = False) -> Optional[Signal]:
        """Check for a NEW signal (rate-limited to ``poll_interval_s``;
        ``force`` skips the limiter). When ``checkpoint_fn`` is set, a
        detected signal is handled inline: the callback checkpoints and
        returns ``(step, checkpoint_dir)``, and the ack is written
        before poll() returns the signal — so by the time the caller
        sees it, the handshake's pod half is already done."""
        if not self.enabled:
            return None
        now = self._time()
        if not force and now < self._next_poll:
            return None
        self._next_poll = now + self.poll_interval_s
        env = self.read_env()
        if not env:
            return None
        sig = self._detect(env)
        if sig is None:
            return None
        self.signals_seen += 1
        self.last_signal = sig
        logger.warning(
            "lifecycle: %s signal (%s; deadline_ts=%s epoch=%s)",
            sig.kind, sig.value, sig.deadline_ts, sig.epoch,
        )
        if self.checkpoint_fn is not None:
            try:
                step, ckpt_dir = self.checkpoint_fn(sig)
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.exception("lifecycle: checkpoint callback failed")
                return sig
            self.ack(
                step, checkpoint_dir=ckpt_dir, signal=sig.value,
                world_size=world_size_of(env), epoch=sig.epoch,
            )
        return sig

    # -- acknowledging --------------------------------------------------------

    def ack(
        self,
        step: Optional[int],
        checkpoint_dir: str = "",
        kind: str = "checkpoint",
        signal: str = "",
        world_size: Optional[int] = None,
        epoch: Optional[int] = None,
        ts: Optional[float] = None,
        digest: Optional[str] = None,
        extra: Optional[Dict] = None,
    ) -> bool:
        """Write this pod's ack file (see :func:`write_checkpoint_ack`);
        ``world_size`` defaults from the CURRENT stamped env."""
        if not self.enabled:
            return False
        if world_size is None:
            world_size = world_size_of(self.read_env())
        ok = write_checkpoint_ack(
            self.alloc_spec_dir, self.alloc_hash, step,
            checkpoint_dir=checkpoint_dir, kind=kind, signal=signal,
            world_size=world_size, epoch=epoch, ts=ts, digest=digest,
            extra=extra,
        )
        if ok:
            self.acks_written += 1
        return ok

    def ack_precopy(
        self,
        step: Optional[int],
        round_: int,
        checkpoint_dir: str = "",
        delta_bytes: Optional[int] = None,
        total_bytes: Optional[int] = None,
        digest: Optional[str] = None,
        signal: str = "",
        ts: Optional[float] = None,
    ) -> bool:
        """One pre-copy ROUND acknowledgement: the delta for ``round_``
        is durable on shared storage but the workload is STILL TRAINING
        — the coordinator must not reclaim on it (only journal progress
        and decide when to cut over). ``digest`` is the round's chain
        digest from :class:`~.checkpointing.DeltaCheckpointer`."""
        return self.ack(
            step, checkpoint_dir=checkpoint_dir, kind="precopy",
            signal=signal, ts=ts, digest=digest or "",
            extra={
                "round": int(round_),
                **({"delta_bytes": int(delta_bytes)}
                   if delta_bytes is not None else {}),
                **({"total_bytes": int(total_bytes)}
                   if total_bytes is not None else {}),
            },
        )

    def ack_resume(
        self, step: Optional[int], checkpoint_dir: str = "",
        ts: Optional[float] = None,
    ) -> bool:
        """The replacement pod's half of resume verification: written
        AFTER the restore committed, carrying the restored step and the
        world size the workload actually came up at."""
        return self.ack(
            step, checkpoint_dir=checkpoint_dir, kind="resume", ts=ts,
        )


def drain_serving(
    engine,
    watcher: Optional[LifecycleWatcher] = None,
    signal: Optional[Signal] = None,
    max_steps: int = 100_000,
    handoff: bool = False,
) -> dict:
    """Drain a ServingEngine's in-flight requests (the serving
    workload's answer to a drain signal: there is no optimizer state to
    checkpoint — finishing the live streams IS saving the work).

    Runs ``engine.step()`` until no live or pending requests remain
    (each step advances every live decode and pumps one pending-prefill
    chunk), then writes a ``kind="drained"`` ack through ``watcher``.
    Returns a summary; never raises past the step loop's own errors.

    ``handoff=True`` (shared-pool engines only) is the live-migration
    drain: instead of decoding every open stream to completion inside
    the drain window, each one is PUBLISHED through the pool's
    mid-stream registry (``engine.publish_stream``) for a destination
    engine to adopt and continue — pending prefills are pumped to
    activation first so nothing is cancelled. The ack's ``extra``
    carries ``handoff_streams`` so the coordinator can reconcile
    published == adopted.
    """
    drained_tokens = 0
    steps = 0
    published = 0
    if handoff and getattr(engine, "shared_pool", None) is not None:
        while steps < max_steps and (
            engine.stats()["pending_prefills"]
        ):
            out = engine.step()
            drained_tokens += sum(
                len(v) if isinstance(v, list) else 1
                for v in out.values()
            )
            steps += 1
        for rid in list(engine._slot_of):
            engine.publish_stream(rid)
            published += 1
    while steps < max_steps:
        stats = engine.stats()
        if not stats["live_requests"] and not stats["pending_prefills"]:
            break
        out = engine.step()
        drained_tokens += sum(
            len(v) if isinstance(v, list) else 1 for v in out.values()
        )
        steps += 1
    summary = {
        "steps": steps,
        "drained_tokens": drained_tokens,
        "live_requests": engine.stats()["live_requests"],
        "handoff_streams": published,
    }
    if watcher is not None and watcher.enabled:
        watcher.ack(
            None, kind="drained",
            signal=signal.value if signal is not None else "",
            extra=(
                {"handoff_streams": published} if published else None
            ),
        )
    return summary
