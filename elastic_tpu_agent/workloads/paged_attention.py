"""Pallas paged-attention decode kernel for the serving engine.

The paged serving step (serving.py) gathers each live slot's blocks
into a dense transient view, runs the shared forward, and scatters
the written position back — correct, but the gather MATERIALIZES a
copy the attention then re-reads: ~2x the HBM traffic of the cache
itself per decode step. This kernel removes the copy: the block
table rides in as a SCALAR-PREFETCH argument and the k/v BlockSpec
index maps dereference it, so each pool block streams HBM->VMEM
exactly once, straight into the flash-style online-softmax
accumulation (the standard TPU paged-attention shape; see the
jax-ml scaling playbook's serving chapter for the design space).

Decode-only (one query token per slot): no backward pass needed, the
carry is tiny ([r, h] per kv head), and blocks past a slot's length
contribute nothing through the mask (their reads come from the junk
block or stale pool entries — finite by the pool's NaN discipline in
serving.py — and exp(-inf)=0 drops them).

Interpret mode on CPU for hermetic CI, like attention.py's flash
kernels. No reference counterpart (the reference agent has no model
code); TPU workload stack.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_INF


def _paged_kernel(
    table_ref,    # scalar prefetch: [slots, nb] physical block ids
    lengths_ref,  # scalar prefetch: [slots] VALID positions per slot
    q_ref,        # [1, 1, r, h] this (slot, kv head)'s queries
    k_ref,        # [1, bs, 1, h] the current block, this kv head
    v_ref,        # [1, bs, 1, h]
    o_ref,        # [1, 1, r, h]
    m_scr,        # [r, 1] running max
    l_scr,        # [r, 1] running denominator
    acc_scr,      # [r, h] running numerator
    *,
    scale: float,
    block_size: int,
    window: int,
):
    s = pl.program_id(0)
    j = pl.program_id(2)
    nb = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[0, 0].astype(jnp.float32)          # [r, h]
    k = k_ref[:, :, 0, :][0].astype(jnp.float32)  # [bs, h]
    v = v_ref[:, :, 0, :][0].astype(jnp.float32)

    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                     # [r, bs]
    pos = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_size), 1
    )
    valid = pos < lengths_ref[s]                  # [1, bs]
    if window > 0:
        # sliding window: the query sits at position n_valid-1 and
        # attends only the last ``window`` positions (matches
        # _cached_attention's rows - cols < window)
        valid &= (lengths_ref[s] - 1 - pos) < window
    scores = jnp.where(valid, scores, NEG_INF)

    m_prev = m_scr[:]                             # [r, 1]
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)               # [r, 1]
    p = jnp.exp(scores - m_new)                   # [r, bs]
    l_new = l_scr[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_new = acc_scr[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = m_new
    l_scr[:] = l_new
    acc_scr[:] = acc_new

    @pl.when(j == nb - 1)
    def _finish():
        o_ref[0, 0] = (
            acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("kv_heads", "interpret", "window")
)
def paged_decode_attention(
    q, pool_k, pool_v, table, lengths, kv_heads: int,
    interpret: bool = False, window: int = 0,
):
    """One decode token per slot against the paged KV pool.

    q [slots, n, h]; pool_k/pool_v [n_blocks, bs, g, h] (ONE layer's
    pool); table [slots, nb] physical block ids (junk 0 where
    unmapped); lengths [slots] = number of VALID positions (i.e. the
    row's cached length INCLUDING the just-written decode token).
    Returns [slots, n, h].

    Heads are grouped GQA-style: query head i reads kv head i // r,
    matching generate._cached_attention's contiguous-group reshape.
    """
    slots, n, h = q.shape
    g = kv_heads
    r = n // g
    nb = table.shape[1]
    bs = pool_k.shape[1]
    scale = 1.0 / np.sqrt(h)
    q4 = q.reshape(slots, g, r, h)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, g, nb),
        in_specs=[
            pl.BlockSpec(
                (1, 1, r, h),
                lambda s, kv, j, table, lens: (s, kv, 0, 0),
            ),
            pl.BlockSpec(
                (1, bs, 1, h),
                lambda s, kv, j, table, lens: (table[s, j], 0, kv, 0),
            ),
            pl.BlockSpec(
                (1, bs, 1, h),
                lambda s, kv, j, table, lens: (table[s, j], 0, kv, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, r, h),
            lambda s, kv, j, table, lens: (s, kv, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((r, 1), jnp.float32),
            pltpu.VMEM((r, 1), jnp.float32),
            pltpu.VMEM((r, h), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_kernel, scale=scale, block_size=bs,
            window=window,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, g, r, h), q.dtype),
        interpret=interpret,
    )(table, lengths, q4, pool_k, pool_v)
    return out.reshape(slots, n, h)


def kernel_traffic(
    slots: int, table_blocks: int, block_size: int, kv_heads: int,
    head_dim: int, itemsize: int,
) -> dict:
    """Exact per-invocation HBM stream accounting of the kernel above,
    derived from its grid: (slots, g, nb) programs, each DMA-ing one
    [1, bs, 1, h] K block and V block HBM->VMEM exactly once (the
    BlockSpec index maps dereference the prefetched table), one
    [1, 1, r, h] query read and one output write per (slot, kv head).
    serving_proxy.py consumes this so the bench's paged-path byte
    model IS the kernel's shape, not a re-derivation that could
    drift."""
    g, h, bs, nb = kv_heads, head_dim, block_size, table_blocks
    kv_read = slots * g * nb * bs * h * itemsize * 2   # k + v
    return {
        "grid": (slots, g, nb),
        "kv_bytes_read": kv_read,
        "blocks_streamed": slots * g * nb,
        "reads_per_block": 1,
    }


def paged_decode_attention_reference(
    q, pool_k, pool_v, table, lengths, kv_heads: int, window: int = 0
):
    """Gather-based oracle: materialize each slot's dense view and
    run masked softmax attention — the exact computation the kernel
    must reproduce (and the serving engine's current step path)."""
    slots, n, h = q.shape
    g = kv_heads
    r = n // g
    nb = table.shape[1]
    bs = pool_k.shape[1]
    kg = pool_k[table.reshape(-1)].reshape(slots, nb * bs, g, h)
    vg = pool_v[table.reshape(-1)].reshape(slots, nb * bs, g, h)
    q5 = q.reshape(slots, g, r, h).astype(jnp.float32)
    scale = 1.0 / np.sqrt(h)
    scores = jnp.einsum(
        "sgrh,ssgh->sgrS".replace("ss", "sS"),
        q5, kg.astype(jnp.float32),
    ) * scale
    cols = jnp.arange(nb * bs)
    keep = cols[None, :] < lengths[:, None]       # [slots, S]
    if window > 0:
        keep &= (lengths[:, None] - 1 - cols[None, :]) < window
    scores = jnp.where(
        keep[:, None, None, :], scores, NEG_INF
    )
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "sgrS,sSgh->sgrh", probs, vg.astype(jnp.float32)
    )
    return out.reshape(slots, n, h).astype(q.dtype)
