"""Serving-artifact export: turn a training checkpoint into a
self-contained directory a serving process loads without knowing
anything about the training run.

The train side persists (params, opt_state, step) for RESUME
(checkpointing.py). Serving wants none of that: it needs the weights
(optionally int8-quantized, optionally with LoRA adapters already
merged) plus the exact ModelConfig to rebuild the decode program. An
artifact here is:

    <dir>/weights/...   one orbax StandardSave of the params pytree
                        (float, or the int8 {"q","s"} form — orbax is
                        structure-agnostic)
    <dir>/config.json   the ModelConfig, with the dtype field
                        serialized by name

CLI: convert the latest train checkpoint in one shot —

    python -m elastic_tpu_agent.workloads.export \
        --checkpoint-dir /ckpt --preset small --seq 1024 \
        --out /artifact --int8

`generate`/`ServingEngine`/`decode_shardings` consume load_artifact's
result directly; runner decode mode serves it via --params-dir.

No reference counterpart (the reference agent has no model code);
TPU workload stack, same family as checkpointing.py.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Tuple

import jax.numpy as jnp

from .transformer import ModelConfig

_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float16": jnp.float16,
}


def _cfg_to_json(cfg: ModelConfig) -> Dict[str, Any]:
    d = dataclasses.asdict(cfg)
    d["dtype"] = jnp.dtype(cfg.dtype).name
    return d


def _cfg_from_json(d: Dict[str, Any]) -> ModelConfig:
    d = dict(d)
    name = d.pop("dtype")
    if name not in _DTYPES:
        raise ValueError(f"unknown dtype {name!r} in artifact config")
    return ModelConfig(dtype=_DTYPES[name], **d)


def save_artifact(directory: str, params: Dict, cfg: ModelConfig) -> None:
    """Write a serving artifact. ``params`` may be the float tree, the
    int8 weight-only form (quantize.quantize_params), or a merged-LoRA
    tree — any pytree of arrays."""
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(directory, "weights"), params)
    with open(os.path.join(directory, "config.json"), "w") as f:
        json.dump(_cfg_to_json(cfg), f, indent=1, sort_keys=True)


def load_artifact(directory: str) -> Tuple[Dict, ModelConfig]:
    """(params, cfg) from a save_artifact directory. Arrays come back
    on the default device; shard for serving with
    generate.decode_shardings(mesh, cfg, params=params)."""
    import orbax.checkpoint as ocp

    directory = os.path.abspath(directory)
    with open(os.path.join(directory, "config.json")) as f:
        cfg = _cfg_from_json(json.load(f))
    with ocp.StandardCheckpointer() as ckptr:
        params = ckptr.restore(os.path.join(directory, "weights"))
    return params, cfg


def export_checkpoint(
    checkpoint_dir: str,
    out_dir: str,
    cfg: ModelConfig,
    int8: bool = False,
    ema: bool = False,
) -> Dict[str, Any]:
    """Latest train checkpoint -> serving artifact. Returns a summary
    dict (step, bytes, int8, ema). ``ema=True`` exports the smoothed
    weights a --ema-decay training run saved. LoRA adapters are not
    part of the train checkpoint format; merge them BEFORE exporting
    (lora.merge_lora) and export the merged tree via save_artifact
    directly."""
    import jax

    from .checkpointing import TrainCheckpointer
    from .transformer import init_params

    ckpt = TrainCheckpointer(checkpoint_dir)
    if ckpt.latest_step is None:
        # library API: catchable (main() maps it to an exit message)
        raise FileNotFoundError(
            f"{checkpoint_dir} holds no checkpoint to export"
        )
    params = init_params(cfg, jax.random.key(0))
    params, step = ckpt.restore_params(
        params, item="ema" if ema else "params"
    )
    ckpt.close()

    if int8:
        from .quantize import quantize_params

        params = jax.jit(quantize_params)(params)
        jax.block_until_ready(params)

    save_artifact(out_dir, params, cfg)
    n_bytes = sum(
        p.size * p.dtype.itemsize
        for p in jax.tree_util.tree_leaves(params)
    )
    return {
        "step": step,
        "int8": int8,
        "ema": ema,
        "bytes": n_bytes,
        "out": os.path.abspath(out_dir),
    }


def main(argv=None) -> int:
    import argparse

    from .runner import PRESETS

    parser = argparse.ArgumentParser(
        description="export a train checkpoint as a serving artifact"
    )
    parser.add_argument("--checkpoint-dir", required=True)
    parser.add_argument("--out", required=True)
    parser.add_argument(
        "--preset", choices=sorted(PRESETS), default="small",
        help="must match the training run's preset",
    )
    parser.add_argument("--seq", type=int, default=256)
    parser.add_argument("--kv-heads", type=int, default=0)
    parser.add_argument("--int8", action="store_true")
    parser.add_argument(
        "--ema", action="store_true",
        help="export the EMA weights (requires an --ema-decay "
             "training run)",
    )
    args = parser.parse_args(argv)

    cfg = ModelConfig(
        max_seq=args.seq, n_kv_heads=args.kv_heads,
        **PRESETS[args.preset],
    )
    try:
        summary = export_checkpoint(
            args.checkpoint_dir, args.out, cfg,
            int8=args.int8, ema=args.ema,
        )
    except FileNotFoundError as e:
        raise SystemExit(str(e)) from e
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
