"""Continuous-batching serving engine: sequences join and leave a
fixed-slot decode batch mid-flight (the Orca/vLLM scheduling idea,
rebuilt for XLA's static-shape world).

Why: naive batched decode waits for the whole batch to finish — one
long request stalls every short one, and freed rows idle. Continuous
batching admits a new request into a slot the moment its previous
occupant finishes, keeping every row of the batched matmuls live.

TPU-first mechanics:
- ONE preallocated KV cache [L, slots, max_len, g, h]; a slot's row is
  simply overwritten by its next occupant — no allocation, no shape
  change, no retrace. Both cache buffers are donated through the step,
  so XLA updates them in place (no per-token cache copy).
- Per-row sequence lengths: each slot decodes at its own position.
  The whole forward is generate._forward_chunk with ``positions=`` —
  the SAME code path the solo-decode oracle runs, so serving cannot
  silently diverge from it.
- Prefill pads prompts up to a fixed bucket length (one compiled
  program per bucket, not per prompt length); pad positions write
  stale cache entries that are never attended (masked by row length)
  and are overwritten by subsequent decode steps.
- The host drives admission/release (that loop is control, not
  compute); the per-step compute — all slots, active or not, in
  lockstep — is a single jitted program. Inactive slots burn a row of
  the matmul (the price of static shapes) but their state is frozen.

Correctness pin (tests): every stream produced through interleaved
admissions equals generate()'s output for that prompt alone.

No reference counterpart (the reference agent has no model/serving
code); TPU workload stack, same family as generate.py.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .generate import KVCache, _forward_chunk, _sample_rowwise
from .transformer import ModelConfig


class ServingEngine:
    """Host-driven continuous-batching decoder over fixed slots.

    >>> eng = ServingEngine(params, cfg, slots=4, max_len=256)
    >>> rid = eng.admit(prompt_tokens)       # prefill + first token
    >>> toks = eng.step()                    # {rid: token} per live req
    >>> eng.release(rid)                     # tokens; slot reusable

    Requests are identified by a monotonically increasing request id —
    never by slot, since slots are recycled. A request that fills its
    row to max_len — or emits one of its stop tokens — is
    auto-finished: it leaves the live set but its stream stays
    retrievable via release()/stream() until collected.

    Sampling is PER REQUEST: admit() takes temperature/top_k/top_p
    (defaulting to the engine-wide constructor values) and an optional
    stop-token set. The step program samples row-wise
    (generate._sample_rowwise) with the params as traced arrays, so a
    greedy request and a top-p request share one compiled step — no
    recompile per sampling mix. The per-step and per-bucket-prefill
    programs compile once each.
    """

    def __init__(
        self,
        params: Dict,
        cfg: ModelConfig,
        slots: int = 4,
        max_len: int = 512,
        prompt_buckets: Sequence[int] = (16, 64, 256),
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        seed: int = 0,
    ):
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.max_len = max_len
        self.buckets = tuple(sorted(set(prompt_buckets)))
        assert self.buckets and self.buckets[-1] <= max_len
        if cfg.pos == "learned":
            assert cfg.max_seq >= max_len
        self._sampling = (temperature, top_k, top_p)
        self._key = jax.random.key(seed)

        cache = KVCache.empty(cfg, slots, max_len)
        self._k, self._v = cache.k, cache.v
        self._lengths = jnp.zeros((slots,), jnp.int32)
        self._last = jnp.zeros((slots,), jnp.int32)
        self._free: List[int] = list(range(slots))
        self._next_rid = 0
        self._slot_of: Dict[int, int] = {}     # live rid -> slot
        self._streams: Dict[int, List[int]] = {}  # rid -> tokens (live
        self._finished: set = set()               # or auto-finished)
        # per-slot sampling params, set at admit() (host side; handed
        # to the step program as traced arrays)
        self._row_temp = np.zeros((slots,), np.float32)
        self._row_topk = np.zeros((slots,), np.int32)
        self._row_topp = np.zeros((slots,), np.float32)
        self._stop: Dict[int, frozenset] = {}  # rid -> stop-token set

        self._step_fn = self._build_step()
        self._step_greedy_fn = self._build_step_greedy()
        self._prefill_fns = {
            b: self._build_prefill(b) for b in self.buckets
        }
        self._prefix_prefill_fns: Dict[Tuple[int, int], object] = {}
        self._prefixes: Dict[int, tuple] = {}
        self._next_prefix_id = 0
        # one jitted prefix-forward per engine (re-wrapping
        # _forward_chunk per register_prefix call would recompile)
        self._prefix_forward = jax.jit(
            _forward_chunk, static_argnums=(3,)
        )

    # -- compiled programs -------------------------------------------

    def _build_step(self):
        cfg = self.cfg

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def step(params, k, v, lengths, toks, active, key, temp, tk, tp):
            cache = KVCache(k=k, v=v, length=jnp.int32(0))
            logits, cache = _forward_chunk(
                params, toks[:, None], cache, cfg,
                moe_drop_free=True, positions=lengths,
            )
            nxt = _sample_rowwise(logits[:, 0], key, temp, tk, tp)
            # frozen slots keep their token and length
            nxt = jnp.where(active, nxt, toks)
            lengths = jnp.where(active, lengths + 1, lengths)
            return cache.k, cache.v, lengths, nxt

        return step

    def _build_step_greedy(self):
        """Argmax-only step: when every LIVE request is greedy (the
        default engine config), the rowwise sampler's full-vocab sort +
        softmax + cumsum per decode token is pure discarded overhead —
        step() dispatches here instead and the compiled program is a
        bare argmax."""
        cfg = self.cfg

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def step(params, k, v, lengths, toks, active):
            cache = KVCache(k=k, v=v, length=jnp.int32(0))
            logits, cache = _forward_chunk(
                params, toks[:, None], cache, cfg,
                moe_drop_free=True, positions=lengths,
            )
            nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            nxt = jnp.where(active, nxt, toks)
            lengths = jnp.where(active, lengths + 1, lengths)
            return cache.k, cache.v, lengths, nxt

        return step

    def _build_prefill(self, bucket: int):
        cfg = self.cfg

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def prefill(params, k, v, padded, true_len, slot, key, tkp):
            # single-row chunk forward in a scratch cache, then splice
            # the row into the big cache at the slot index
            mini = KVCache.empty(cfg, 1, bucket)
            logits, mini = _forward_chunk(
                params, padded[None], mini, cfg
            )
            k = jax.lax.dynamic_update_slice(
                k, mini.k, (0, slot, 0, 0, 0)
            )
            v = jax.lax.dynamic_update_slice(
                v, mini.v, (0, slot, 0, 0, 0)
            )
            first = _sample_rowwise(
                logits[:, true_len - 1], key,
                tkp[0:1], tkp[1:2].astype(jnp.int32), tkp[2:3],
            )[0]
            return k, v, first

        return prefill

    def _build_prefix_prefill(self, pref_bucket: int, bucket: int):
        """Like _build_prefill, but the chunk CONTINUES a cached prefix:
        the mini cache starts with the prefix's K/V spliced at [0, plen)
        and the prompt runs from position plen — the prefix's forward
        is never recomputed."""
        cfg = self.cfg

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def prefill(
            params, k, v, pref_k, pref_v, plen, padded, true_len,
            slot, key, tkp,
        ):
            mini = KVCache.empty(cfg, 1, pref_bucket + bucket)
            mini = KVCache(
                k=jax.lax.dynamic_update_slice(
                    mini.k, pref_k, (0, 0, 0, 0, 0)
                ),
                v=jax.lax.dynamic_update_slice(
                    mini.v, pref_v, (0, 0, 0, 0, 0)
                ),
                length=plen,
            )
            logits, mini = _forward_chunk(params, padded[None], mini, cfg)
            k = jax.lax.dynamic_update_slice(k, mini.k, (0, slot, 0, 0, 0))
            v = jax.lax.dynamic_update_slice(v, mini.v, (0, slot, 0, 0, 0))
            first = _sample_rowwise(
                logits[:, true_len - 1], key,
                tkp[0:1], tkp[1:2].astype(jnp.int32), tkp[2:3],
            )[0]
            return k, v, first

        return prefill

    # -- host API ----------------------------------------------------

    def register_prefix(self, tokens) -> int:
        """Prefill a shared prefix (e.g. a system prompt) ONCE; admit()
        with ``prefix=`` then reuses its K/V instead of recomputing the
        prefix forward per request. Returns a prefix id."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        plen = len(tokens)
        # admission control raises (not assert): under python -O a
        # vanished check would silently corrupt a slot's stream
        if plen == 0:
            raise ValueError("empty prefix")
        bucket = next((b for b in self.buckets if b >= plen), None)
        if bucket is None:
            raise ValueError(
                f"prefix length {plen} exceeds largest bucket "
                f"{self.buckets[-1]}"
            )
        padded = jnp.zeros((bucket,), jnp.int32).at[:plen].set(
            jnp.asarray(tokens)
        )
        mini = KVCache.empty(self.cfg, 1, bucket)
        _, mini = self._prefix_forward(
            self.params, padded[None], mini, self.cfg
        )
        pid = self._next_prefix_id
        self._next_prefix_id += 1
        # stored at bucket width; pad K/V beyond plen is masked by
        # position downstream exactly like admit()'s own padding
        self._prefixes[pid] = (mini.k, mini.v, plen, bucket)
        return pid

    def release_prefix(self, pid: int) -> None:
        """Drop a registered prefix's cached K/V (each one pins
        [L, 1, bucket, g, h] arrays in device memory for the engine's
        lifetime otherwise). In-flight requests already admitted with
        it are unaffected — their slot rows hold a copy."""
        del self._prefixes[pid]

    def admit(
        self,
        prompt,
        prefix: Optional[int] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        stop_tokens: Sequence[int] = (),
    ) -> int:
        """Prefill a prompt (1-D int sequence) into a free slot;
        returns the request id. The first generated token is already in
        stream(rid). With ``prefix=``, the request's sequence is
        (registered prefix + prompt) but only the prompt's forward
        runs.

        temperature/top_k/top_p override the engine-wide constructor
        defaults FOR THIS REQUEST (None = keep the default); requests
        with different sampling configs batch into the same step
        program. ``stop_tokens``: emitting any of these auto-finishes
        the request in step() — the stop token IS appended to the
        stream (callers that want it hidden strip the tail), and the
        slot frees without the caller polling."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = len(prompt)
        # admission control raises (not assert): under python -O the
        # "no room to decode" check would vanish and a full-row request
        # would clamp its decode writes at max_len-1, corrupting the
        # slot's stream
        if p == 0:
            raise ValueError("empty prompt")
        bucket = next(
            (b for b in self.buckets if b >= p), None
        )
        if bucket is None:
            raise ValueError(
                f"prompt length {p} exceeds largest bucket "
                f"{self.buckets[-1]}"
            )
        if prefix is not None:
            if prefix not in self._prefixes:
                raise ValueError(
                    f"unknown or released prefix {prefix}"
                )
            pref_k, pref_v, plen, pref_bucket = self._prefixes[prefix]
        else:
            plen, pref_bucket = 0, 0
        total = plen + p
        if total >= self.max_len:
            raise ValueError(
                f"prefix+prompt length {total} leaves no room to "
                f"decode (max_len {self.max_len})"
            )
        if pref_bucket + bucket > self.max_len:
            raise ValueError(
                "prefix bucket + prompt bucket exceed the slot row"
            )
        if not self._free:
            raise ValueError("no free slot; release() one first")
        slot = self._free.pop(0)

        d_temp, d_topk, d_topp = self._sampling
        temp = d_temp if temperature is None else float(temperature)
        tk = d_topk if top_k is None else int(top_k)
        tp = d_topp if top_p is None else float(top_p)
        self._row_temp[slot] = temp
        self._row_topk[slot] = tk
        self._row_topp[slot] = tp

        padded = jnp.zeros((bucket,), jnp.int32)
        padded = padded.at[:p].set(jnp.asarray(prompt))
        self._key, sub = jax.random.split(self._key)
        # sampling params ride in ONE traced f32 triple (top_k cast
        # back inside) so per-request values never retrace the prefill
        tkp = jnp.asarray([temp, float(tk), tp], jnp.float32)
        if prefix is not None:
            fn_key = (pref_bucket, bucket)
            if fn_key not in self._prefix_prefill_fns:
                self._prefix_prefill_fns[fn_key] = (
                    self._build_prefix_prefill(*fn_key)
                )
            # true_len is CHUNK-relative: the last real prompt token
            # sits at chunk index p-1 (absolute plen+p-1)
            k, v, first = self._prefix_prefill_fns[fn_key](
                self.params, self._k, self._v, pref_k, pref_v,
                jnp.int32(plen), padded, jnp.int32(p),
                jnp.int32(slot), sub, tkp,
            )
        else:
            k, v, first = self._prefill_fns[bucket](
                self.params, self._k, self._v, padded,
                jnp.int32(p), jnp.int32(slot), sub, tkp,
            )
        self._k, self._v = k, v
        self._lengths = self._lengths.at[slot].set(total)
        self._last = self._last.at[slot].set(first)
        rid = self._next_rid
        self._next_rid += 1
        self._slot_of[rid] = slot
        self._streams[rid] = [int(first)]
        self._stop[rid] = frozenset(int(t) for t in stop_tokens)
        # the admission token itself may be a stop token
        if int(first) in self._stop[rid]:
            self._finish(rid)
        return rid

    def step(self) -> Dict[int, int]:
        """Advance every live request one token; returns {rid: token}.
        Requests whose row fills to max_len — or that emit one of
        their stop tokens — are auto-finished (their streams remain
        retrievable via release())."""
        if not self._slot_of:
            return {}
        live_slots = set(self._slot_of.values())
        active = jnp.asarray(
            [s in live_slots for s in range(self.slots)]
        )
        # key advances every step regardless of path so a request's
        # draws don't depend on its neighbors' admission order
        self._key, sub = jax.random.split(self._key)
        live = sorted(live_slots)
        if not (self._row_temp[live] > 0.0).any():
            # all live rows greedy: argmax-only program (no sort)
            self._k, self._v, self._lengths, self._last = (
                self._step_greedy_fn(
                    self.params, self._k, self._v, self._lengths,
                    self._last, active,
                )
            )
        else:
            self._k, self._v, self._lengths, self._last = self._step_fn(
                self.params, self._k, self._v, self._lengths,
                self._last, active, sub,
                jnp.asarray(self._row_temp),
                jnp.asarray(self._row_topk),
                jnp.asarray(self._row_topp),
            )
        out = {}
        toks = np.asarray(self._last)
        lengths = np.asarray(self._lengths)
        for rid, slot in list(self._slot_of.items()):
            tok = int(toks[slot])
            self._streams[rid].append(tok)
            out[rid] = tok
            # a row at max_len-1 can't take another write; a stop
            # token ends the stream without the caller polling
            if (
                int(lengths[slot]) >= self.max_len - 1
                or tok in self._stop[rid]
            ):
                self._finish(rid)
        return out

    def _finish(self, rid: int) -> None:
        slot = self._slot_of.pop(rid)
        self._finished.add(rid)
        self._free.append(slot)
        self._free.sort()

    def stream(self, rid: int) -> List[int]:
        """Tokens generated so far (admission's first token onward);
        valid for live and finished-uncollected requests."""
        return list(self._streams[rid])

    def release(self, rid: int) -> List[int]:
        """Finish a live request (freeing its slot) or collect an
        auto-finished one; returns its generated tokens."""
        if rid in self._slot_of:
            self._finish(rid)
        self._finished.discard(rid)
        self._stop.pop(rid, None)
        return self._streams.pop(rid)
