"""Continuous-batching serving engine: sequences join and leave a
fixed-slot decode batch mid-flight (the Orca/vLLM scheduling idea,
rebuilt for XLA's static-shape world), over a PAGED KV cache.

Why: naive batched decode waits for the whole batch to finish — one
long request stalls every short one, and freed rows idle. Continuous
batching admits a new request into a slot the moment its previous
occupant finishes, keeping every row of the batched matmuls live. And
a dense per-slot cache reserves slots*max_len tokens of HBM however
short the live requests are; paging reserves only what's written.

TPU-first mechanics:
- KV lives in a BLOCK POOL [L, n_blocks, block, g, h]; each slot owns
  an ordered list of pool blocks (its block table). HBM scales with
  LIVE TOKENS, not slots*max_len, and a shared prefix is shared
  blocks under refcounts — no per-slot prefix copies (only a partial
  tail block is copied, once, at admission).
- The per-step program GATHERS the live slots' blocks into a dense
  [slots, S] view sized by a bucket over the longest live row (a
  handful of compiled programs, not one per length), runs the SAME
  generate._forward_chunk the solo-decode oracle runs (so serving
  cannot silently diverge from it), then SCATTERS the one newly
  written position per slot back to its pool block. The gather is
  transient and bucket-bounded — short live rows touch little HBM
  even when max_len is huge.
- Per-row sequence lengths: each slot decodes at its own position
  (``positions=`` row-wise machinery in _forward_chunk).
- Prefill pads prompts up to a fixed bucket length (one compiled
  program per bucket, not per prompt length); pad positions write
  stale cache entries that are never attended (masked by row length)
  and are overwritten by subsequent decode steps.
- The host drives admission/release and block allocation (that loop
  is control, not compute); the per-step compute — all slots, active
  or not, in lockstep — is a single jitted program.

Correctness pin (tests): every stream produced through interleaved
admissions equals generate()'s output for that prompt alone.

No reference counterpart (the reference agent has no model/serving
code); TPU workload stack, same family as generate.py.
"""

from __future__ import annotations

import functools
import math
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .generate import KVCache, _forward_chunk, _qkv, _sample_rowwise
from .quantize import embed_lookup, wdense
from .transformer import ModelConfig, _rmsnorm, rope

# physical block 0 is the JUNK block: never allocated, the write target
# for frozen slots and the gather source for empty table entries — its
# contents are garbage by design and masked everywhere it could be read
_JUNK = 0


def gather_bucket(needed_blocks: int, max_blocks: int) -> int:
    """Power-of-two gather-width bucketing — ONE function shared by
    the engine's compiled-program keys and the HBM-traffic proxy
    (serving_proxy.py models exactly the widths the engine compiles,
    so a bucketing change can't silently stale the paged-default
    evidence)."""
    b = 1
    while b < needed_blocks:
        b *= 2
    return min(b, max_blocks)


# -- pool representation helpers ------------------------------------
#
# The KV pool is either a plain array [L, n_blocks, bs, g, h] or (engine
# flag kv_int8) the quantized pytree {"q": int8 same shape, "s": f32
# [..., 1] per-position scales} — decode is HBM-bound and the pool is
# the engine's dominant HBM resident, so int8 storage cuts per-step
# cache reads ~4x (f32 models) / ~2x (bf16). Every pool read/write goes
# through these two helpers, so the compiled programs handle both forms
# with one code path (quantize on scatter, dequantize on gather).

def _pool_empty(shape, dtype, kv_int8: bool):
    if not kv_int8:
        return jnp.zeros(shape, dtype)
    return {
        "q": jnp.zeros(shape, jnp.int8),
        "s": jnp.zeros(shape[:-1] + (1,), jnp.float32),
    }


def _pool_shape(pool):
    return pool["q"].shape if isinstance(pool, dict) else pool.shape


def _pool_set(pool, idx, val):
    """pool.at[idx].set(val) for either pool form (float values in;
    int8 pools quantize per position on the way down)."""
    if isinstance(pool, dict):
        from .quantize import quantize_kv

        qv = quantize_kv(val)
        return {
            "q": pool["q"].at[idx].set(qv["q"]),
            "s": pool["s"].at[idx].set(qv["s"]),
        }
    return pool.at[idx].set(val.astype(pool.dtype))


def _pool_get(pool, idx):
    """pool[idx] for either form (int8 pools gather the int8 entries +
    scales and dequantize AFTER the gather — the HBM read stays
    int8-sized, exactly embed_lookup's pattern)."""
    if isinstance(pool, dict):
        return pool["q"][idx].astype(jnp.float32) * pool["s"][idx]
    return pool[idx]


class BlockAllocator:
    """Host-side pool bookkeeping: a free list plus per-block refcounts
    (shared prefix blocks are held by several tables at once).

    ``reclaim`` (optional, set by the engine when automatic prefix
    caching is on) is the pool-pressure hook: called with the number of
    blocks needed when the free list runs dry, it may evict cache-held
    refcount-1 blocks back onto the free list before alloc() declares
    exhaustion."""

    def __init__(self, n_blocks: int):
        self.n_blocks = n_blocks
        self._free: List[int] = list(range(n_blocks - 1, 0, -1))
        self._ref = np.zeros((n_blocks,), np.int32)
        self.reclaim: Optional[object] = None  # (n_blocks) -> freed

    def alloc(self) -> int:
        if not self._free and self.reclaim is not None:
            self.reclaim(1)
        if not self._free:
            raise RuntimeError(
                "KV block pool exhausted; release() a request or size "
                "the engine with more pool_blocks"
            )
        bid = self._free.pop()
        self._ref[bid] = 1
        return bid

    def share(self, bid: int) -> int:
        self._ref[bid] += 1
        return bid

    def drop(self, bid: int) -> None:
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)

    @property
    def used(self) -> int:
        """Blocks currently held (excludes the junk block)."""
        return self.n_blocks - 1 - len(self._free)


class SharedKVPool:
    """One paged KV block pool shared by SEVERAL engines — the substrate
    of prefill/decode disaggregation (FlexNPU's co-location shape): a
    PREFILL-role engine writes prompt K/V into pool blocks and publishes
    them through the automatic prefix cache; a DECODE-role engine admits
    the same prompt, adopts the published blocks via the refcounted
    ``BlockAllocator``/``PrefixCache`` plumbing (the exact explicit-
    prefix machinery — no bytes copied, no recompute), prefills only the
    ≥1-token tail and decodes. One chip serves both phases without the
    decode stream ever waiting behind a whole prompt, and the phase
    imbalance between the two roles is exactly the signal the agent's
    repartition controller moves core quota along.

    Owns the allocator, the prefix cache (always on — it IS the
    handoff channel) and the pool arrays; attached engines read and
    write the arrays through their ``_pool_k``/``_pool_v`` properties,
    so donated jit programs keep working unchanged. Host-side driving
    is expected from one thread (or externally serialized) — the same
    contract a single engine already has.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        block_size: int,
        pool_blocks: int,
        kv_int8: bool = False,
        prefix_cache_blocks: Optional[int] = None,
    ):
        from .prefix_cache import PrefixCache

        self.cfg = cfg
        self.block_size = block_size
        self.pool_blocks = pool_blocks
        self.kv_int8 = kv_int8
        self.allocator = BlockAllocator(pool_blocks)
        self.prefix_cache = PrefixCache(
            self.allocator, block_size, max_blocks=prefix_cache_blocks
        )
        self.allocator.reclaim = self.prefix_cache.reclaim
        shape = (
            cfg.n_layers, pool_blocks, block_size,
            cfg.kv_heads, cfg.head_dim,
        )
        self.pool_k = _pool_empty(shape, cfg.dtype, kv_int8)
        self.pool_v = _pool_empty(shape, cfg.dtype, kv_int8)
        # Cross-role adoption accounting: admissions that mapped cached
        # blocks some attached engine published earlier. In the
        # disaggregated flow the decode role publishes only digests the
        # prefill role already owns (dedup), so decode-side hits ARE
        # prefill->decode handoffs.
        self.adoptions = 0
        self.adopted_tokens = 0
        # Cross-role request STITCHING (request_obs.py): a prefill-role
        # engine publishes its observatory record here under the
        # prompt's block-chain digests — the same keys the prefix cache
        # uses — and the decode-role engine adopts it at the auto-cache
        # hit that IS the handoff, so one request id spans both roles.
        # Bounded: un-adopted publications age out LRU (the observatory
        # separately closes their partitions as handoff_expired).
        self._request_registry: "OrderedDict[bytes, object]" = (
            OrderedDict()
        )
        self.max_registry_digests = 1024
        self.published_requests = 0
        self.adopted_requests = 0
        # Mid-stream handoff registry (live migration): a DRAINING
        # engine publishes each OPEN stream's block chain + generation
        # cursor here (``ServingEngine.publish_stream``) and a peer
        # engine on the same pool claims and continues it
        # (``adopt_stream``) — same refcounted blocks, zero bytes
        # copied, zero client-visible resets. Each record owns one
        # refcount per block (taken at publish, inherited at adopt),
        # so a published stream survives its source slot's release.
        self._stream_registry: "OrderedDict[int, dict]" = OrderedDict()
        self._next_handoff_id = 0
        self.max_pending_streams = 256
        self.published_streams = 0
        self.adopted_streams = 0
        self.expired_streams = 0

    def publish_request(self, digests, record) -> None:
        """Publish a prefill-role request's observatory record under
        every digest of its block chain (the decode side may cover a
        shorter prefix than the publisher wrote, so any chain point
        must adopt)."""
        if not digests:
            return
        for d in digests:
            self._request_registry[d] = record
            self._request_registry.move_to_end(d)
        self.published_requests += 1
        while len(self._request_registry) > self.max_registry_digests:
            self._request_registry.popitem(last=False)

    def adopt_request(self, digest):
        """Claim (and remove) the published record whose chain contains
        ``digest``; a publication is adopted at most once."""
        rec = self._request_registry.get(digest)
        if rec is None:
            return None
        for d in [
            k for k, v in self._request_registry.items() if v is rec
        ]:
            del self._request_registry[d]
        self.adopted_requests += 1
        return rec

    def publish_stream(self, record: dict) -> int:
        """Register a mid-stream handoff record (built by
        ``ServingEngine.publish_stream``; the record already holds one
        block refcount per entry of its chain). Overflow expires the
        OLDEST pending record — its block refs drop and its open
        observatory partition (if any) closes as ``handoff_expired``,
        so an un-adopted publication can neither leak pool blocks nor
        leak a live request partition."""
        hid = self._next_handoff_id
        self._next_handoff_id += 1
        record["handoff_id"] = hid
        self._stream_registry[hid] = record
        self.published_streams += 1
        while len(self._stream_registry) > self.max_pending_streams:
            _, stale = self._stream_registry.popitem(last=False)
            for bid in stale.get("blocks", ()):
                self.allocator.drop(bid)
            stale_obs = stale.get("obs")
            if stale_obs is not None:
                stale_obs.owner.finish(stale_obs.uid, "handoff_expired")
            self.expired_streams += 1
        return hid

    def claim_stream(self, handoff_id: Optional[int] = None):
        """Claim (and remove) a pending mid-stream handoff record —
        oldest first, or a specific one by id. Returns None when
        nothing is pending. The claimer inherits the record's block
        refcounts; if it cannot seat the stream it MUST hand the
        record back via ``restore_stream`` (not drop it)."""
        if handoff_id is None:
            if not self._stream_registry:
                return None
            _, rec = self._stream_registry.popitem(last=False)
        else:
            rec = self._stream_registry.pop(handoff_id, None)
            if rec is None:
                return None
        self.adopted_streams += 1
        return rec

    def restore_stream(self, record: dict) -> None:
        """Return a claimed-but-unseatable record to the FRONT of the
        registry (it stays oldest) and un-count the claim."""
        hid = record["handoff_id"]
        self._stream_registry[hid] = record
        self._stream_registry.move_to_end(hid, last=False)
        self.adopted_streams -= 1

    @property
    def pending_streams(self) -> int:
        return len(self._stream_registry)

    def compatible_with(self, cfg: ModelConfig) -> bool:
        return (
            cfg.n_layers == self.cfg.n_layers
            and cfg.kv_heads == self.cfg.kv_heads
            and cfg.head_dim == self.cfg.head_dim
        )

    @property
    def used_blocks(self) -> int:
        return self.allocator.used

    def stats(self) -> Dict:
        return {
            "pool_blocks": self.pool_blocks,
            "used_blocks": self.used_blocks,
            "block_size": self.block_size,
            "adoptions": self.adoptions,
            "adopted_tokens": self.adopted_tokens,
            "published_requests": self.published_requests,
            "adopted_requests": self.adopted_requests,
            "published_streams": self.published_streams,
            "adopted_streams": self.adopted_streams,
            "expired_streams": self.expired_streams,
            "pending_streams": self.pending_streams,
            "prefix_cache": self.prefix_cache.stats(),
        }


def disaggregated_status(prefill: "ServingEngine",
                         decode: "ServingEngine") -> Dict:
    """Combined serving status for a prefill/decode pair over one
    SharedKVPool — the ``serving`` block shape the sampler/doctor
    bundle schema validates (pool totals at the top level like a
    unified engine, plus per-role queue depths and the shared-pool
    adoption counters the per-role gauges read)."""
    ps, ds = prefill.stats(), decode.stats()
    pool = prefill.shared_pool
    out = {
        "slots": ps["slots"] + ds["slots"],
        "live_requests": ps["live_requests"] + ds["live_requests"],
        "pending_prefills": (
            ps["pending_prefills"] + ds["pending_prefills"]
        ),
        "block_size": pool.block_size,
        "pool_blocks": pool.pool_blocks,
        "used_blocks": pool.used_blocks,
        "pool_occupancy": round(
            pool.used_blocks / max(1, pool.pool_blocks - 1), 4
        ),
        "prefilled_tokens_total": (
            ps["prefilled_tokens_total"] + ds["prefilled_tokens_total"]
        ),
        "admitted_tokens_total": (
            ps["admitted_tokens_total"] + ds["admitted_tokens_total"]
        ),
        "prefix_cache": pool.prefix_cache.stats(),
        "shared_pool": {
            "adoptions": pool.adoptions,
            "adopted_tokens": pool.adopted_tokens,
            "published_requests": pool.published_requests,
            "adopted_requests": pool.adopted_requests,
        },
        "roles": {
            "prefill": {
                "role": "prefill",
                "queue_depth": ps["pending_prefills"],
                "prefilled_tokens_total": ps["prefilled_tokens_total"],
            },
            "decode": {
                "role": "decode",
                "queue_depth": (
                    ds["live_requests"] + ds["pending_prefills"]
                ),
                "adopted_tokens_total": ds.get(
                    "adopted_tokens_total", 0
                ),
            },
        },
    }
    return out


class ServingEngine:
    """Host-driven continuous-batching decoder over fixed slots and a
    paged KV block pool.

    >>> eng = ServingEngine(params, cfg, slots=4, max_len=256)
    >>> rid = eng.admit(prompt_tokens)       # prefill + first token
    >>> toks = eng.step()                    # {rid: token} per live req
    >>> eng.release(rid)                     # tokens; slot reusable

    admit() prefills synchronously (every live decode waits for the
    whole prompt); enqueue() instead spreads the prefill one
    block-sized chunk per step() — the chunked-prefill interleave —
    so decodes advance every step and the request activates when its
    last chunk lands.

    Requests are identified by a monotonically increasing request id —
    never by slot, since slots are recycled. A request that fills its
    row to max_len — or emits one of its stop tokens — is
    auto-finished: it leaves the live set but its stream stays
    retrievable via release()/stream() until collected.

    Sampling is PER REQUEST: admit() takes temperature/top_k/top_p
    (defaulting to the engine-wide constructor values) and an optional
    stop-token set. The step program samples row-wise
    (generate._sample_rowwise) with the params as traced arrays, so a
    greedy request and a top-p request share one compiled step — no
    recompile per sampling mix; an all-greedy batch dispatches to an
    argmax-only program with no sort.

    ``block_size`` (None = largest power of two dividing every prompt
    bucket and max_len) sets paging granularity; ``pool_blocks``
    (default: one slot's worth of headroom beyond slots*max_len for
    registered prefixes) sets total KV HBM. `used_blocks` exposes live
    pool pressure. ``paged_kernel=True`` switches plain decode steps
    to the Pallas paged-attention path (paged_attention.py): K/V
    writes land directly in pool blocks and attention streams each
    block from HBM once — no gathered transient. Streams are pinned
    identical to the gather path; prefill/spec steps keep the gather
    (they are multi-token). ``paged_kernel=None`` (auto) resolves from
    the HBM-traffic proxy's documented threshold (serving_proxy.py):
    ON for native TPU backends, OFF where the kernel would only be
    emulated (CPU interpret mode) or can't run the layout (int8 pool,
    tensor-parallel mesh).

    ``prefix_cache=True`` turns on AUTOMATIC cross-request prefix
    caching (prefix_cache.py): every full prompt block a prefill
    writes is published under a token hash chain, admissions share
    the longest cached chain and prefill only the tail, and
    refcount-1 cached blocks evict LRU under pool pressure.
    ``prefix_cache_blocks`` caps the cache; hit/miss/eviction counters
    ride ``stats()``, the flight recorder, and the agent's serving
    gauges. Cached streams are bit-identical to uncached ones — the
    reuse is the original K/V bytes, never a recompute.

    ``kv_int8=True`` stores the pool as int8 with per-position f32
    scales (quantize.quantize_kv): KV reads shrink ~4x (f32) / ~2x
    (bf16); decode attends dequantized values, so streams are
    approximate (quantizer noise), not bit-pinned. Gather path only.

    ``mesh`` (partitioner.make_serving_mesh) makes the engine
    TENSOR-PARALLEL: heads/MLP/vocab and the pool's kv-head axis
    shard over the mesh's "mp" axis; host-side pool bookkeeping —
    and so occupancy, prefix caching, eviction — is identical to the
    single-device engine. Gather path only; spec mode unsupported.

    SPECULATIVE MODE: pass ``draft_params``/``draft_cfg`` (and
    optionally ``gamma``) and every step() becomes a speculative
    multi-token step — the draft proposes gamma tokens per live slot,
    the target verifies all slots' gamma+1 positions in ONE batched
    chunk (per-row positions), and each row commits its own accepted
    prefix + correction (per-slot acceptance cursors). step() then
    returns {rid: [tokens...]} — a LIST per request, variable length
    per row per step. Greedy rows are EXACT: the stream equals the
    target-only greedy stream token for token (the solo
    speculative.py guarantee, vectorized). Sampling is per-request
    temperature only (the Leviathan accept/resample rule needs the
    draft and target distributions in the same family; top-k/top-p
    admissions are rejected in spec mode). The draft uses a small
    dense [slots, max_len] cache — it is narrow by design, so paging
    it would complicate the rollback-by-length trick for no real HBM
    win; the paged pool covers the target, where the memory is.
    """

    def __init__(
        self,
        params: Dict,
        cfg: ModelConfig,
        slots: int = 4,
        max_len: int = 512,
        prompt_buckets: Sequence[int] = (16, 64, 256),
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        seed: int = 0,
        block_size: Optional[int] = None,
        pool_blocks: Optional[int] = None,
        draft_params: Optional[Dict] = None,
        draft_cfg: Optional[ModelConfig] = None,
        gamma: int = 4,
        # None = AUTO: resolved from the HBM-traffic proxy's documented
        # threshold (serving_proxy.py) — the kernel is the default
        # wherever it runs natively; see the class docstring
        paged_kernel: Optional[bool] = None,
        recorder=None,
        prefix_cache: bool = False,
        prefix_cache_blocks: Optional[int] = None,
        kv_int8: bool = False,
        mesh=None,
        role: str = "both",
        pool: Optional[SharedKVPool] = None,
        lifecycle=None,
        observatory=None,
    ):
        # optional flight recorder (workloads/telemetry.py): every
        # admit/step emits a JSONL record tagged with the agent's
        # propagated trace id, so broker-side sharing decisions can be
        # validated against measured serving throughput
        self._recorder = recorder
        # optional LifecycleWatcher (workloads/lifecycle.py): once the
        # agent's drain signal lands in the alloc spec, NEW admissions
        # are refused so the serving loop can finish in-flight streams
        # and ack (lifecycle.drain_serving) before the chips go away
        self._lifecycle = lifecycle
        # optional RequestObservatory (workloads/request_obs.py): every
        # admission gets a request id and a gap-free phase partition
        # (queued|prefill|decode|stalled|handoff), TTFT/TPOT per SLO
        # class, and prefix-cache / KV-byte attribution. Share ONE
        # observatory across a disaggregated pair so stitched
        # partitions live in one ledger.
        self._observatory = observatory
        if (
            observatory is not None
            and recorder is not None
            and observatory.recorder is None
        ):
            observatory.recorder = recorder
        self._obs_uid: Dict[int, int] = {}  # rid -> observatory uid
        # requests force-finished for pool exhaustion (the observatory
        # step breakdown reports these as evictions)
        self._evictions_total = 0
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.max_len = max_len
        self.buckets = tuple(sorted(set(prompt_buckets)))
        assert self.buckets and self.buckets[-1] <= max_len
        if cfg.pos == "learned":
            assert cfg.max_seq >= max_len
        self._sampling = (temperature, top_k, top_p)
        self._key = jax.random.key(seed)

        # Disaggregated roles over a SharedKVPool (see SharedKVPool):
        # "prefill" admits-and-publishes (no decode slots retained),
        # "decode" adopts published blocks and decodes, "both" is the
        # unified engine. The pool must be shared for roles to talk.
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be both|prefill|decode, got {role!r}"
            )
        if role != "both" and draft_params is not None:
            raise ValueError(
                "speculative serving does not support disaggregated "
                "prefill/decode roles"
            )
        self.role = role
        self.shared_pool = pool
        if pool is not None:
            if mesh is not None:
                raise ValueError(
                    "a SharedKVPool does not compose with a "
                    "tensor-parallel mesh yet (per-engine placement "
                    "would shard one pool two ways)"
                )
            if draft_params is not None:
                raise ValueError(
                    "speculative serving does not support a shared pool"
                )
            if paged_kernel:
                raise ValueError(
                    "paged_kernel=True does not compose with a shared "
                    "pool yet; shared-pool engines run the gather path"
                )
            paged_kernel = False
            if kv_int8 != pool.kv_int8:
                raise ValueError(
                    f"engine kv_int8={kv_int8} disagrees with the "
                    f"shared pool's kv_int8={pool.kv_int8}"
                )
            if not pool.compatible_with(cfg):
                raise ValueError(
                    "model config (n_layers/kv_heads/head_dim) does not "
                    "match the shared pool's"
                )
            if block_size is not None and block_size != pool.block_size:
                raise ValueError(
                    f"block_size {block_size} != shared pool's "
                    f"{pool.block_size}"
                )
            block_size = pool.block_size
            if pool_blocks is not None and pool_blocks != pool.pool_blocks:
                raise ValueError(
                    f"pool_blocks {pool_blocks} != shared pool's "
                    f"{pool.pool_blocks}"
                )

        if block_size is None:
            # paging granularity: largest power of two dividing every
            # prompt bucket and max_len (so prefill chunks and rows
            # tile into whole blocks)
            g = math.gcd(max_len, *self.buckets)
            block_size = g & (-g)
        self.block_size = block_size
        if max_len % block_size or any(
            b % block_size for b in self.buckets
        ):
            raise ValueError(
                f"block_size {block_size} must divide max_len "
                f"{max_len} and every prompt bucket {self.buckets}"
            )
        self.max_blocks = max_len // block_size
        if pool is not None:
            # Shared substrate: the pool owns allocator + prefix cache
            # (the cache IS the cross-role handoff channel, so it is
            # always on) and the arrays; this engine is a view.
            self.pool_blocks = pool.pool_blocks
            self._alloc = pool.allocator
            self._prefix_cache = pool.prefix_cache
        else:
            if pool_blocks is None:
                # all slots at max_len plus one slot's worth of headroom
                # for registered prefixes, plus the junk block
                pool_blocks = 1 + (slots + 1) * self.max_blocks
            self.pool_blocks = pool_blocks
            self._alloc = BlockAllocator(pool_blocks)
            # automatic cross-request prefix caching (prefix_cache.py):
            # every full prompt block a prefill writes is published
            # under a token hash chain; admissions share the longest
            # cached chain and prefill only the tail. Off by default —
            # cached blocks outlive their request (refcount 1,
            # LRU-evicted under pool pressure), which changes
            # used_blocks bookkeeping callers may watch.
            self._prefix_cache = None
            if prefix_cache:
                from .prefix_cache import PrefixCache

                self._prefix_cache = PrefixCache(
                    self._alloc, block_size,
                    max_blocks=prefix_cache_blocks,
                )
                self._alloc.reclaim = self._prefix_cache.reclaim
        if self.role == "prefill" and self._prefix_cache is None:
            raise ValueError(
                "role='prefill' publishes through the prefix cache; "
                "construct with prefix_cache=True or a SharedKVPool"
            )
        # REAL prompt tokens run through a prefill forward (tails only
        # when the cache hits); the serving bench's >=3x prefill
        # reduction claim is measured against this counter.
        self.prefilled_tokens_total = 0
        self.admitted_tokens_total = 0
        # Cache-adoption accounting (nonzero only with the prefix cache
        # on): admissions that mapped already-cached blocks, and the
        # prompt tokens those blocks covered. On a decode-role engine
        # over a shared pool these are prefill->decode handoffs.
        self.adoptions_total = 0
        self.adopted_tokens_total = 0
        # Optional MoeRoutingStats (workloads/moe.py): engines serving
        # MoE models can attach a host-side routing accumulator;
        # stats() surfaces it so expert load/imbalance reach the
        # serving gauges and the doctor bundle.
        self.moe_stats = None
        # speculative-mode accounting (populated by _step_speculative)
        self.spec_rounds_total = 0
        self.spec_drafted_total = 0
        self.spec_accepted_total = 0

        self.kv_int8 = kv_int8
        if kv_int8 and draft_params is not None:
            raise ValueError(
                "speculative serving does not support kv_int8 (the "
                "accept/resample algebra is pinned to the float pool)"
            )
        # tensor-parallel serving (partitioner.py): attention heads,
        # MLP and vocab shard over the mesh's "mp" axis, and the paged
        # KV pool shards its kv-head axis the same way — the host-side
        # allocator/table bookkeeping never changes, so pool occupancy
        # matches the single-device engine block for block.
        from .partitioner import ServingPartitioner

        self.mesh = mesh
        self._part = ServingPartitioner(mesh, cfg)
        if mesh is not None:
            self.params = params = self._part.shard_params(params)
        if pool is None:
            pool_shape = (
                cfg.n_layers, self.pool_blocks, block_size,
                cfg.kv_heads, cfg.head_dim,
            )
            self._pool_k = self._part.place_pool(
                _pool_empty(pool_shape, cfg.dtype, kv_int8)
            )
            self._pool_v = self._part.place_pool(
                _pool_empty(pool_shape, cfg.dtype, kv_int8)
            )
        # (shared pool: the arrays already live on the pool; the
        # _pool_k/_pool_v properties read and write through it)
        # logical->physical block map per slot; 0 = unmapped (junk)
        self._table = np.zeros((slots, self.max_blocks), np.int32)
        self._lengths = jnp.zeros((slots,), jnp.int32)
        self._host_len = np.zeros((slots,), np.int64)
        self._last = jnp.zeros((slots,), jnp.int32)
        self._free: List[int] = list(range(slots))
        self._next_rid = 0
        self._slot_of: Dict[int, int] = {}     # live rid -> slot
        self._streams: Dict[int, List[int]] = {}  # rid -> tokens (live
        self._finished: set = set()               # or auto-finished)
        # per-slot sampling params, set at admit() (host side; handed
        # to the step program as traced arrays)
        self._row_temp = np.zeros((slots,), np.float32)
        self._row_topk = np.zeros((slots,), np.int32)
        self._row_topp = np.zeros((slots,), np.float32)
        self._stop: Dict[int, frozenset] = {}  # rid -> stop-token set
        # shared-pool engines keep each live request's REAL token
        # history (prefix + prompt, host int32) so a mid-stream
        # handoff (publish_stream) can rebuild the block hash chain
        # without re-deriving tokens from KV bytes
        self._seq_tokens: Dict[int, np.ndarray] = {}
        # mid-stream handoffs this engine published / adopted
        self.stream_handoffs_out = 0
        self.stream_handoffs_in = 0
        # chunked admissions mid-prefill (enqueue()): FIFO of rids;
        # per-rid host state in _pending_state. _settling holds slots
        # whose request activated THIS step (they sit the decode out)
        self._pending: List[int] = []
        self._pending_state: Dict[int, Dict] = {}
        self._chunk_prefill_fns: Dict[int, object] = {}
        self._settling: set = set()
        # why each finished rid stopped: "released" | "max_len" |
        # "stop_token" | "pool_exhausted"; cleared when release()
        # collects the stream
        self.finish_reason: Dict[int, str] = {}

        # paged_kernel=True: plain decode steps run the Pallas
        # paged-attention path (no gather transient; pool blocks read
        # once). Interpret mode on CPU so tests stay hermetic.
        # paged_kernel=None resolves from the HBM-traffic proxy's
        # documented threshold (serving_proxy.py): ON for a real TPU
        # backend, OFF where the kernel would only be emulated.
        self._interpret = jax.default_backend() == "cpu"
        if paged_kernel is None:
            from .serving_proxy import recommend_paged_kernel

            paged_kernel = recommend_paged_kernel(
                cfg, interpret=self._interpret, kv_int8=kv_int8,
                mesh=mesh, slots=slots, seq_len=max_len,
                block_size=self.block_size,
            )
        if paged_kernel and kv_int8:
            raise ValueError(
                "kv_int8 and paged_kernel are mutually exclusive: the "
                "Pallas kernel streams raw pool blocks; int8 pools "
                "dequantize on the gather path"
            )
        if paged_kernel and mesh is not None:
            raise ValueError(
                "paged_kernel does not compose with a tensor-parallel "
                "mesh yet; the TP engine runs the partitioned gather "
                "path"
            )
        self.paged_kernel = paged_kernel
        self._step_fns: Dict[Tuple[int, bool], object] = {}
        self._prefill_fns = {
            b: self._build_prefill(b) for b in self.buckets
        }
        self._prefix_prefill_fns: Dict[Tuple[int, int], object] = {}
        # pid -> (pool block ids, token count, the tokens themselves —
        # kept so spec-mode admissions can re-run the draft forward)
        self._prefixes: Dict[int, Tuple[List[int], int, np.ndarray]] = {}
        self._next_prefix_id = 0
        # one jitted prefix-forward per engine (re-wrapping
        # _forward_chunk per register_prefix call would recompile)
        self._prefix_forward = jax.jit(
            _forward_chunk, static_argnums=(3,)
        )
        # in-place pool scatter for register_prefix (donated like the
        # prefill/step programs; an eager .at[].set would copy the pool)
        self._pool_write = jax.jit(
            lambda pk, pv, mk, mv, phys: (
                _pool_set(pk, (slice(None), phys), mk),
                _pool_set(pv, (slice(None), phys), mv),
            ),
            donate_argnums=(0, 1),
        )

        # -- speculative mode ----------------------------------------
        self.draft_params = draft_params
        self.draft_cfg = draft_cfg
        self.gamma = gamma
        if draft_params is not None:
            assert draft_cfg is not None
            if mesh is not None:
                raise ValueError(
                    "speculative serving does not support a "
                    "tensor-parallel mesh yet (the draft's dense cache "
                    "is unsharded)"
                )
            if cfg.vocab != draft_cfg.vocab:
                raise ValueError("draft/target vocabularies must match")
            if cfg.moe_experts or draft_cfg.moe_experts:
                raise ValueError(
                    "speculative serving supports dense models"
                )
            if gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
            if top_k or top_p:
                raise ValueError(
                    "speculative serving supports greedy/temperature "
                    "sampling only (no engine-wide top-k/top-p)"
                )
            if draft_cfg.pos == "learned":
                assert draft_cfg.max_seq >= max_len
            dshape = (
                draft_cfg.n_layers, slots, max_len,
                draft_cfg.kv_heads, draft_cfg.head_dim,
            )
            self._draft_k = jnp.zeros(dshape, draft_cfg.dtype)
            self._draft_v = jnp.zeros(dshape, draft_cfg.dtype)
            self._draft_prefill_fns: Dict[int, object] = {}
            self._spec_step_fn = self._build_spec_step()
            self._draft_catchup_fn = self._build_draft_catchup()

    # -- pool array indirection --------------------------------------
    #
    # Every compiled program reads the pool through these and writes the
    # (donated) result back through them, so attaching a SharedKVPool
    # needed no change to any program or call site: a solo engine keeps
    # its own arrays, a shared-pool engine reads/writes the pool's — the
    # other role sees every update immediately.

    @property
    def _pool_k(self):
        if self.shared_pool is not None:
            return self.shared_pool.pool_k
        return self._pool_k_own

    @_pool_k.setter
    def _pool_k(self, value):
        if self.shared_pool is not None:
            self.shared_pool.pool_k = value
        else:
            self._pool_k_own = value

    @property
    def _pool_v(self):
        if self.shared_pool is not None:
            return self.shared_pool.pool_v
        return self._pool_v_own

    @_pool_v.setter
    def _pool_v(self, value):
        if self.shared_pool is not None:
            self.shared_pool.pool_v = value
        else:
            self._pool_v_own = value

    # -- paging helpers ----------------------------------------------

    def _blocks_for(self, n_positions: int) -> int:
        """Logical blocks needed to hold positions [0, n_positions)."""
        return -(-n_positions // self.block_size)

    def _ensure_blocks(self, slot: int, n_positions: int) -> None:
        """Allocate table entries so positions [0, n_positions) of
        ``slot`` are backed by pool blocks. The whole deficit is
        reclaimed in ONE cache sweep up front — per-alloc reclaim(1)
        backstops remain, but k blocks against a dry pool must not
        cost k full cache scans."""
        need = [
            j for j in range(self._blocks_for(n_positions))
            if self._table[slot, j] == _JUNK
        ]
        deficit = len(need) - len(self._alloc._free)
        if deficit > 0 and self._alloc.reclaim is not None:
            self._alloc.reclaim(deficit)
        for j in need:
            self._table[slot, j] = self._alloc.alloc()

    def _drop_row(self, slot: int) -> None:
        for j in range(self.max_blocks):
            bid = int(self._table[slot, j])
            if bid != _JUNK:
                self._alloc.drop(bid)
        self._table[slot, :] = _JUNK

    def _gather_bucket(self, needed_blocks: int) -> int:
        """Round a live-row block count up to a power-of-two bucket so
        the gathered step program compiles a handful of times, not
        once per length."""
        return gather_bucket(needed_blocks, self.max_blocks)

    @property
    def used_blocks(self) -> int:
        return self._alloc.used

    @property
    def kv_block_bytes(self) -> int:
        """HBM bytes one pool block holds across K+V and every layer
        (int8 pools count their scales) — the unit of the observatory's
        per-request KV occupancy attribution."""
        pk = self._pool_k
        if isinstance(pk, dict):
            per = (
                pk["q"].size * pk["q"].dtype.itemsize
                + pk["s"].size * pk["s"].dtype.itemsize
            )
        else:
            per = pk.size * pk.dtype.itemsize
        return int(2 * per // max(1, self.pool_blocks))

    def stats(self) -> Dict:
        """Structured serving status: block-pool occupancy, prefill
        accounting and (when enabled) prefix-cache counters — the
        payload behind the sampler's ``serving`` block on
        /debug/allocations and the doctor bundle, and the
        ``elastic_tpu_serving_*`` gauges."""
        out = {
            "slots": self.slots,
            "live_requests": len(self._slot_of),
            "pending_prefills": len(self._pending),
            "block_size": self.block_size,
            "pool_blocks": self.pool_blocks,
            "used_blocks": self.used_blocks,
            "pool_occupancy": round(
                self.used_blocks / max(1, self.pool_blocks - 1), 4
            ),
            "prefilled_tokens_total": self.prefilled_tokens_total,
            "admitted_tokens_total": self.admitted_tokens_total,
            "paged_kernel": self.paged_kernel,
            "kv_int8": self.kv_int8,
            "role": self.role,
            "adoptions_total": self.adoptions_total,
            "adopted_tokens_total": self.adopted_tokens_total,
            "stream_handoffs_out": self.stream_handoffs_out,
            "stream_handoffs_in": self.stream_handoffs_in,
        }
        if self._prefix_cache is not None:
            out["prefix_cache"] = self._prefix_cache.stats()
        if self.shared_pool is not None:
            out["shared_pool"] = {
                "adoptions": self.shared_pool.adoptions,
                "adopted_tokens": self.shared_pool.adopted_tokens,
                "published_requests": self.shared_pool.published_requests,
                "adopted_requests": self.shared_pool.adopted_requests,
                "published_streams": self.shared_pool.published_streams,
                "adopted_streams": self.shared_pool.adopted_streams,
                "expired_streams": self.shared_pool.expired_streams,
                "pending_streams": self.shared_pool.pending_streams,
            }
        if self.draft_params is not None:
            drafted = self.spec_drafted_total
            out["speculative"] = {
                "rounds": self.spec_rounds_total,
                "gamma": self.gamma,
                "drafted_tokens": drafted,
                "accepted_tokens": self.spec_accepted_total,
                "rejected_tokens": drafted - self.spec_accepted_total,
                "acceptance_rate": (
                    round(self.spec_accepted_total / drafted, 4)
                    if drafted else None
                ),
            }
        if self.moe_stats is not None:
            out["moe"] = self.moe_stats.stats()
        return out

    # -- compiled programs -------------------------------------------

    def _gathered_view(self, pk, pv, table_b):
        """[L, n_blocks, bs, g, h] pool + [slots, Bb] table -> dense
        [L, slots, Bb*bs, g, h] view (transient; bucket-bounded).
        int8 pools dequantize after the gather (reads stay
        int8-sized)."""
        L, _, bs, g, h = _pool_shape(pk)
        slots, Bb = table_b.shape
        flat = (slice(None), table_b.reshape(-1))
        kg = _pool_get(pk, flat).reshape(L, slots, Bb * bs, g, h)
        vg = _pool_get(pv, flat).reshape(L, slots, Bb * bs, g, h)
        return kg, vg

    def _decode_forward_paged(
        self, params, toks, pool_k, pool_v, table_b, lengths,
        wblk, woff,
    ):
        """One decode token per slot DIRECTLY against the pool: each
        layer writes its new K/V entry straight to the slot's block
        and attends through the Pallas paged kernel — no dense gather
        copy, each pool block read once (paged_attention.py). Plain
        single-token steps only (the spec step's gamma+1-wide verify
        keeps the gather path).

        This loop deliberately mirrors generate._forward_chunk's
        layer body (cache write + attention swapped for the pool
        forms); the cross-path stream-identity pins in
        tests/test_paged_attention.py are the tripwire for any future
        drift between the two."""
        from .paged_attention import paged_decode_attention

        cfg = self.cfg
        x = embed_lookup(params, toks[:, None], cfg.dtype)  # [s,1,d]
        posmat = lengths[:, None]                           # [s,1]
        if cfg.pos == "learned":
            x = x + params["pos_embed"].astype(cfg.dtype)[posmat]
        n_valid = lengths + 1  # incl. this step's written position
        for i, layer in enumerate(params["layers"]):
            h = _rmsnorm(x, layer["ln1_scale"])
            q, k_c, v_c = _qkv(h, layer, cfg)
            if cfg.pos == "rope":
                q = rope(q, posmat, cfg.rope_theta)
                k_c = rope(k_c, posmat, cfg.rope_theta)
            pool_k = pool_k.at[i, wblk, woff].set(
                k_c[:, 0].astype(pool_k.dtype)
            )
            pool_v = pool_v.at[i, wblk, woff].set(
                v_c[:, 0].astype(pool_v.dtype)
            )
            attn = paged_decode_attention(
                q[:, 0], pool_k[i], pool_v[i], table_b, n_valid,
                cfg.kv_heads, interpret=self._interpret,
                window=cfg.window,
            )
            x = x + jnp.einsum(
                "snh,nhd->sd", attn, wdense(layer, "wo", cfg.dtype)
            )[:, None]
            h2 = _rmsnorm(x, layer["ln2_scale"])
            if "moe" in layer:
                from .moe import moe_mlp

                y, _ = moe_mlp(
                    h2, layer["moe"], float(cfg.moe_experts),
                    mesh=None,
                )
                x = x + y
            else:
                h2 = jax.nn.gelu(jnp.einsum(
                    "std,df->stf", h2, wdense(layer, "w1", cfg.dtype)
                ))
                x = x + jnp.einsum(
                    "stf,fd->std", h2, wdense(layer, "w2", cfg.dtype)
                )
        x = _rmsnorm(x, params["final_norm_scale"])
        logits = jnp.einsum(
            "std,dv->stv", x, wdense(params, "lm_head", cfg.dtype)
        ).astype(jnp.float32)
        return logits[:, 0], pool_k, pool_v

    def _build_step_kernel(self, greedy: bool):
        """Plain step via the Pallas paged-attention path (engine
        constructed with paged_kernel=True): same signature/results
        as _build_step, no gather transient."""

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def step(
            params, pk, pv, table_b, lengths, toks, active, key,
            temp, tk, tp, wblk, woff,
        ):
            logits, pk, pv = self._decode_forward_paged(
                params, toks, pk, pv, table_b, lengths, wblk, woff
            )
            if greedy:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                nxt = _sample_rowwise(logits, key, temp, tk, tp)
            nxt = jnp.where(active, nxt, toks)
            lengths = jnp.where(active, lengths + 1, lengths)
            return pk, pv, lengths, nxt

        return step

    def _build_step(self, greedy: bool):
        """Step program; the gather width is carried by table_b's
        shape (jit traces per shape, so the (bucket, greedy) cache key
        in _step_fn matches the compiled programs 1:1)."""
        cfg = self.cfg

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def step(
            params, pk, pv, table_b, lengths, toks, active, key,
            temp, tk, tp, wblk, woff,
        ):
            kg, vg = self._gathered_view(pk, pv, table_b)
            cache = KVCache(k=kg, v=vg, length=jnp.int32(0))
            logits, cache = _forward_chunk(
                params, toks[:, None], cache, cfg,
                moe_drop_free=True, positions=lengths,
            )
            if greedy:
                nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
            else:
                nxt = _sample_rowwise(logits[:, 0], key, temp, tk, tp)
            # scatter the ONE written position per slot back to its
            # pool block (frozen slots aim at the junk block). CLIP
            # the extraction index: a frozen slot's stale length can
            # exceed the gathered width, and the default out-of-bounds
            # gather fill is NaN — which would land in the junk block
            # and poison every later row that gathers it (0 * NaN at
            # masked positions is NaN, not 0).
            idx = lengths.reshape(1, -1, 1, 1, 1)
            wk = jnp.take_along_axis(
                cache.k, idx, axis=2, mode="clip"
            )[:, :, 0]
            wv = jnp.take_along_axis(
                cache.v, idx, axis=2, mode="clip"
            )[:, :, 0]
            pk = _pool_set(pk, (slice(None), wblk, woff), wk)
            pv = _pool_set(pv, (slice(None), wblk, woff), wv)
            # frozen slots keep their token and length
            nxt = jnp.where(active, nxt, toks)
            lengths = jnp.where(active, lengths + 1, lengths)
            return pk, pv, lengths, nxt

        return step

    def _step_fn(self, n_b: int, greedy: bool):
        key = (n_b, greedy)
        if key not in self._step_fns:
            self._step_fns[key] = (
                self._build_step_kernel(greedy)
                if self.paged_kernel else self._build_step(greedy)
            )
        return self._step_fns[key]

    def _build_prefill(self, bucket: int):
        cfg = self.cfg
        bs = self.block_size
        nb = bucket // bs

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def prefill(params, pk, pv, padded, true_len, key, tkp, phys):
            # single-row chunk forward in a scratch cache, then
            # scatter its blocks into the pool (phys[j] = the slot's
            # physical block for logical block j, junk where the
            # request doesn't need the bucket's padded tail)
            mini = KVCache.empty(cfg, 1, bucket)
            logits, mini = _forward_chunk(
                params, padded[None], mini, cfg
            )
            L, _, _, g, h = _pool_shape(pk)
            mk = mini.k.reshape(L, nb, bs, g, h)
            mv = mini.v.reshape(L, nb, bs, g, h)
            pk = _pool_set(pk, (slice(None), phys), mk)
            pv = _pool_set(pv, (slice(None), phys), mv)
            first = _sample_rowwise(
                logits[:, true_len - 1], key,
                tkp[0:1], tkp[1:2].astype(jnp.int32), tkp[2:3],
            )[0]
            return pk, pv, first

        return prefill

    def _build_prefix_prefill(self, pref_padded: int, bucket: int):
        """Like _build_prefill, but the chunk CONTINUES a cached
        prefix: the prefix's blocks are GATHERED from the pool into
        the scratch cache (its forward is never recomputed) and only
        the blocks the prompt wrote scatter back — shared prefix
        blocks are never touched, so sharing is copy-free (a partial
        tail block lands in a private block via the same scatter).
        ``pref_padded`` = prefix length rounded up to a block
        multiple."""
        cfg = self.cfg
        bs = self.block_size
        npb = pref_padded // bs
        nb = (pref_padded + bucket) // bs

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def prefill(
            params, pk, pv, pref_phys, plen, padded, true_len, key,
            tkp, phys,
        ):
            L, _, _, g, h = _pool_shape(pk)
            mini = KVCache.empty(cfg, 1, pref_padded + bucket)
            pidx = (slice(None), pref_phys)
            pref_k = _pool_get(pk, pidx).reshape(
                L, 1, pref_padded, g, h
            ).astype(mini.k.dtype)
            pref_v = _pool_get(pv, pidx).reshape(
                L, 1, pref_padded, g, h
            ).astype(mini.v.dtype)
            mini = KVCache(
                k=jax.lax.dynamic_update_slice(
                    mini.k, pref_k, (0, 0, 0, 0, 0)
                ),
                v=jax.lax.dynamic_update_slice(
                    mini.v, pref_v, (0, 0, 0, 0, 0)
                ),
                length=plen,
            )
            logits, mini = _forward_chunk(params, padded[None], mini, cfg)
            mk = mini.k.reshape(L, nb, bs, g, h)
            mv = mini.v.reshape(L, nb, bs, g, h)
            pk = _pool_set(pk, (slice(None), phys), mk)
            pv = _pool_set(pv, (slice(None), phys), mv)
            first = _sample_rowwise(
                logits[:, true_len - 1], key,
                tkp[0:1], tkp[1:2].astype(jnp.int32), tkp[2:3],
            )[0]
            return pk, pv, first

        return prefill

    def _build_chunk_prefill(self, n_b: int):
        """One block-sized prefill CHUNK for a single pending row:
        gather the row's first ``n_b`` blocks, run the chunk at
        positions [start, start+block), scatter the one written block
        back. enqueue()+step() drives this once per step so live
        decodes never stall behind a long prompt (the chunked-prefill
        interleave lever). Returns the chunk's logits so the FINAL
        chunk can sample the first token host-side."""
        cfg = self.cfg
        bs = self.block_size

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def chunk_prefill(params, pk, pv, row_blocks, toks, start, wphys):
            L, _, _, g, h = _pool_shape(pk)
            ridx = (slice(None), row_blocks)
            kg = _pool_get(pk, ridx).reshape(L, 1, n_b * bs, g, h)
            vg = _pool_get(pv, ridx).reshape(L, 1, n_b * bs, g, h)
            cache = KVCache(
                k=kg.astype(cfg.dtype), v=vg.astype(cfg.dtype),
                length=start,
            )
            logits, cache = _forward_chunk(
                params, toks[None], cache, cfg
            )
            wk = jax.lax.dynamic_slice(
                cache.k, (0, 0, start, 0, 0), (L, 1, bs, g, h)
            )[:, 0]
            wv = jax.lax.dynamic_slice(
                cache.v, (0, 0, start, 0, 0), (L, 1, bs, g, h)
            )[:, 0]
            pk = _pool_set(pk, (slice(None), wphys), wk)
            pv = _pool_set(pv, (slice(None), wphys), wv)
            return pk, pv, logits[0]

        return chunk_prefill

    def _prefill_tail_chunks(
        self, slot, seq, total: int, start: int, key, tkp
    ) -> int:
        """Synchronous block-chunked prefill of positions
        [start, total) of ``seq`` for an automatic prefix-cache hit:
        the same per-chunk program _pump_prefill drives (keyed by the
        power-of-two gather bucket, so compiles stay bounded no matter
        what widths cached chains take). Samples and returns the first
        generated token from the last REAL prompt position."""
        bs = self.block_size
        pos = start
        logits = None
        while pos < total:
            chunk = np.zeros((bs,), np.int32)
            avail = min(bs, total - pos)
            chunk[:avail] = seq[pos:pos + avail]
            n_b = self._gather_bucket(self._blocks_for(pos + bs))
            if n_b not in self._chunk_prefill_fns:
                self._chunk_prefill_fns[n_b] = (
                    self._build_chunk_prefill(n_b)
                )
            row_blocks = self._table[slot, :n_b].astype(np.int32)
            self._pool_k, self._pool_v, logits = (
                self._chunk_prefill_fns[n_b](
                    self.params, self._pool_k, self._pool_v,
                    jnp.asarray(row_blocks), jnp.asarray(chunk),
                    jnp.int32(pos),
                    jnp.int32(self._table[slot, pos // bs]),
                )
            )
            pos += bs
        return int(_sample_rowwise(
            logits[(total - 1) - (pos - bs)][None], key,
            tkp[0:1], tkp[1:2].astype(jnp.int32), tkp[2:3],
        )[0])

    def _pump_prefill(self) -> Dict[int, int]:
        """Advance the OLDEST pending admission by one chunk; on its
        final chunk, sample the first token and activate the row.
        Returns {rid: first_token} when a row activates, else {}."""
        rid = self._pending[0]
        st = self._pending_state[rid]
        slot, seq, total = st["slot"], st["seq"], st["total"]
        bs = self.block_size
        start = st["next_pos"]
        obs = self._observatory
        ouid = self._obs_uid.get(rid)
        if obs is not None and ouid is not None and start == st["start0"]:
            # first chunk: the request leaves the queue — queued ends,
            # prefill begins (and spans the inter-chunk waits until
            # activation: that wait IS prefill latency to the client)
            obs.prefill_start(ouid)
        chunk = np.zeros((bs,), np.int32)
        avail = min(bs, total - start)
        chunk[:avail] = seq[start:start + avail]
        n_b = self._gather_bucket(self._blocks_for(start + bs))
        if n_b not in self._chunk_prefill_fns:
            self._chunk_prefill_fns[n_b] = self._build_chunk_prefill(n_b)
        row_blocks = self._table[slot, :n_b].astype(np.int32)
        self._pool_k, self._pool_v, logits = self._chunk_prefill_fns[
            n_b
        ](
            self.params, self._pool_k, self._pool_v,
            jnp.asarray(row_blocks), jnp.asarray(chunk),
            jnp.int32(start), jnp.int32(self._table[slot, start // bs]),
        )
        st["next_pos"] = start + bs
        if st["next_pos"] < total:
            return {}
        # final chunk: sample the first token from the last REAL
        # prompt position and activate the row
        self._pending.pop(0)
        self._pending_state.pop(rid)
        self._key, sub = jax.random.split(self._key)
        tkp = st["tkp"]
        first = int(_sample_rowwise(
            logits[(total - 1) - start][None], sub,
            jnp.asarray([tkp[0]], jnp.float32),
            jnp.asarray([tkp[1]], jnp.int32),
            jnp.asarray([tkp[2]], jnp.float32),
        )[0])
        self.prefilled_tokens_total += total - st["start0"]
        self.admitted_tokens_total += total
        if self._prefix_cache is not None:
            self._prefix_cache.insert(seq[:total], self._table[slot])
        if self.draft_params is not None:
            self._draft_prefill_row(slot, seq, total)
        self._lengths = self._lengths.at[slot].set(total)
        self._host_len[slot] = total
        self._last = self._last.at[slot].set(first)
        self._slot_of[rid] = slot
        self._streams[rid] = [first]
        if self.shared_pool is not None:
            self._seq_tokens[rid] = np.asarray(
                seq[:total], np.int32
            ).copy()
        if obs is not None and ouid is not None:
            blocks = int(np.count_nonzero(self._table[slot]))
            obs.prefill_done(
                ouid, computed_tokens=total - st["start0"],
                kv_blocks=blocks,
                kv_bytes=blocks * self.kv_block_bytes,
            )
            if self.role != "prefill":
                obs.first_token(ouid)
        if first in self._stop[rid]:
            self._finish(rid, "stop_token")
        elif self.role == "prefill":
            # Prefill role (see admit): publish-and-release — the slot
            # frees for the next queued prompt instead of decoding.
            self._finish(rid, "prefilled")
        return {rid: first}

    # -- speculative-mode programs -----------------------------------

    def _build_draft_prefill(self, width: int):
        """Prefill the DRAFT's dense cache row for an admission: the
        full (prefix + prompt) token run as one chunk (the draft is
        cheap — recomputing its prefix forward per admission beats
        keeping a second paged pool coherent)."""
        dcfg = self.draft_cfg

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def prefill(draft_params, dk, dv, padded, slot):
            mini = KVCache.empty(dcfg, 1, width)
            _, mini = _forward_chunk(
                draft_params, padded[None], mini, dcfg
            )
            dk = jax.lax.dynamic_update_slice(
                dk, mini.k, (0, slot, 0, 0, 0)
            )
            dv = jax.lax.dynamic_update_slice(
                dv, mini.v, (0, slot, 0, 0, 0)
            )
            return dk, dv

        return prefill

    def _draft_prefill_row(self, slot, seq, total, width=None):
        """Prefill the draft's dense row for positions [0, total) of
        ``seq`` (full recompute — the draft is cheap by design). The
        default width rounds through the power-of-two block buckets so
        activations compile a handful of programs, not one per prompt
        length."""
        if width is None:
            width = (
                self._gather_bucket(self._blocks_for(total))
                * self.block_size
            )
        run = np.zeros((width,), np.int32)
        run[:total] = seq[:total]
        if width not in self._draft_prefill_fns:
            self._draft_prefill_fns[width] = (
                self._build_draft_prefill(width)
            )
        self._draft_k, self._draft_v = self._draft_prefill_fns[width](
            self.draft_params, self._draft_k, self._draft_v,
            jnp.asarray(run), jnp.int32(slot),
        )

    def _build_draft_catchup(self):
        """Feed ``last`` through the draft at each row's position —
        used when a near-max_len row forces a plain (non-speculative)
        step, so the draft cache keeps mirroring the target's
        'cached = everything but last' invariant."""
        dcfg = self.draft_cfg

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def catchup(draft_params, dk, dv, lengths, toks):
            cache = KVCache(k=dk, v=dv, length=jnp.int32(0))
            _, cache = _forward_chunk(
                draft_params, toks[:, None], cache, dcfg,
                moe_drop_free=True, positions=lengths,
            )
            return cache.k, cache.v

        return catchup

    @staticmethod
    def _probs_rowwise(logits, temp, vocab):
        """Per-row sampling distribution: one-hot argmax for greedy
        rows (temp == 0, which makes the accept/resample algebra
        reduce to exact greedy matching), softmax(logits/T) else.
        logits [..., b, vocab], temp [b]."""
        t = jnp.maximum(temp, 1e-6)[..., None]
        p = jax.nn.softmax(logits / t, axis=-1)
        onehot = jax.nn.one_hot(
            jnp.argmax(logits, axis=-1), vocab, dtype=jnp.float32
        )
        return jnp.where((temp <= 0.0)[..., None], onehot, p)

    def _build_spec_step(self):
        """The speculative step over ALL slots in lockstep: draft
        scan (gamma single-token rows), ONE target verify chunk of
        width gamma+1 at per-row positions, per-row Leviathan
        accept/resample, commit + scatter-back. Invariant (same as
        speculative.py's cursor-1): ``lengths`` counts CACHED
        positions — every committed token except the trailing
        ``last``, which each round re-feeds as its chunk head."""
        cfg = self.cfg
        dcfg = self.draft_cfg
        gamma = self.gamma
        vocab = cfg.vocab

        @functools.partial(jax.jit, donate_argnums=(1, 2, 3, 4))
        def spec_step(
            params, pk, pv, dk, dv, table_b, lengths, toks, active,
            key, temp, wblk, woff, draft_params,
        ):
            slots = toks.shape[0]

            # -- draft proposes gamma tokens per row -----------------
            def draft_step(carry, i):
                dk, dv, tok, key = carry
                key, sub = jax.random.split(key)
                cache = KVCache(k=dk, v=dv, length=jnp.int32(0))
                logits, cache = _forward_chunk(
                    draft_params, tok[:, None], cache, dcfg,
                    moe_drop_free=True, positions=lengths + i,
                )
                q = self._probs_rowwise(logits[:, 0], temp, vocab)
                nxt = jax.random.categorical(
                    sub, jnp.log(jnp.maximum(q, 1e-30)), axis=-1
                ).astype(jnp.int32)
                return (cache.k, cache.v, nxt, key), (nxt, q)

            key, dkey = jax.random.split(key)
            (dk, dv, _, _), (draft_toks, draft_q) = jax.lax.scan(
                draft_step, (dk, dv, toks, dkey),
                jnp.arange(gamma),
            )
            draft_toks = jnp.moveaxis(draft_toks, 0, 1)  # [slots, g]
            draft_q = jnp.moveaxis(draft_q, 0, 1)        # [slots, g, V]
            # cache d_gamma too: a fully-accepted round needs its
            # entry next round (stale-but-masked on partial accepts)
            cache = KVCache(k=dk, v=dv, length=jnp.int32(0))
            _, cache = _forward_chunk(
                draft_params, draft_toks[:, gamma - 1][:, None],
                cache, dcfg, moe_drop_free=True,
                positions=lengths + gamma,
            )
            dk, dv = cache.k, cache.v

            # -- target verifies all rows' gamma+1 positions at once -
            kg, vg = self._gathered_view(pk, pv, table_b)
            chunk = jnp.concatenate(
                [toks[:, None], draft_toks], axis=1
            )  # [slots, gamma+1]
            tcache = KVCache(k=kg, v=vg, length=jnp.int32(0))
            tlogits, tcache = _forward_chunk(
                params, chunk, tcache, cfg,
                moe_drop_free=True, positions=lengths,
            )
            target_p = self._probs_rowwise(
                tlogits, temp[:, None], vocab
            )  # [slots, gamma+1, V]

            # scatter ALL gamma+1 written positions back to the pool
            # (rejected tails are stale-but-masked, overwritten by the
            # next round's chunk at the same positions)
            pos = lengths[:, None] + jnp.arange(gamma + 1)[None]
            idx = jnp.minimum(
                pos, kg.shape[2] - 1
            ).reshape(1, slots, gamma + 1, 1, 1)
            wk = jnp.take_along_axis(tcache.k, idx, axis=2, mode="clip")
            wv = jnp.take_along_axis(tcache.v, idx, axis=2, mode="clip")
            pk = _pool_set(pk, (slice(None), wblk, woff), wk)
            pv = _pool_set(pv, (slice(None), wblk, woff), wv)

            # -- per-row Leviathan accept / resample -----------------
            p_i = jnp.take_along_axis(
                target_p[:, :gamma], draft_toks[..., None], axis=-1
            )[..., 0]                                   # [slots, g]
            q_i = jnp.take_along_axis(
                draft_q, draft_toks[..., None], axis=-1
            )[..., 0]
            key, ukey = jax.random.split(key)
            u = jax.random.uniform(ukey, (slots, gamma))
            ok = u < jnp.minimum(1.0, p_i / jnp.maximum(q_i, 1e-30))
            n_acc = jnp.sum(
                jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1
            )                                           # [slots]

            cut = jnp.minimum(n_acc, gamma - 1)
            p_cut = jnp.take_along_axis(
                target_p[:, :gamma], cut[:, None, None], axis=1
            )[:, 0]                                     # [slots, V]
            q_cut = jnp.take_along_axis(
                draft_q, cut[:, None, None], axis=1
            )[:, 0]
            resid = jnp.maximum(p_cut - q_cut, 0.0)
            rsum = jnp.sum(resid, axis=-1, keepdims=True)
            resid = jnp.where(rsum > 0, resid / jnp.maximum(rsum, 1e-30), p_cut)
            correction_dist = jnp.where(
                (n_acc == gamma)[:, None], target_p[:, gamma], resid
            )
            key, ckey = jax.random.split(key)
            correction = jax.random.categorical(
                ckey, jnp.log(jnp.maximum(correction_dist, 1e-30)),
                axis=-1,
            ).astype(jnp.int32)                         # [slots]

            # committed tokens this round: draft_toks[:n_acc] then the
            # correction; slots >= n_acc carry the correction value
            # (only slot n_acc of those is real — the host slices by
            # n_emit)
            emit = jnp.concatenate(
                [draft_toks, correction[:, None]], axis=1
            )
            committed = jnp.where(
                jnp.arange(gamma + 1)[None] < n_acc[:, None],
                emit, correction[:, None],
            )                                           # [slots, g+1]
            n_emit = jnp.where(active, n_acc + 1, 0)
            lengths = jnp.where(active, lengths + n_acc + 1, lengths)
            last = jnp.where(active, correction, toks)
            return pk, pv, dk, dv, lengths, last, committed, n_emit

        return spec_step

    # -- host API ----------------------------------------------------

    def register_prefix(self, tokens) -> int:
        """Prefill a shared prefix (e.g. a system prompt) ONCE into
        pool blocks; admit() with ``prefix=`` then maps those blocks
        into the request's table under refcounts instead of
        recomputing (or copying) the prefix. Returns a prefix id."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        plen = len(tokens)
        # admission control raises (not assert): under python -O a
        # vanished check would silently corrupt a slot's stream
        if plen == 0:
            raise ValueError("empty prefix")
        bucket = next((b for b in self.buckets if b >= plen), None)
        if bucket is None:
            raise ValueError(
                f"prefix length {plen} exceeds largest bucket "
                f"{self.buckets[-1]}"
            )
        padded = jnp.zeros((bucket,), jnp.int32).at[:plen].set(
            jnp.asarray(tokens)
        )
        mini = KVCache.empty(self.cfg, 1, bucket)
        _, mini = self._prefix_forward(
            self.params, padded[None], mini, self.cfg
        )
        # scatter the prefix's blocks into the pool; bucket-padding
        # blocks past the prefix go to junk
        bs = self.block_size
        need = self._blocks_for(plen)
        deficit = need - len(self._alloc._free)
        if deficit > 0 and self._alloc.reclaim is not None:
            self._alloc.reclaim(deficit)  # one sweep, not one per block
        block_ids: List[int] = []
        try:
            for _ in range(need):
                block_ids.append(self._alloc.alloc())
        except RuntimeError as e:
            # free the partial grab — a failed registration must not
            # wedge the pool — and raise the admission-control type
            for bid in block_ids:
                self._alloc.drop(bid)
            raise ValueError(str(e)) from e
        phys = np.full((bucket // bs,), _JUNK, np.int32)
        phys[:need] = block_ids
        L = self.cfg.n_layers
        g, h = self.cfg.kv_heads, self.cfg.head_dim
        mk = mini.k.reshape(L, bucket // bs, bs, g, h)
        mv = mini.v.reshape(L, bucket // bs, bs, g, h)
        # donated write: the pool is the engine's dominant HBM
        # allocation, an undonated .at[].set would transiently double it
        self._pool_k, self._pool_v = self._pool_write(
            self._pool_k, self._pool_v, mk, mv, jnp.asarray(phys)
        )
        pid = self._next_prefix_id
        self._next_prefix_id += 1
        # tokens kept for speculative mode: the draft re-runs the
        # full (prefix + prompt) forward at admission
        self._prefixes[pid] = (block_ids, plen, tokens)
        return pid

    def release_prefix(self, pid: int) -> None:
        """Drop the prefix's hold on its pool blocks. In-flight
        requests admitted with it are unaffected — their tables hold
        refcounted shares, and the blocks free only when the last
        sharer releases."""
        block_ids, _, _ = self._prefixes.pop(pid)
        for bid in block_ids:
            self._alloc.drop(bid)

    def _claim_admission(
        self, prompt, prefix, temperature, top_k, top_p,
        need_bucket: bool, slo: Optional[str] = None,
    ):
        """Shared admission control for admit() and enqueue():
        validate, claim a slot, resolve per-request sampling, and map
        blocks (shared full prefix blocks + private allocations),
        rolling everything back on failure. Returns the claim as a
        dict; ``need_bucket`` additionally resolves the synchronous
        path's prompt bucket."""
        if self._lifecycle is not None:
            self._lifecycle.poll()
            if getattr(self._lifecycle, "draining", False):
                # ValueError: the engine's admission-control type (slot
                # exhaustion, oversize prompts raise it too) — a serving
                # loop that rejects/queues on ValueError must treat a
                # drain refusal the same way, not die on it
                raise ValueError(
                    "engine draining: the node signalled "
                    "ELASTIC_TPU_DRAIN — no new admissions; finish "
                    "in-flight streams (lifecycle.drain_serving) and ack"
                )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = len(prompt)
        if p == 0:
            raise ValueError("empty prompt")
        pref_blocks, plen, pref_padded = [], 0, 0
        pref_tokens = np.zeros((0,), np.int32)
        auto_hit = False
        if prefix is not None:
            if prefix not in self._prefixes:
                raise ValueError(
                    f"unknown or released prefix {prefix}"
                )
            pref_blocks, plen, pref_tokens = self._prefixes[prefix]
            pref_padded = self._blocks_for(plen) * self.block_size
        elif self._prefix_cache is not None:
            # automatic prefix cache: reuse the longest cached block
            # chain as an internal (block-aligned) prefix. Always leave
            # >= 1 prompt token to prefill — the tail forward is where
            # the first generated token's logits come from. (Hit/miss
            # accounting happens at claim SUCCESS, not here: a lookup
            # whose admission then fails reused nothing.)
            bs = self.block_size
            blocks, covered = self._prefix_cache.lookup(
                prompt[: ((p - 1) // bs) * bs]
            )
            if covered:
                auto_hit = True
                pref_blocks, plen, pref_padded = blocks, covered, covered
                pref_tokens = prompt[:covered]
                prompt = prompt[covered:]
                p = len(prompt)
        bucket = None
        if need_bucket:
            bucket = next(
                (b for b in self.buckets if b >= p), None
            )
            if bucket is None:
                raise ValueError(
                    f"prompt length {p} exceeds largest bucket "
                    f"{self.buckets[-1]}"
                )
        total = plen + p
        if total >= self.max_len:
            raise ValueError(
                f"prefix+prompt length {total} leaves no room to "
                f"decode (max_len {self.max_len})"
            )
        if (
            need_bucket and not auto_hit
            and pref_padded + bucket > self.max_len
        ):
            # the EXPLICIT-prefix mini program is (pref_padded +
            # bucket) wide; auto-cache tails prefill chunked instead,
            # so only that path carries this constraint
            raise ValueError(
                "prefix bucket + prompt bucket exceed the slot row"
            )
        if not self._free:
            raise ValueError("no free slot; release() one first")
        slot = self._free.pop(0)

        d_temp, d_topk, d_topp = self._sampling
        temp = d_temp if temperature is None else float(temperature)
        tk = d_topk if top_k is None else int(top_k)
        tp = d_topp if top_p is None else float(top_p)
        if self.draft_params is not None and (tk or tp):
            self._free.insert(0, slot)
            raise ValueError(
                "speculative serving supports greedy/temperature "
                "sampling only (no top-k/top-p)"
            )
        self._row_temp[slot] = temp
        self._row_topk[slot] = tk
        self._row_topp[slot] = tp

        # block mapping: share full prefix blocks, allocate the rest
        # (incl. the next decode write's block)
        bs = self.block_size
        n_shared = plen // bs          # only FULL blocks are shared
        try:
            for j in range(n_shared):
                self._table[slot, j] = self._alloc.share(pref_blocks[j])
            self._ensure_blocks(slot, total + 1)
        except RuntimeError as e:
            self._drop_row(slot)
            self._free.append(slot)
            self._free.sort()
            raise ValueError(str(e)) from e
        if self._prefix_cache is not None and prefix is None:
            # the claim HELD (slot + blocks are this request's now):
            # this admission counts against the cache
            self._prefix_cache.record_admission(plen if auto_hit else 0)
            if auto_hit:
                self.adoptions_total += 1
                self.adopted_tokens_total += plen
                if self.shared_pool is not None:
                    # cross-role handoff accounting (SharedKVPool)
                    self.shared_pool.adoptions += 1
                    self.shared_pool.adopted_tokens += plen
        # -- request observatory: the claim held, so the partition
        # opens here. A decode-role auto hit over a shared pool first
        # tries to ADOPT the record the prefill role published under
        # the covered prefix's chain digest — that continues the SAME
        # partition across the handoff instead of minting a new id.
        ouid = None
        obs = self._observatory
        if obs is not None:
            from .prefix_cache import chain_hashes

            seq = np.concatenate([pref_tokens, prompt]).astype(np.int32)
            digests = chain_hashes(seq, bs)
            adopted = None
            if auto_hit and self.shared_pool is not None and n_shared:
                adopted = self.shared_pool.adopt_request(
                    digests[n_shared - 1]
                )
            if adopted is not None:
                ouid = obs.adopt(adopted, engine_key=id(self))
            else:
                ouid = obs.admit(id(self), slo=slo)
            obs.prefill_done(
                ouid,
                cached_tokens=plen,
                prefix_digest=digests[-1].hex() if digests else "",
                chain_digests=tuple(digests),
            )
        return dict(
            prompt=prompt, p=p, bucket=bucket,
            pref_blocks=pref_blocks, plen=plen,
            pref_tokens=pref_tokens, pref_padded=pref_padded,
            total=total, slot=slot, n_shared=n_shared,
            temp=temp, tk=tk, tp=tp, auto_hit=auto_hit, ouid=ouid,
        )

    def admit(
        self,
        prompt,
        prefix: Optional[int] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        stop_tokens: Sequence[int] = (),
        slo: Optional[str] = None,
    ) -> int:
        """Prefill a prompt (1-D int sequence) into a free slot;
        returns the request id. The first generated token is already in
        stream(rid). With ``prefix=``, the request's sequence is
        (registered prefix + prompt) but only the prompt's forward
        runs, and full prefix blocks are SHARED, not copied.

        temperature/top_k/top_p override the engine-wide constructor
        defaults FOR THIS REQUEST (None = keep the default); requests
        with different sampling configs batch into the same step
        program. ``stop_tokens``: emitting any of these auto-finishes
        the request in step() — the stop token IS appended to the
        stream (callers that want it hidden strip the tail), and the
        slot frees without the caller polling.

        ``slo`` is the request-carried SLO-class annotation
        ("ttft"|"tpot"|"batch", default batch) the request observatory
        buckets TTFT/TPOT histograms by; it is accounting only and
        never changes scheduling."""
        t0 = time.perf_counter() if self._recorder is not None else 0.0
        claim = self._claim_admission(
            prompt, prefix, temperature, top_k, top_p,
            need_bucket=True, slo=slo,
        )
        prompt, p, bucket = claim["prompt"], claim["p"], claim["bucket"]
        pref_blocks, plen = claim["pref_blocks"], claim["plen"]
        pref_tokens, pref_padded = (
            claim["pref_tokens"], claim["pref_padded"]
        )
        total, slot, n_shared = (
            claim["total"], claim["slot"], claim["n_shared"]
        )
        temp, tk, tp = claim["temp"], claim["tk"], claim["tp"]
        bs = self.block_size
        nb_req = self._blocks_for(total + 1)

        # synchronous prefill = the unified-mode head-of-line hazard:
        # every live decode on this engine sits still until it lands.
        # The observatory attributes that time to their ``stalled``
        # phase (disaggregation exists to make this window vanish).
        obs, ouid = self._observatory, claim["ouid"]
        if obs is not None and ouid is not None:
            obs.prefill_start(ouid)
            obs.stall_begin(id(self))

        self._key, sub = jax.random.split(self._key)
        # sampling params ride in ONE traced f32 triple (top_k cast
        # back inside) so per-request values never retrace the prefill
        tkp = jnp.asarray([temp, float(tk), tp], jnp.float32)
        if claim["auto_hit"]:
            # automatic cache hit: the tail prefills CHUNKED through
            # the power-of-two-bounded chunk-prefill family. A cached
            # chain's width is whatever traffic produced, and a
            # per-(covered, bucket) prefix program would mint a fresh
            # multi-second XLA compile per distinct depth.
            first = self._prefill_tail_chunks(
                slot,
                np.concatenate([pref_tokens, prompt]).astype(np.int32),
                total, n_shared * bs, sub, tkp,
            )
            pk, pv = self._pool_k, self._pool_v
        elif plen:
            padded = jnp.zeros((bucket,), jnp.int32)
            padded = padded.at[:p].set(jnp.asarray(prompt))
            # explicit registered prefix: continue the pool-resident
            # K/V prefix in one (pref_padded + bucket)-wide program
            fn_key = (pref_padded, bucket)
            if fn_key not in self._prefix_prefill_fns:
                self._prefix_prefill_fns[fn_key] = (
                    self._build_prefix_prefill(*fn_key)
                )
            # scatter map over the mini's logical blocks: shared
            # prefix blocks are NOT written back (junk), the partial
            # prefix tail + prompt land in this slot's private blocks,
            # bucket padding past the request's need goes to junk
            nb_mini = (pref_padded + bucket) // bs
            phys = np.full((nb_mini,), _JUNK, np.int32)
            for j in range(n_shared, min(nb_req, nb_mini)):
                phys[j] = self._table[slot, j]
            # gather map for the prefix's own blocks (pref_padded is
            # exactly len(pref_blocks) * block_size by construction)
            pref_phys = np.asarray(pref_blocks, np.int32)
            # true_len is CHUNK-relative: the last real prompt token
            # sits at chunk index p-1 (absolute plen+p-1)
            pk, pv, first = self._prefix_prefill_fns[fn_key](
                self.params, self._pool_k, self._pool_v,
                jnp.asarray(pref_phys), jnp.int32(plen), padded,
                jnp.int32(p), sub, tkp, jnp.asarray(phys),
            )
        else:
            padded = jnp.zeros((bucket,), jnp.int32)
            padded = padded.at[:p].set(jnp.asarray(prompt))
            nb_mini = bucket // bs
            phys = np.full((nb_mini,), _JUNK, np.int32)
            for j in range(min(nb_req, nb_mini)):
                phys[j] = self._table[slot, j]
            pk, pv, first = self._prefill_fns[bucket](
                self.params, self._pool_k, self._pool_v, padded,
                jnp.int32(p), sub, tkp, jnp.asarray(phys),
            )
        self._pool_k, self._pool_v = pk, pv
        self.prefilled_tokens_total += p
        self.admitted_tokens_total += total
        if self._prefix_cache is not None:
            # publish the admission's full token blocks (cache-shared
            # ones dedupe by digest); the hash history is the REAL
            # sequence, so explicit-prefix admissions publish too
            self._prefix_cache.insert(
                np.concatenate([pref_tokens, prompt]).astype(np.int32),
                self._table[slot],
            )
        if self.draft_params is not None:
            # prefill the draft's dense row on the FULL sequence (the
            # prefix's tokens were kept at registration). Explicit
            # prefixes share the target's static (pref_padded + bucket)
            # width family; auto-cache hits take arbitrary widths, so
            # they use the default power-of-two rounding instead.
            seq = np.concatenate(
                [pref_tokens, prompt]
            ).astype(np.int32)
            self._draft_prefill_row(
                slot, seq, total,
                width=(
                    None if claim["auto_hit"]
                    else pref_padded + bucket
                ),
            )
        if obs is not None and ouid is not None:
            obs.stall_end(id(self))
        self._lengths = self._lengths.at[slot].set(total)
        self._host_len[slot] = total
        self._last = self._last.at[slot].set(first)
        rid = self._next_rid
        self._next_rid += 1
        self._slot_of[rid] = slot
        self._streams[rid] = [int(first)]
        self._stop[rid] = frozenset(int(t) for t in stop_tokens)
        if self.shared_pool is not None:
            self._seq_tokens[rid] = np.concatenate(
                [pref_tokens, prompt]
            ).astype(np.int32)
        if obs is not None and ouid is not None:
            self._obs_uid[rid] = ouid
            blocks = int(np.count_nonzero(self._table[slot]))
            obs.prefill_done(
                ouid, computed_tokens=p, kv_blocks=blocks,
                kv_bytes=blocks * self.kv_block_bytes,
            )
            if self.role != "prefill":
                # a prefill-role first token is a publication artifact,
                # not the client-visible TTFT — the stitched record's
                # decode side stamps that
                obs.first_token(ouid)
        # the admission token itself may be a stop token
        if int(first) in self._stop[rid]:
            self._finish(rid, "stop_token")
        elif self.role == "prefill":
            # Prefill role: the published cache blocks ARE the output —
            # free the slot immediately (the decode-role engine adopts
            # the blocks and owns the stream from here; the sampled
            # first token stays retrievable for the caller to compare).
            self._finish(rid, "prefilled")
        if self._recorder is not None:
            from .request_obs import normalize_slo

            rec = dict(
                rid=rid, prompt_len=p, prefix_len=plen, bucket=bucket,
                duration_ms=round((time.perf_counter() - t0) * 1000, 3),
                used_blocks=self.used_blocks,
                # SLO class + observatory id: sidecar summaries join
                # flight records against /debug/requests on these
                slo=normalize_slo(slo),
            )
            if ouid is not None:
                rec["request_uid"] = ouid
            if claim["auto_hit"]:
                rec["cached_tokens"] = plen
            if self._prefix_cache is not None:
                rec["prefix_cache_hit"] = bool(claim["auto_hit"])
            self._recorder.record("serving_admit", **rec)
        return rid

    def enqueue(
        self,
        prompt,
        prefix: Optional[int] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        stop_tokens: Sequence[int] = (),
        slo: Optional[str] = None,
    ) -> int:
        """CHUNKED admission: claim a slot and blocks now, but run the
        prefill one block-sized chunk per step() — live decodes
        advance every step instead of stalling behind the whole
        prompt (admit() runs the prefill synchronously). The request
        activates — its first token appears in a step() result — once
        its last chunk lands. A pending rid can be cancelled with
        release() (returns []).

        Chunks re-run the sequence from the first NON-SHARED block
        boundary: full prefix blocks stay shared untouched, and an
        unaligned prefix tail is simply recomputed into the private
        tail block (the tokens were kept at registration), which is
        why no tail copy exists on this path."""
        claim = self._claim_admission(
            prompt, prefix, temperature, top_k, top_p,
            need_bucket=False, slo=slo,
        )
        rid = self._next_rid
        self._next_rid += 1
        if claim["ouid"] is not None:
            self._obs_uid[rid] = claim["ouid"]
        self._stop[rid] = frozenset(int(t) for t in stop_tokens)
        self._pending.append(rid)
        self._pending_state[rid] = dict(
            slot=claim["slot"],
            seq=np.concatenate(
                [claim["pref_tokens"], claim["prompt"]]
            ).astype(np.int32),
            total=claim["total"],
            next_pos=claim["n_shared"] * self.block_size,
            start0=claim["n_shared"] * self.block_size,
            tkp=(claim["temp"], float(claim["tk"]), claim["tp"]),
        )
        return rid

    def step(self) -> Dict[int, object]:
        """Advance every live request; auto-finishes rows that fill
        to max_len, emit a stop token, or starve for pool blocks
        (``finish_reason`` says which; streams stay retrievable via
        release(); step() never raises mid-decode).

        Plain engines return {rid: token} — one token per live
        request. SPECULATIVE engines (constructed with draft_params)
        return {rid: [tokens...]} — each row commits its accepted
        draft prefix + correction, so lists have variable length ≥ 1
        per step."""
        t0 = time.perf_counter() if self._recorder is not None else 0.0
        obs = self._observatory
        ot0 = obs.clock.monotonic() if obs is not None else 0.0
        ev0 = self._evictions_total
        # one pending-prefill chunk per step (enqueue()): live decodes
        # never stall behind a long admission. A row activating here
        # SITS OUT this step's decode (it "settles"): its entry in the
        # returned dict is its activation token, never silently
        # overwritten by a same-step decode token.
        activated = self._pump_prefill() if self._pending else {}
        ot1 = obs.clock.monotonic() if obs is not None else 0.0
        self._settling = {
            self._slot_of[r] for r in activated if r in self._slot_of
        }
        try:
            if self.draft_params is not None:
                out = self._step_speculative()
                out = {**{r: [t] for r, t in activated.items()}, **out}
            else:
                out = {**activated, **self._step_plain()}
        finally:
            self._settling = set()
        if obs is not None:
            ot2 = obs.clock.monotonic()
            obs.step(
                id(self),
                live=len(self._slot_of),
                slots=self.slots,
                pending=len(self._pending),
                activated=len(activated),
                evicted=self._evictions_total - ev0,
                emitted_tokens=sum(
                    len(v) if isinstance(v, list) else 1
                    for v in out.values()
                ),
                prefill_s=ot1 - ot0,
                decode_s=ot2 - ot1,
            )
        if self._recorder is not None:
            self._recorder.record(
                "serving_step",
                duration_ms=round((time.perf_counter() - t0) * 1000, 3),
                emitted_tokens=sum(
                    len(v) if isinstance(v, list) else 1
                    for v in out.values()
                ),
                live_requests=len(self._slot_of),
                pending_prefills=len(self._pending),
                used_blocks=self.used_blocks,
                pool_blocks=self.pool_blocks,
            )
        return out

    def _step_plain(self) -> Dict[int, int]:
        if not self._slot_of:
            return {}
        # back each write position with a pool block; a slot that
        # can't get one is finished (freeing ITS blocks may unblock
        # later slots in the same sweep)
        rid_of_slot = {
            s: r for r, s in self._slot_of.items()
            if s not in self._settling
        }
        for s in sorted(rid_of_slot):
            try:
                self._ensure_blocks(s, int(self._host_len[s]) + 1)
            except RuntimeError:
                self._finish(rid_of_slot[s], "pool_exhausted")
        if not self._slot_of:
            return {}
        live_slots = (
            set(self._slot_of.values()) - self._settling
        )
        if not live_slots:
            return {}
        live = sorted(live_slots)
        bs = self.block_size
        wblk = np.full((self.slots,), _JUNK, np.int32)
        woff = np.zeros((self.slots,), np.int32)
        for s in live:
            w = int(self._host_len[s])
            wblk[s] = self._table[s, w // bs]
            woff[s] = w % bs
        n_b = self._gather_bucket(
            max(self._blocks_for(int(self._host_len[s]) + 1)
                for s in live)
        )
        table_b = jnp.asarray(self._table[:, :n_b])
        active = jnp.asarray(
            [s in live_slots for s in range(self.slots)]
        )
        # key advances every step regardless of path so a request's
        # draws don't depend on its neighbors' admission order
        self._key, sub = jax.random.split(self._key)
        greedy = not (self._row_temp[live] > 0.0).any()
        fn = self._step_fn(n_b, greedy)
        self._pool_k, self._pool_v, self._lengths, self._last = fn(
            self.params, self._pool_k, self._pool_v, table_b,
            self._lengths, self._last, active, sub,
            jnp.asarray(self._row_temp),
            jnp.asarray(self._row_topk),
            jnp.asarray(self._row_topp),
            jnp.asarray(wblk), jnp.asarray(woff),
        )
        self._host_len[live] += 1
        out = {}
        toks = np.asarray(self._last)
        for rid, slot in list(self._slot_of.items()):
            if slot in self._settling:
                continue
            tok = int(toks[slot])
            self._streams[rid].append(tok)
            out[rid] = tok
            if self._observatory is not None:
                ouid = self._obs_uid.get(rid)
                if ouid is not None:
                    self._observatory.tokens_emitted(ouid, 1)
            # a row at max_len-1 can't take another write; a stop
            # token ends the stream without the caller polling
            if int(self._host_len[slot]) >= self.max_len - 1:
                self._finish(rid, "max_len")
            elif tok in self._stop[rid]:
                self._finish(rid, "stop_token")
        return out

    def _step_speculative(self) -> Dict[int, List[int]]:
        if not self._slot_of:
            return {}
        g = self.gamma
        # a row within gamma of max_len can't take a full verify
        # chunk: catch the draft cache up and take a plain step (the
        # row auto-finishes at max_len within a few of these)
        if any(
            int(self._host_len[s]) + g >= self.max_len
            for s in self._slot_of.values()
            if s not in self._settling
        ):
            self._draft_k, self._draft_v = self._draft_catchup_fn(
                self.draft_params, self._draft_k, self._draft_v,
                self._lengths, self._last,
            )
            return {
                rid: [tok] for rid, tok in self._step_plain().items()
            }
        # back the whole verify chunk (positions len..len+gamma) with
        # pool blocks, per live slot
        rid_of_slot = {
            s: r for r, s in self._slot_of.items()
            if s not in self._settling
        }
        for s in sorted(rid_of_slot):
            try:
                self._ensure_blocks(s, int(self._host_len[s]) + g + 1)
            except RuntimeError:
                self._finish(rid_of_slot[s], "pool_exhausted")
        if not self._slot_of:
            return {}
        live_slots = (
            set(self._slot_of.values()) - self._settling
        )
        if not live_slots:
            return {}
        live = sorted(live_slots)
        bs = self.block_size
        wblk = np.full((self.slots, g + 1), _JUNK, np.int32)
        woff = np.zeros((self.slots, g + 1), np.int32)
        for s in live:
            for i in range(g + 1):
                w = int(self._host_len[s]) + i
                wblk[s, i] = self._table[s, w // bs]
                woff[s, i] = w % bs
        n_b = self._gather_bucket(
            max(self._blocks_for(int(self._host_len[s]) + g + 1)
                for s in live)
        )
        table_b = jnp.asarray(self._table[:, :n_b])
        active = jnp.asarray(
            [s in live_slots for s in range(self.slots)]
        )
        self._key, sub = jax.random.split(self._key)
        # one jit wrapper; jax retraces per table_b gather width
        (
            self._pool_k, self._pool_v, self._draft_k, self._draft_v,
            self._lengths, self._last, committed, n_emit,
        ) = self._spec_step_fn(
            self.params, self._pool_k, self._pool_v,
            self._draft_k, self._draft_v, table_b, self._lengths,
            self._last, active, sub, jnp.asarray(self._row_temp),
            jnp.asarray(wblk), jnp.asarray(woff), self.draft_params,
        )
        committed = np.asarray(committed)
        n_emit = np.asarray(n_emit)
        self.spec_rounds_total += 1
        out: Dict[int, List[int]] = {}
        for rid, slot in list(self._slot_of.items()):
            if slot in self._settling:
                continue
            # per-row speculative economics: gamma proposed, the
            # committed prefix (n_emit - 1) survived verification
            self.spec_drafted_total += g
            self.spec_accepted_total += int(n_emit[slot]) - 1
            toks = committed[slot][: int(n_emit[slot])].tolist()
            self._host_len[slot] += int(n_emit[slot])
            # stop-token truncation: the stream ends AT the first
            # stop; later tokens from the same round are dropped
            # (they're the oracle's continuation past the stop)
            cut = next(
                (i for i, t in enumerate(toks)
                 if t in self._stop[rid]), None,
            )
            if cut is not None:
                toks = toks[: cut + 1]
            self._streams[rid].extend(toks)
            out[rid] = toks
            if cut is not None:
                self._finish(rid, "stop_token")
            elif int(self._host_len[slot]) >= self.max_len - 1:
                self._finish(rid, "max_len")
        return out

    def _finish(self, rid: int, reason: str = "released") -> None:
        slot = self._slot_of.pop(rid)
        self._finished.add(rid)
        self.finish_reason[rid] = reason
        if reason == "pool_exhausted":
            self._evictions_total += 1
        obs = self._observatory
        ouid = self._obs_uid.pop(rid, None)
        if obs is not None and ouid is not None:
            # block count BEFORE _drop_row zeroes the table row
            blocks = int(np.count_nonzero(self._table[slot]))
            published = False
            if reason == "prefilled" and self.shared_pool is not None:
                # disaggregated handoff: keep the partition open (the
                # handoff phase runs until a decode engine adopts the
                # record off the shared pool's request registry)
                rec = obs.handoff_begin(ouid)
                if rec is not None and rec.chain_digests:
                    self.shared_pool.publish_request(
                        rec.chain_digests, rec
                    )
                    published = True
            if not published:
                obs.finish(
                    ouid, reason,
                    kv_blocks=blocks,
                    kv_bytes=blocks * self.kv_block_bytes,
                )
        self._drop_row(slot)
        self._free.append(slot)
        self._free.sort()
        self._seq_tokens.pop(rid, None)

    def stream(self, rid: int) -> List[int]:
        """Tokens generated so far (admission's first token onward);
        valid for live and finished-uncollected requests. A pending
        (still-prefilling) enqueue() rid has no tokens yet: []."""
        if rid in self._pending_state:
            return []
        return list(self._streams[rid])

    def release(self, rid: int) -> List[int]:
        """Finish a live request (freeing its slot and blocks) or
        collect an auto-finished one; returns its generated tokens.
        Releasing a PENDING enqueue() rid cancels its prefill
        mid-flight (blocks freed, slot reusable) and returns []."""
        if rid in self._pending_state:
            st = self._pending_state.pop(rid)
            self._pending.remove(rid)
            ouid = self._obs_uid.pop(rid, None)
            if self._observatory is not None and ouid is not None:
                self._observatory.finish(ouid, "cancelled")
            self._drop_row(st["slot"])
            self._free.append(st["slot"])
            self._free.sort()
            self._stop.pop(rid, None)
            self._seq_tokens.pop(rid, None)
            return []
        if rid in self._slot_of:
            self._finish(rid)
        self._finished.discard(rid)
        self._stop.pop(rid, None)
        self.finish_reason.pop(rid, None)
        return self._streams.pop(rid)

    # -- mid-stream handoff (live migration) -------------------------
    #
    # The cross-role request registry hands a request from prefill to
    # decode at a phase boundary. Live migration needs the harder
    # version: hand an OPEN stream — KV blocks, generation cursor,
    # sampling state, emitted tokens — from a draining engine to a
    # peer on the same SharedKVPool mid-decode, so the client sees one
    # uninterrupted stream instead of a reset. Blocks move by
    # refcount, never by copy: positions [0, host_len) stay the exact
    # K/V bytes the source wrote, so a greedy adopted stream is
    # bit-identical to the stream the source would have produced
    # (pinned in tests/test_serving.py).

    def publish_stream(self, rid: int) -> dict:
        """Publish a LIVE request's in-flight decode state through the
        shared pool's stream registry and release its slot here. The
        record carries the slot's block chain (one registry-owned
        refcount per block), the generation cursor, the real token
        history, the emitted stream, per-request sampling and stop
        state, and the open observatory partition. The source's rid
        finishes as ``handoff`` — its stream stays readable, nothing
        client-visible resets."""
        if self.shared_pool is None:
            raise ValueError(
                "publish_stream needs a SharedKVPool (the registry IS "
                "the transport; solo engines have no peer to adopt)"
            )
        if rid in self._pending_state:
            raise ValueError(
                f"request {rid} is still prefilling; pump step() until "
                "it activates (or release() to cancel) before handoff"
            )
        if rid not in self._slot_of:
            raise ValueError(f"request {rid} is not live")
        slot = self._slot_of[rid]
        hl = int(self._host_len[slot])
        full = np.concatenate([
            self._seq_tokens[rid],
            np.asarray(self._streams[rid], np.int32),
        ]).astype(np.int32)
        # KV positions [0, hl) back full[:hl]; the newest stream
        # token's K/V is written on its feed-back step, so it travels
        # as data (``last``), not as pool bytes
        n_blocks = self._blocks_for(hl)
        blocks = [int(self._table[slot, j]) for j in range(n_blocks)]
        for bid in blocks:
            self._alloc.share(bid)
        from .prefix_cache import chain_hashes

        obs_rec = None
        ouid = self._obs_uid.get(rid)
        if self._observatory is not None and ouid is not None:
            obs_rec = self._observatory.handoff_begin(ouid)
        record = {
            "kind": "stream",
            "blocks": blocks,
            "host_len": hl,
            "tokens": full,
            "stream": list(self._streams[rid]),
            "last": int(full[hl]) if hl < len(full) else int(full[-1]),
            "temp": float(self._row_temp[slot]),
            "topk": int(self._row_topk[slot]),
            "topp": float(self._row_topp[slot]),
            "stop": tuple(int(t) for t in self._stop.get(rid, ())),
            "digests": tuple(chain_hashes(full[:hl], self.block_size)),
            "obs": obs_rec,
        }
        self.shared_pool.publish_stream(record)
        self.stream_handoffs_out += 1
        # the partition continues at the adopter: drop our uid mapping
        # BEFORE _finish so the source side doesn't close it
        self._obs_uid.pop(rid, None)
        self._finish(rid, "handoff")
        return record

    def adopt_stream(self, record: Optional[dict] = None) -> Optional[int]:
        """Adopt a mid-stream handoff from the shared pool (oldest
        pending record, or one the caller already claimed): seat it in
        a free slot, inherit the record's block refcounts (zero bytes
        copied), restore cursor/sampling/stop/stream state, and
        continue decoding. Returns the new rid, or None when nothing
        is pending. On a seating failure (no slot, pool dry for the
        write block) the record goes BACK to the registry front and
        the admission-control ValueError raises — a failed adoption
        never strands or leaks the stream."""
        if self.shared_pool is None:
            raise ValueError("adopt_stream needs a SharedKVPool")
        claimed = record is None
        if claimed:
            record = self.shared_pool.claim_stream()
            if record is None:
                return None
        if not self._free:
            self.shared_pool.restore_stream(record)
            raise ValueError("no free slot; release() one first")
        slot = self._free.pop(0)
        hl = int(record["host_len"])
        blocks = record["blocks"]
        for j, bid in enumerate(blocks):
            # inherit the registry's refcount — no share(), no copy
            self._table[slot, j] = bid
        try:
            # the next decode write's block may be fresh (hl on a
            # block boundary); allocate it privately
            self._ensure_blocks(slot, hl + 1)
        except RuntimeError as e:
            # roll back WITHOUT _drop_row: the inherited refs belong
            # to the record, which goes back to the registry intact
            for j in range(len(blocks), self.max_blocks):
                bid = int(self._table[slot, j])
                if bid != _JUNK:
                    self._alloc.drop(bid)
            self._table[slot, :] = _JUNK
            self._free.append(slot)
            self._free.sort()
            self.shared_pool.restore_stream(record)
            raise ValueError(str(e)) from e
        rid = self._next_rid
        self._next_rid += 1
        self._slot_of[rid] = slot
        self._streams[rid] = list(record["stream"])
        self._stop[rid] = frozenset(record["stop"])
        self._row_temp[slot] = record["temp"]
        self._row_topk[slot] = record["topk"]
        self._row_topp[slot] = record["topp"]
        self._lengths = self._lengths.at[slot].set(hl)
        self._host_len[slot] = hl
        self._last = self._last.at[slot].set(int(record["last"]))
        tokens = np.asarray(record["tokens"], np.int32)
        self._seq_tokens[rid] = tokens[
            : len(tokens) - len(record["stream"])
        ].copy()
        self.stream_handoffs_in += 1
        obs_rec = record.get("obs")
        if self._observatory is not None and obs_rec is not None:
            self._obs_uid[rid] = self._observatory.adopt(
                obs_rec, engine_key=id(self)
            )
        return rid
