"""Analytic HBM-traffic / FLOPs proxy for the serving decode step.

Why this exists: the Pallas paged-attention kernel (paged_attention.py)
has been written and stream-pinned since PR 4, but defaulted OFF
because the decision evidence — an on-chip A/B — needed a reachable
TPU, and two straight bench rounds lost the chip to backend-init
timeouts. The decision does not actually need a chip: both step paths
move PREDICTABLE amounts of HBM per decode step, so a deterministic
traffic model (corroborated by XLA's own cost analysis of the two
compiled attention programs on CPU) yields the paged-vs-gather ratio
the default flip was waiting for.

The model, per decode step (KV-cache traffic; parameter reads are
identical across paths and reported separately):

- GATHER path (the engine's reference step): materialize the live
  slots' blocks as a dense [slots, S] view, attend against it,
  scatter one written position back. The pool blocks are READ once to
  build the view, the view is WRITTEN to HBM, and attention READS it
  again — 3x the view's bytes — plus the one-position write-back.
- PAGED path (paged_decode_attention): the block table rides in as
  scalar prefetch and each (slot, kv head, block) grid step streams
  its block HBM->VMEM exactly once, straight into the online-softmax
  accumulation — 1x the view's bytes — plus the same write-back.

Both paths compute over the same bucket-padded width, so FLOPs are
equal by construction and the KV-byte ratio sits at ~3. int8 KV pools
(ServingEngine kv_int8) shrink the same KV terms by the storage ratio
and are reported alongside.

THE DOCUMENTED THRESHOLD: ``ServingEngine(paged_kernel=None)`` (auto)
resolves ON when (a) the kernel would run NATIVELY — a real TPU
backend, no tensor-parallel mesh, float pool — and (b) the modeled
gather/paged KV-byte ratio at the engine's own shape is >=
``PAGED_DEFAULT_MIN_RATIO``. Under interpret mode (CPU CI) the kernel
is an emulation with no HBM to save, so auto resolves OFF there;
an explicit ``paged_kernel=True/False`` always wins. The
``serving_proxy`` bench leg prints the full model so the flip is
auditable from BENCH json alone.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

# The paged default flips ON (native backends) at this modeled
# gather/paged KV-byte ratio; the model puts the ratio at ~3 for every
# realistic shape, so 1.5 leaves a 2x safety margin for traffic the
# model can't see (prefetch inefficiency, partial-block waste).
PAGED_DEFAULT_MIN_RATIO = 1.5

# Reference operating point for the bench leg / auto default when the
# engine's own shape isn't in hand: a mid-size continuous batch at a
# serving-typical depth.
DEFAULT_SLOTS = 8
DEFAULT_SEQ_LEN = 512
DEFAULT_BLOCK_SIZE = 32


def _matmul_param_count(cfg) -> int:
    """Parameters decode re-reads per step (every matmul weight; the
    embedding gather reads one row per token and is excluded)."""
    n, g, h = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    d, f = cfg.d_model, cfg.d_ff
    per_layer = n * h * d                       # wo
    if cfg.is_gqa:
        per_layer += d * n * h + d * 2 * g * h  # wq + wkv
    else:
        per_layer += d * 3 * n * h              # wqkv
    per_layer += d * f + f * d                  # w1 + w2
    return cfg.n_layers * per_layer + d * cfg.vocab  # + lm_head


def decode_step_traffic(
    cfg,
    slots: int = DEFAULT_SLOTS,
    seq_len: int = DEFAULT_SEQ_LEN,
    block_size: int = DEFAULT_BLOCK_SIZE,
    kv_int8: bool = False,
    max_len: Optional[int] = None,
) -> Dict:
    """Modeled bytes moved + FLOPs for ONE decode step over ``slots``
    live rows at depth ``seq_len``, for both step paths. Deterministic
    and closed-form — the serving_proxy bench leg prints exactly
    this."""
    from .paged_attention import kernel_traffic
    # the ENGINE's bucketing function, not a re-derivation: the model
    # prices exactly the widths the engine compiles for
    from .serving import gather_bucket

    g, h, L = cfg.kv_heads, cfg.head_dim, cfg.n_layers
    n = cfg.n_heads
    itemsize = np.dtype(cfg.dtype).itemsize
    max_blocks = -(-(max_len or max(seq_len, 1)) // block_size)
    nb = gather_bucket(-(-seq_len // block_size), max_blocks)
    S = nb * block_size                     # bucket-padded view width
    # K+V bytes per cached position, as stored in the pool
    if kv_int8:
        per_pos = 2 * g * (h * 1 + 4)       # int8 entries + f32 scale
    else:
        per_pos = 2 * g * h * itemsize
    # one full sweep of the live view, taken from the KERNEL's own grid
    # accounting (per layer; scaled by the pool's storage ratio for
    # int8) so the paged byte model is the kernel's shape by
    # construction, not a re-derivation
    kt = kernel_traffic(slots, nb, block_size, g, h, itemsize)
    view_bytes = (
        L * kt["kv_bytes_read"] * per_pos // (2 * g * h * itemsize)
    )
    writeback = L * slots * per_pos         # the one written position
    # FLOPs are path-independent: q·K and p·V over the padded width
    # (2 FLOPs per MAC), plus every matmul weight once per slot-token.
    attn_flops = L * slots * 2 * (2 * n * h * S)
    param_flops = 2 * _matmul_param_count(cfg) * slots
    param_bytes = _matmul_param_count(cfg) * itemsize
    gather_kv = 3 * view_bytes + writeback
    paged_kv = view_bytes + writeback
    return {
        "slots": slots,
        "seq_len": seq_len,
        "block_size": block_size,
        "gather_blocks": nb,
        "kv_int8": kv_int8,
        "gather": {
            "kv_bytes": gather_kv,
            "total_bytes": gather_kv + param_bytes,
            "flops": attn_flops + param_flops,
        },
        "paged": {
            "kv_bytes": paged_kv,
            "total_bytes": paged_kv + param_bytes,
            "flops": attn_flops + param_flops,
        },
        "param_bytes": param_bytes,
        "kv_bytes_ratio": round(gather_kv / paged_kv, 3),
        "total_bytes_ratio": round(
            (gather_kv + param_bytes) / (paged_kv + param_bytes), 3
        ),
        "ops_ratio": 1.0,  # same masked compute on both paths
    }


def xla_measured_costs(
    slots: int = 4, kv_heads: int = 2, q_per_kv: int = 2,
    head_dim: int = 8, block_size: int = 4, n_blocks: int = 17,
    table_blocks: int = 4,
) -> Dict:
    """Corroboration by instrumentation: XLA's compiled cost analysis
    ('bytes accessed' / 'flops') of the two ATTENTION programs at a
    small shape — the gather-based reference path and the Pallas
    kernel in interpret mode. Runs on CPU, no chip needed. Read the
    interpret-mode numbers for what they are: the cost of the
    EMULATION's lowering, not of the TPU kernel — the reference-path
    numbers are the real gather-path cost; the analytic model above is
    the decision input."""
    import jax
    import jax.numpy as jnp

    from .paged_attention import (
        paged_decode_attention,
        paged_decode_attention_reference,
    )

    g, r, h, bs, nb = kv_heads, q_per_kv, head_dim, block_size, table_blocks
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(slots, g * r, h)), jnp.float32)
    pk = jnp.asarray(
        rng.normal(size=(n_blocks, bs, g, h)), jnp.float32
    )
    pv = jnp.asarray(
        rng.normal(size=(n_blocks, bs, g, h)), jnp.float32
    )
    table = jnp.asarray(
        rng.integers(1, n_blocks, size=(slots, nb)), jnp.int32
    )
    lengths = jnp.asarray(
        rng.integers(1, nb * bs + 1, size=(slots,)), jnp.int32
    )

    def costs(fn):
        compiled = jax.jit(fn).lower(q, pk, pv, table, lengths).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return {}
        return {
            "bytes_accessed": ca.get("bytes accessed"),
            "flops": ca.get("flops"),
        }

    return {
        "shape": {
            "slots": slots, "kv_heads": g, "q_per_kv": r,
            "head_dim": h, "block_size": bs, "table_blocks": nb,
        },
        "gather_reference": costs(
            lambda *a: paged_decode_attention_reference(*a, kv_heads=g)
        ),
        "paged_interpret": costs(
            lambda *a: paged_decode_attention(
                *a, kv_heads=g, interpret=True
            )
        ),
    }


def recommend_paged_kernel(
    cfg=None,
    interpret: bool = False,
    kv_int8: bool = False,
    mesh=None,
    slots: int = DEFAULT_SLOTS,
    seq_len: int = DEFAULT_SEQ_LEN,
    block_size: int = DEFAULT_BLOCK_SIZE,
) -> bool:
    """Resolve ServingEngine's ``paged_kernel=None`` auto default per
    the documented threshold (module docstring): native backend only,
    modeled KV-byte ratio >= PAGED_DEFAULT_MIN_RATIO."""
    if interpret or kv_int8 or mesh is not None:
        # the kernel would be emulated (CPU) or can't run this layout:
        # no HBM win to collect, keep the gather path
        return False
    if cfg is None:
        return True  # the ratio is shape-independent at ~3x
    est = decode_step_traffic(
        cfg, slots=slots, seq_len=seq_len, block_size=block_size
    )
    return est["kv_bytes_ratio"] >= PAGED_DEFAULT_MIN_RATIO


def serving_proxy_report(cfg=None) -> Dict:
    """The full ``serving_proxy`` bench-leg payload: modeled traffic at
    the reference operating point (float + int8 pools), the XLA
    cost-analysis corroboration, the threshold and the resulting
    default. Deterministic; runs anywhere."""
    if cfg is None:
        from .transformer import ModelConfig

        # the bench flagship's shape (bench.py tpu_measure_once)
        cfg = ModelConfig(
            vocab=32768, d_model=2048, n_heads=16, n_layers=8,
            d_ff=8192, max_seq=1024,
        )
    model = decode_step_traffic(cfg)
    model_int8 = decode_step_traffic(cfg, kv_int8=True)
    try:
        measured = xla_measured_costs()
    except Exception as e:  # noqa: BLE001 - corroboration, not decision
        measured = {"error": f"{type(e).__name__}: {e}"}
    return {
        "operating_point": {
            "model": {
                "d_model": cfg.d_model, "n_layers": cfg.n_layers,
                "n_heads": cfg.n_heads, "kv_heads": cfg.kv_heads,
                "head_dim": cfg.head_dim, "dtype": str(
                    np.dtype(cfg.dtype)
                ),
            },
            "slots": model["slots"],
            "seq_len": model["seq_len"],
            "block_size": model["block_size"],
        },
        "per_decode_step": {
            "gather": model["gather"],
            "paged": model["paged"],
            "param_bytes": model["param_bytes"],
        },
        "hbm_kv_bytes_ratio_gather_over_paged": model["kv_bytes_ratio"],
        "hbm_total_bytes_ratio": model["total_bytes_ratio"],
        "ops_ratio": model["ops_ratio"],
        "int8_kv": {
            "paged_kv_bytes": model_int8["paged"]["kv_bytes"],
            "kv_bytes_reduction_vs_float": round(
                model["paged"]["kv_bytes"]
                / model_int8["paged"]["kv_bytes"], 3
            ),
        },
        "threshold": PAGED_DEFAULT_MIN_RATIO,
        "paged_kernel_default": {
            "tpu_native": recommend_paged_kernel(cfg, interpret=False),
            "cpu_interpret": recommend_paged_kernel(cfg, interpret=True),
            "rule": (
                "paged_kernel=None resolves ON iff the kernel runs "
                "natively (TPU backend, float pool, no mesh) AND the "
                "modeled gather/paged KV-byte ratio >= threshold"
            ),
        },
        "xla_cost_analysis": measured,
    }
