"""Token data pipeline: memory-mapped datasets with deterministic,
dp-sharded batching.

The training story's input side (the reference has no workload code at
all — SURVEY.md §2; the runner previously trained on random tokens).
TPU-first design notes:

- The file is a flat token stream behind a tiny header, read through
  ``np.memmap`` — the kernel's page cache IS the prefetcher for
  sequential training reads; no native reader thread beats mmap for
  this access pattern on a TPU-VM host.
- Batching is a pure function of (step, dp_rank, dp_size): every host
  of a slice computes ITS shard without coordination (the same
  derive-from-facts principle as slice_env), restarts/resumes are
  exactly reproducible, and no host ever materializes another host's
  shard.
- Batches are yielded as numpy; the caller's jit feeds them to the
  device — keeping host->device transfer the only copy.

File format (little-endian): magic ``ETPU``, uint32 version (1),
uint32 token dtype itemsize (2 = uint16, 4 = uint32), uint64 token
count, then the raw tokens.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator

import numpy as np

MAGIC = b"ETPU"
VERSION = 1
_HEADER = struct.Struct("<4sIIQ")


def write_token_file(path: str, tokens: np.ndarray) -> None:
    """Write a token array (any int dtype; stored uint16 when it fits)."""
    tokens = np.asarray(tokens)
    if tokens.size and tokens.min() < 0:
        raise ValueError("tokens must be non-negative")
    dtype = np.uint16 if (not tokens.size or tokens.max() < 2 ** 16) \
        else np.uint32
    tokens = tokens.astype(dtype)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_HEADER.pack(
            MAGIC, VERSION, dtype().itemsize, tokens.size
        ))
        tokens.tofile(f)
    os.replace(tmp, path)


def encode_bytes(text: bytes) -> np.ndarray:
    """Hermetic byte-level encoding (vocab 256) — no tokenizer download
    needed; real deployments drop in their own tokenized .bin."""
    return np.frombuffer(text, dtype=np.uint8).astype(np.uint16)


class TokenDataset:
    """Memory-mapped token stream with deterministic sharded batching."""

    def __init__(self, path: str) -> None:
        with open(path, "rb") as f:
            raw = f.read(_HEADER.size)
        magic, version, itemsize, count = _HEADER.unpack(raw)
        if magic != MAGIC:
            raise ValueError(f"{path}: not an ETPU token file")
        if version != VERSION:
            raise ValueError(f"{path}: unsupported version {version}")
        dtype = {2: np.uint16, 4: np.uint32}.get(itemsize)
        if dtype is None:
            raise ValueError(f"{path}: unsupported token itemsize {itemsize}")
        self.n_tokens = count
        self._tokens = np.memmap(
            path, dtype=dtype, mode="r", offset=_HEADER.size, shape=(count,)
        )

    def max_token(self, sample: "int | None" = None) -> int:
        """Max token id (vocab sanity checks). ``sample`` bounds the scan
        to a prefix for quick checks; None (default) scans the whole file
        in chunks — one out-of-range token anywhere corrupts training, so
        callers gating on the vocab should pay the full sequential read."""
        if self.n_tokens == 0:
            return 0
        end = self.n_tokens if sample is None else min(sample, self.n_tokens)
        out = 0
        chunk = 1 << 24
        for start in range(0, end, chunk):
            out = max(out, int(self._tokens[start: min(start + chunk, end)]
                               .max()))
        return out

    def sequences_per_epoch(self, seq: int) -> int:
        return max(1, (self.n_tokens - 1) // seq)

    def batch(
        self,
        step: int,
        batch: int,
        seq: int,
        dp_rank: int = 0,
        dp_size: int = 1,
        region: "tuple[int, int] | None" = None,
    ) -> np.ndarray:
        """[batch, seq+1] int32 tokens for this host's shard of ``step``.

        ``batch`` is the LOCAL batch; sample k of step t globally is
        ``t*dp_size*batch + dp_rank*batch + k``, striding the stream in
        seq-token windows and wrapping at epoch end (the +1 column is
        the shift-by-one target, overlapping the next window by one
        token like every LM data pipeline).

        ``region`` = (first_seq, n_seqs) restricts sampling to a
        contiguous range of sequence indices — how train/eval splits
        share one file without overlap (see split_regions)."""
        if self.n_tokens < seq + 1:
            raise ValueError(
                f"dataset has {self.n_tokens} tokens; need >= {seq + 1}"
            )
        first, n_seqs = region or (0, self.sequences_per_epoch(seq))
        assert n_seqs >= 1, region
        out = np.empty((batch, seq + 1), np.int32)
        base = step * dp_size * batch + dp_rank * batch
        for k in range(batch):
            idx = first + (base + k) % n_seqs
            start = idx * seq
            out[k] = self._tokens[start: start + seq + 1]
        return out

    def split_regions(
        self, seq: int, eval_frac: float
    ) -> "tuple[tuple[int, int], tuple[int, int]]":
        """((train_first, train_n), (eval_first, eval_n)): the LAST
        max(1, floor(per_epoch * eval_frac)) of the file's sequence
        windows (capped so train keeps at least one) is held out —
        train wrapping never touches it, so eval loss measures
        generalization, not memorization. At least one window is always
        held out, even at eval_frac == 0. A file with a single window
        cannot be split: raising beats silently evaluating on the
        training data."""
        per_epoch = self.sequences_per_epoch(seq)
        if per_epoch < 2:
            raise ValueError(
                f"dataset has only {per_epoch} sequence window(s) of "
                f"seq={seq}; a held-out split needs at least 2 "
                "(eval on the training window would measure "
                "memorization)"
            )
        n_eval = min(
            max(1, int(per_epoch * eval_frac)), per_epoch - 1
        )
        return (0, per_epoch - n_eval), (per_epoch - n_eval, n_eval)

    def batches(
        self, batch: int, seq: int, dp_rank: int = 0, dp_size: int = 1,
        start_step: int = 0,
    ) -> Iterator[np.ndarray]:
        step = start_step
        while True:
            yield self.batch(step, batch, seq, dp_rank, dp_size)
            step += 1


def encode_file(input_path: str, output_path: str) -> int:
    """Byte-encode a text/binary file into an ETPU token file; returns
    the token count."""
    with open(input_path, "rb") as f:
        tokens = encode_bytes(f.read())
    write_token_file(output_path, tokens)
    return int(tokens.size)


if __name__ == "__main__":  # tiny CLI: encode a file
    import argparse

    p = argparse.ArgumentParser(
        description="byte-encode a file into an ETPU token dataset"
    )
    p.add_argument("input")
    p.add_argument("output")
    args = p.parse_args()
    n = encode_file(args.input, args.output)
    print(f"wrote {n} tokens to {args.output}")
