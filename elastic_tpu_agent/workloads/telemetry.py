"""In-pod workload flight recorder: the other half of the agent's
allocation tracing.

The agent can prove it *gave* a pod its slice (tracing.py, /debug/traces)
but not what the workload *achieved* on it — and a broker that co-locates
jobs (fractional core/HBM shares) needs exactly that feedback to validate
its sharing decisions. This module captures per-step facts from inside
the pod:

- wall time per step (dispatch-to-dispatch; JAX dispatch is async, so in
  a saturated loop this converges on true device step time),
- tokens/sec when the caller supplies a token count,
- jit recompile count (cache-size delta of the watched jitted fns — a
  recompile mid-run is the classic silent throughput killer),
- JAX device memory stats where the backend reports them (bytes_in_use
  against the pod's cooperative HBM quota).

Records are JSONL, tagged with the **propagated trace id**: the agent
writes ``ELASTIC_TPU_TRACE_ID`` into the alloc-spec env, the OCI
hook/NRI adjustment copies it into ``/run/elastic-tpu/env``, the runner
applies that file to its environment, and this recorder reads it — so
one id links `kubectl describe pod`, the agent's /debug/traces dump,
and these step records.

Output is bounded: the JSONL file rotates to ``<path>.1`` past
``max_bytes`` (≤ 2x max_bytes on disk, ever) and the in-memory ring
keeps the newest ``max_memory_records`` for end-of-run summaries.
Everything is best-effort — a broken disk must not fail a train step.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

ENV_TRACE_ID = "ELASTIC_TPU_TRACE_ID"
ENV_RECORDER_PATH = "ELASTIC_TPU_FLIGHT_RECORDER"

DEFAULT_MAX_BYTES = 4 * 1024 * 1024
DEFAULT_MEMORY_RECORDS = 512


def device_memory_stats() -> Optional[dict]:
    """bytes_in_use/peak/limit of the first local device, when the
    backend exposes them (TPU does; CPU returns None). Never raises."""
    try:
        import jax

        devs = jax.local_devices()
        if not devs:
            return None
        stats = devs[0].memory_stats()
        if not stats:
            return None
        keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
        return {k: int(stats[k]) for k in keep if k in stats}
    except Exception:  # noqa: BLE001 - telemetry, never load-bearing
        return None


class StepTimer:
    """Context manager timing one step; created by FlightRecorder.step."""

    def __init__(self, recorder: "FlightRecorder", step: int,
                 tokens: Optional[int], attrs: Dict) -> None:
        self._recorder = recorder
        self.step = step
        self.tokens = tokens
        self.attrs = dict(attrs)
        self._t0 = 0.0

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "StepTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dt = time.perf_counter() - self._t0
        fields = {"step": self.step, "duration_ms": round(dt * 1000, 3)}
        if self.tokens is not None and dt > 0:
            fields["tokens"] = self.tokens
            fields["tokens_per_s"] = round(self.tokens / dt, 3)
        recompiles = self._recorder._recompile_delta()
        if recompiles is not None:
            fields["jit_recompiles"] = recompiles
        mem = device_memory_stats()
        if mem:
            fields["device_memory"] = mem
        if exc is not None:
            fields["error"] = f"{type(exc).__name__}: {exc}"
        fields.update(self.attrs)
        self._recorder.record("step", **fields)
        # never suppress the exception


class FlightRecorder:
    """Bounded JSONL step recorder, correlated to the agent's trace id.

    ``path`` None/"" -> in-memory only (the ring still feeds summary()).
    ``jit_fns`` are watched for cache growth: each recorded step carries
    the number of NEW compilations since the previous record.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        trace_id: Optional[str] = None,
        max_bytes: int = DEFAULT_MAX_BYTES,
        max_memory_records: int = DEFAULT_MEMORY_RECORDS,
        jit_fns: tuple = (),
    ) -> None:
        self.trace_id = (
            trace_id if trace_id is not None
            else os.environ.get(ENV_TRACE_ID, "")
        )
        self.path = (
            path if path is not None
            else os.environ.get(ENV_RECORDER_PATH, "")
        )
        self.max_bytes = max_bytes
        self.records: "deque[dict]" = deque(maxlen=max_memory_records)
        self._jit_fns = [f for f in jit_fns if hasattr(f, "_cache_size")]
        self._last_cache_size: Optional[int] = None
        self._lock = threading.Lock()
        self._file = None
        self._file_broken = False
        self.written = 0  # lines that reached the file
        if self.path:
            self._open_file()

    # -- file plumbing --------------------------------------------------------

    def _open_file(self) -> None:
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._file = open(self.path, "a")
        except OSError as e:
            self._file = None
            self._file_broken = True
            logger.warning(
                "flight recorder: cannot open %s (%s); recording "
                "in-memory only", self.path, e,
            )

    def _rotate_locked(self) -> None:
        try:
            self._file.close()
        except OSError:
            pass
        mode = "w"
        try:
            os.replace(self.path, self.path + ".1")
        except OSError as e:
            # Rotation failed (e.g. <path>.1 is a directory): reopen
            # APPEND — truncating now would destroy the newest records
            # the recorder exists to preserve. The size bound is lost
            # until rotation succeeds; data loss would be worse.
            mode = "a"
            if not self._file_broken:
                logger.warning(
                    "flight recorder: rotating %s failed (%s); "
                    "continuing unrotated", self.path, e,
                )
        try:
            self._file = open(self.path, mode)
        except OSError:
            self._file = None
            self._file_broken = True

    # -- recording ------------------------------------------------------------

    def record(self, kind: str, **fields) -> dict:
        rec = {"ts": round(time.time(), 3), "kind": kind}
        if self.trace_id:
            rec["trace_id"] = self.trace_id
        rec.update(fields)
        with self._lock:
            self.records.append(rec)
            if self._file is not None:
                try:
                    self._file.write(json.dumps(rec) + "\n")
                    self._file.flush()
                    self.written += 1
                    if self._file.tell() > self.max_bytes:
                        self._rotate_locked()
                except (OSError, ValueError):
                    # ValueError: write on a closed file after close()
                    if not self._file_broken:
                        self._file_broken = True
                        logger.warning(
                            "flight recorder: write to %s failed; "
                            "continuing in-memory only", self.path,
                        )
                    self._file = None
        return rec

    def step(self, step: int, tokens: Optional[int] = None,
             **attrs) -> StepTimer:
        """``with recorder.step(i, tokens=n): train_step(...)``"""
        return StepTimer(self, step, tokens, attrs)

    def _recompile_delta(self) -> Optional[int]:
        if not self._jit_fns:
            return None
        try:
            size = sum(int(f._cache_size()) for f in self._jit_fns)
        except Exception:  # noqa: BLE001 - private API, may shift
            return None
        prev, self._last_cache_size = self._last_cache_size, size
        return size - prev if prev is not None else size

    # -- reading --------------------------------------------------------------

    def summary(self) -> dict:
        with self._lock:
            steps = [r for r in self.records if r.get("kind") == "step"]
            n = len(self.records)
        out = {
            "trace_id": self.trace_id,
            "path": self.path or None,
            "records": n,
            "steps": len(steps),
        }
        if steps:
            durs = [r["duration_ms"] for r in steps if "duration_ms" in r]
            if durs:
                out["mean_step_ms"] = round(sum(durs) / len(durs), 3)
            out["jit_recompiles"] = sum(
                r.get("jit_recompiles", 0) for r in steps
            )
            rates = [r["tokens_per_s"] for r in steps if "tokens_per_s" in r]
            if rates:
                out["mean_tokens_per_s"] = round(
                    sum(rates) / len(rates), 3
                )
        # serving-engine admissions (ServingEngine(recorder=)): surface
        # the prefix-cache economics per run — what fraction of
        # admissions reused cached blocks, and how many prompt tokens
        # never re-prefilled because of it
        with self._lock:
            admits = [
                r for r in self.records
                if r.get("kind") == "serving_admit"
            ]
        if admits:
            out["serving_admits"] = len(admits)
            flagged = [r for r in admits if "prefix_cache_hit" in r]
            if flagged:
                hits = sum(
                    1 for r in flagged if r["prefix_cache_hit"]
                )
                out["prefix_cache_hit_rate"] = round(
                    hits / len(flagged), 3
                )
                out["prefix_cache_tokens_saved"] = sum(
                    r.get("cached_tokens", 0) for r in flagged
                )
        # request-level SLO rollup (RequestObservatory's request_finish
        # records): TTFT/TPOT p50/p99 per SLO class, so a recorder file
        # alone can answer "which class missed and by how much"
        with self._lock:
            finishes = [
                r for r in self.records
                if r.get("kind") == "request_finish"
            ]
        if finishes:
            def q(vals, frac):
                vs = sorted(vals)
                idx = min(len(vs) - 1, int(round(frac * (len(vs) - 1))))
                return round(vs[idx], 3)

            classes = {}
            for r in finishes:
                classes.setdefault(r.get("slo", "batch"), []).append(r)
            rollup = {}
            for slo, recs in sorted(classes.items()):
                entry = {"finished": len(recs)}
                ttfts = [
                    r["ttft_ms"] for r in recs
                    if r.get("ttft_ms") is not None
                ]
                tpots = [
                    r["tpot_ms"] for r in recs
                    if r.get("tpot_ms") is not None
                ]
                if ttfts:
                    entry["ttft_p50_ms"] = q(ttfts, 0.5)
                    entry["ttft_p99_ms"] = q(ttfts, 0.99)
                if tpots:
                    entry["tpot_p50_ms"] = q(tpots, 0.5)
                    entry["tpot_p99_ms"] = q(tpots, 0.99)
                rollup[slo] = entry
            out["request_finishes"] = len(finishes)
            out["request_slo"] = rollup
        return out

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                with contextlib.suppress(OSError):
                    self._file.close()
                self._file = None


def load_jsonl(path: str) -> List[dict]:
    """Read back a recorder file (rotated generation first, so records
    come out oldest-to-newest); tolerates a torn final line."""
    out: List[dict] = []
    for p in (path + ".1", path):
        try:
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            continue
    return out


def write_flight_summary(
    alloc_spec_dir: str,
    alloc_hash: str,
    tokens_per_s: float,
    steps: int = 0,
    mean_step_ms: Optional[float] = None,
    ttft_p50_s: Optional[float] = None,
    ts: float = None,
) -> bool:
    """Publish a flight-recorder summary to the node agent.

    The flight recorder's JSONL lives inside the pod; this sidecar is
    the agent-visible digest — ``<alloc dir>/flight/<alloc hash>.json``
    with the latest achieved tokens/s — which the sampler exports as
    ``elastic_tpu_workload_tokens_per_second{pod}`` (bounded, removed
    with the pod's bindings) and the goodput runbook reads next to the
    ledger's productive intervals. Same atomic fixed-temp-name contract
    as :func:`write_usage_report`; never raises.
    """
    from ..common import FlightSummarySubdir

    flight_dir = os.path.join(alloc_spec_dir, FlightSummarySubdir)
    path = os.path.join(flight_dir, f"{alloc_hash}.json")
    tmp = f"{path}.tmp"
    try:
        os.makedirs(flight_dir, exist_ok=True)
        payload = {
            "ts": time.time() if ts is None else ts,
            "tokens_per_s": float(tokens_per_s),
            "steps": int(steps),
        }
        if mean_step_ms is not None:
            payload["mean_step_ms"] = float(mean_step_ms)
        if ttft_p50_s is not None:
            # serving pods also publish their median TTFT; the sampler
            # exports it as elastic_tpu_workload_ttft_seconds{pod}
            # under the same staleness rule as tokens/s
            payload["ttft_p50_s"] = float(ttft_p50_s)
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return True
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def write_usage_report(
    alloc_spec_dir: str,
    alloc_hash: str,
    duty_cycle_percent: float,
    hbm_used_bytes: int = 0,
    ts: float = None,
) -> bool:
    """Publish this workload's measured utilization to the node agent.

    The cooperative half of the repartition contract (repartition.py):
    TPUs expose no per-process duty counters, so the agent's sampler can
    only split chip duty across co-tenants by grant share — useless for
    telling a busy pod from its idle neighbor. A pod that opted into
    live re-partitioning writes {"ts", "duty_cycle_percent",
    "hbm_used_bytes"} to ``<alloc dir>/usage/<alloc hash>.json`` (the
    hash is the pod's ``TPU`` env; the alloc dir is the same
    hostPath-shared surface its env file arrived on), and the sampler
    attributes that pod's usage from the report instead of assuming it.

    Atomic (tmp + rename), never raises — a full disk must not take the
    training loop down. Returns True when the report landed.
    """
    from ..common import UsageReportSubdir

    usage_dir = os.path.join(alloc_spec_dir, UsageReportSubdir)
    path = os.path.join(usage_dir, f"{alloc_hash}.json")
    # FIXED temp name (one writer per hash — the pod that owns the
    # allocation): a crash between write and rename leaves one file the
    # NEXT write reclaims, never an unbounded pid-suffixed pile.
    tmp = f"{path}.tmp"
    try:
        os.makedirs(usage_dir, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({
                "ts": time.time() if ts is None else ts,
                "duty_cycle_percent": float(duty_cycle_percent),
                "hbm_used_bytes": int(hbm_used_bytes),
            }, f)
        os.replace(tmp, path)
        return True
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
