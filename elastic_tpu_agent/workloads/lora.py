"""LoRA: low-rank adapters for parameter-efficient fine-tuning.

Why this matters on TPU: full fine-tuning carries f32 master weights
plus two adam moments — 12 bytes/param of HBM before activations
(docs/perf.md measured a 1B-param model OOMing a v5e chip on exactly
that). LoRA freezes the base model (bf16, no optimizer state) and
trains rank-r factors A[in,r]·B[r,out] per targeted weight: optimizer
HBM drops by ~in·out/(r·(in+out)) per target, and the train step
differentiates ONLY the adapter pytree.

Design:
- Every targeted weight is viewed 2-D as [in, out] via a static
  per-name split of its axes (wqkv [d|3nh], wo [nh|d], ...); the
  delta A@B is computed at the weight's full shape INSIDE the step —
  one [in,out] matmul, trivial next to the forward — and added to the
  frozen base, so the model code runs unmodified on "effective"
  params. No per-layer surgery in transformer.py.
- B is zero-initialized: step 0 is exactly the base model (pinned).
- ``merge_lora`` folds adapters into plain params for serving —
  generate()/quantize_params consume the merged tree directly.

Reference parity: none (the reference agent has no training code);
part of the TPU workload stack (SURVEY.md §5.7 family).
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax

from .transformer import ModelConfig, forward_with_aux

# Targeted weight name -> number of LEADING axes forming the "in" side
# of its matmul; the rest are "out". (Matches each einsum's contraction
# in transformer.py.)
_IN_AXES = {
    "wqkv": 1,   # [d, 3, n, h]
    "wq": 1,     # [d, n, h]
    "wkv": 1,    # [d, 2, g, h]
    "wo": 2,     # [n, h, d]
    "w1": 1,     # [d, f]
    "w2": 1,     # [f, d]
}

DEFAULT_TARGETS = ("wqkv", "wq", "wkv", "wo")


def _in_out(shape: Tuple[int, ...], n_in: int) -> Tuple[int, int]:
    return (
        math.prod(shape[:n_in]), math.prod(shape[n_in:])
    )


def init_lora_params(
    params: Dict,
    key: jax.Array,
    rank: int = 8,
    targets: Sequence[str] = DEFAULT_TARGETS,
) -> Dict:
    """Adapters mirroring the layer structure: layers[i][name] ->
    {"a": [in, r], "b": [r, out]}. A ~ N(0, 1/r), B = 0 (so the
    adapted model starts exactly at the base).

    MoE expert weights (nested under layer["moe"]) are NOT adapted —
    like quantize.py's router exclusion, per-expert low-rank deltas
    interact with routing in ways a frozen router can't compensate;
    MoE layers receive attention adapters only. Target names must be
    known (_IN_AXES — catches typos), but a known name may match zero
    layers: DEFAULT_TARGETS deliberately lists both the fused-MHA and
    GQA projection names so one default covers either convention."""
    unknown = set(targets) - set(_IN_AXES)
    assert not unknown, (
        f"unknown LoRA targets {sorted(unknown)}; "
        f"known: {sorted(_IN_AXES)}"
    )
    adapters = []
    matched = set()
    for layer in params["layers"]:
        entry = {}
        for name in targets:
            if name not in layer:
                continue
            matched.add(name)
            d_in, d_out = _in_out(layer[name].shape, _IN_AXES[name])
            key, sub = jax.random.split(key)
            entry[name] = {
                "a": jax.random.normal(
                    sub, (d_in, rank), jnp.float32
                ) / math.sqrt(rank),
                "b": jnp.zeros((rank, d_out), jnp.float32),
            }
        adapters.append(entry)
    assert matched, (
        f"no LoRA target in {sorted(targets)} matched any layer weight "
        f"(per-layer names: {sorted(params['layers'][0])})"
    )
    return {"layers": adapters}


def lora_param_count(lora: Dict) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(lora))


def _apply_layer(base: Dict, adapters: Dict, scale: float) -> Dict:
    out = dict(base)
    for name, ab in adapters.items():
        w = base[name]
        delta = (ab["a"] @ ab["b"]).reshape(w.shape) * scale
        out[name] = w + delta.astype(w.dtype)
    return out


def apply_lora(params: Dict, lora: Dict, scale: float = 1.0) -> Dict:
    """Effective params: base + scale * (A@B) on every adapted weight.
    Differentiable w.r.t. ``lora`` — used inside the train step; also
    the implementation of merge_lora."""
    return {
        **{k: v for k, v in params.items() if k != "layers"},
        "layers": [
            _apply_layer(layer, ad, scale)
            for layer, ad in zip(params["layers"], lora["layers"])
        ],
    }


def merge_lora(params: Dict, lora: Dict, scale: float = 1.0) -> Dict:
    """Fold adapters into a plain params tree for serving (generate,
    quantize_params, checkpointing all consume the result)."""
    return apply_lora(params, lora, scale)


def make_lora_train_step(
    cfg: ModelConfig,
    rank: int = 8,
    scale: float = 1.0,
    learning_rate: float = 1e-3,
    targets: Sequence[str] = DEFAULT_TARGETS,
):
    """(base_params, lora, opt_state, tokens) ->
    (lora, opt_state, loss), jit'd.

    The base is a non-differentiated argument: gradients and optimizer
    state exist ONLY for the adapter pytree (that asymmetry is the
    entire memory story). For multi-chip runs, pass a base already
    placed by transformer.param_shardings and dp-sharded tokens — jit
    propagates input shardings; the adapters are small enough to stay
    replicated. Returns (step, init) where init(params, key) ->
    (lora, opt_state)."""
    optimizer = optax.adamw(learning_rate)

    def loss_fn(lora, base, tokens):
        eff = apply_lora(base, lora, scale)
        logits, aux = forward_with_aux(eff, tokens[:, :-1], cfg)
        logits = logits.astype(jnp.float32)
        loss = jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(
                logits, tokens[:, 1:]
            )
        )
        return loss + cfg.moe_aux_coef * aux

    @jax.jit
    def step(base, lora, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(lora, base, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, lora)
        lora = optax.apply_updates(lora, updates)
        return lora, opt_state, loss

    def init(params: Dict, key: Optional[jax.Array] = None):
        lora = init_lora_params(
            params, key if key is not None else jax.random.key(0),
            rank=rank, targets=targets,
        )
        return lora, optimizer.init(lora)

    return step, init
