"""Autoregressive generation with a KV cache for the flagship LM.

Inference side of the workload stack (training lives in
transformer.py). TPU-first decode design:

- The KV cache is preallocated at ``max_len`` and updated in place with
  ``lax.dynamic_update_slice`` — static shapes throughout, so the whole
  decode loop is ONE ``lax.scan`` under jit (no per-token retrace, no
  dynamic shapes blocking XLA's tiling).
- The cache stores ``kv_heads`` heads, not ``n_heads`` — for GQA models
  (transformer.ModelConfig.n_kv_heads) the cache is
  n_heads/kv_heads× smaller, which is the entire point of GQA at decode
  time (HBM bandwidth per generated token is the decode bottleneck).
- Attention against the cache masks by position (keys beyond the
  current length contribute NEG_INF) instead of slicing to a dynamic
  length.
- Prefill runs the prompt in one batched pass (MXU-shaped matmuls),
  filling the cache; decode then appends one position per scan step.

Decode-vs-forward equivalence (every step's logits match the full
recompute) is pinned by tests for both MHA and GQA.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .attention import NEG_INF
from .quantize import embed_lookup, wdense
from .transformer import ModelConfig, _rmsnorm, rope


class KVCache(NamedTuple):
    """Per-layer stacked caches: k, v [n_layers, b, max_len, kv_heads, h],
    plus the current filled length (scalar int32)."""

    k: jax.Array
    v: jax.Array
    length: jax.Array

    @classmethod
    def empty(
        cls, cfg: ModelConfig, batch: int, max_len: int, dtype=None
    ) -> "KVCache":
        shape = (
            cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim
        )
        dtype = dtype or cfg.dtype
        return cls(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            length=jnp.int32(0),
        )


def _qkv(x: jax.Array, layer: Dict, cfg: ModelConfig):
    """Projections for a chunk x [b, t, d] -> q [b,t,n,h], k/v [b,t,g,h]."""
    if "wq" in layer:  # GQA
        q = jnp.einsum("btd,dnh->btnh", x, wdense(layer, "wq", cfg.dtype))
        kv = jnp.einsum(
            "btd,dcgh->bctgh", x, wdense(layer, "wkv", cfg.dtype)
        )
        return q, kv[:, 0], kv[:, 1]
    qkv = jnp.einsum(
        "btd,dcnh->bctnh", x, wdense(layer, "wqkv", cfg.dtype)
    )
    return qkv[:, 0], qkv[:, 1], qkv[:, 2]


def _cached_attention(
    q: jax.Array,           # [b, t, n, h] for the current chunk
    cache_k: jax.Array,     # [b, max_len, g, h] incl. the chunk's keys
    cache_v: jax.Array,
    q_pos: jax.Array,       # position of q[:, 0]: scalar, or [b] per row
    cfg: ModelConfig,
    key_positions: Optional[jax.Array] = None,
) -> jax.Array:
    """Causal attention of the chunk against the (masked) full cache.

    q_pos may be a scalar (every row at the same depth — plain decode)
    or a [b] vector (continuous-batching slots, each at its own depth;
    row i attends cols <= q_pos[i] + chunk offset).

    key_positions ([cache_len]) gives each cache slot's ABSOLUTE token
    position when slots aren't position-ordered — the streaming ring
    buffer, where slot j holds position key_positions[j] and unwritten
    slots carry a huge sentinel that the causal compare masks out.
    Default None = slot j holds position j.

    The cache stays at kv_heads width through the whole computation —
    q is viewed as [b, t, g, r, h] (r q-heads per kv head, contiguous
    groups matching transformer._attention's repeat convention) and the
    dots batch over g, so per-token HBM reads are the GQA-sized cache,
    never an expanded MHA-width copy."""
    b, t, n, h = q.shape
    g = cfg.kv_heads
    r = n // g
    q5 = q.reshape(b, t, g, r, h)
    scale = 1.0 / np.sqrt(cfg.head_dim)
    logits = jnp.einsum(
        "btgrh,bsgh->bgrts", q5, cache_k
    ).astype(jnp.float32) * scale
    max_len = cache_k.shape[1]
    q_pos = jnp.asarray(q_pos)
    rows = (
        q_pos[..., None, None] + jnp.arange(t, dtype=jnp.int32)[:, None]
    )  # [t, 1] or [b, t, 1]
    cols = (
        jnp.arange(max_len, dtype=jnp.int32)
        if key_positions is None else key_positions
    )
    keep = cols <= rows                   # [t, s] or [b, t, s]
    if cfg.window > 0:
        keep &= rows - cols < cfg.window
    if keep.ndim == 2:
        keep = keep[None]
    logits = jnp.where(keep[:, None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bgrts,bsgh->btgrh", probs.astype(cache_v.dtype), cache_v
    )
    return out.reshape(b, t, n, h)


def _cache_write(
    cache_layer: jax.Array,   # [b, max_len, g, h]
    kv: jax.Array,            # [b, t, g, h]
    pos: jax.Array,           # scalar, or [b] per-row offsets
) -> jax.Array:
    if pos.ndim == 0:
        return jax.lax.dynamic_update_slice(
            cache_layer, kv, (0, pos, 0, 0)
        )
    return jax.vmap(
        lambda row, val, p: jax.lax.dynamic_update_slice(
            row, val, (p, 0, 0)
        )
    )(cache_layer, kv, pos)


def _forward_chunk(
    params: Dict, tokens: jax.Array, cache: KVCache, cfg: ModelConfig,
    moe_drop_free: bool = False,
    positions: Optional[jax.Array] = None,
    ring: Optional[Tuple[jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, KVCache]:
    """Run a token chunk [b, t] at positions cache.length..+t; returns
    (logits [b, t, vocab], updated cache).

    moe_drop_free selects the MoE capacity policy explicitly (a chunk
    being one token wide does NOT imply it's a decode step — a
    single-token batched prompt is still prefill): False = the training
    capacity factor, exactly transformer.forward's semantics; True =
    cap == T, no token dropped.

    positions: per-row [b] start offsets for continuous-batching
    decode, where each slot sits at its own depth — cache writes,
    RoPE, learned-position lookup, and the attention mask all go
    row-wise, and the returned cache keeps ``length`` UNCHANGED (the
    caller owns per-row lengths). Default None = every row at
    cache.length (plain decode/prefill).

    ring: (write_index, key_positions) for streaming decode over a
    rolling-window cache (streaming.py): K/V write at slot write_index
    (= absolute_pos %% cache_len) instead of the absolute position,
    and key_positions [cache_len] maps every slot to its absolute
    position for the causal/window mask. RoPE still rotates by
    ABSOLUTE position (cache.length), so entries never re-rotate.
    Mutually exclusive with positions; the returned length is
    unchanged (the caller tracks the absolute stream position)."""
    b, t = tokens.shape
    assert not (positions is not None and ring is not None)
    # ring writes one slot per call: a multi-token chunk would need a
    # modular scatter (dynamic_update_slice clamps at the ring edge and
    # would silently clobber a live in-window slot)
    assert ring is None or t == 1, "ring mode decodes one token per call"
    pos = cache.length if positions is None else positions
    x = embed_lookup(params, tokens, cfg.dtype)
    if positions is None:
        posmat = pos + jnp.arange(t)                    # [t]
    else:
        posmat = pos[:, None] + jnp.arange(t)[None]     # [b, t]
    if cfg.pos == "learned":
        pe = params["pos_embed"].astype(cfg.dtype)[posmat]
        x = x + (pe[None] if posmat.ndim == 1 else pe)

    new_k, new_v = cache.k, cache.v
    for i, layer in enumerate(params["layers"]):
        h = _rmsnorm(x, layer["ln1_scale"])
        q, k_c, v_c = _qkv(h, layer, cfg)
        if cfg.pos == "rope":
            # rotated keys go INTO the cache (absolute rotations), so
            # decode steps never re-touch old cache entries
            q = rope(q, posmat, cfg.rope_theta)
            k_c = rope(k_c, posmat, cfg.rope_theta)
        write_at = pos if ring is None else ring[0]
        lk = _cache_write(cache.k[i], k_c.astype(cache.k.dtype), write_at)
        lv = _cache_write(cache.v[i], v_c.astype(cache.v.dtype), write_at)
        new_k = new_k.at[i].set(lk)
        new_v = new_v.at[i].set(lv)
        attn = _cached_attention(
            q, lk, lv, pos, cfg,
            key_positions=None if ring is None else ring[1],
        )
        x = x + jnp.einsum(
            "btnh,nhd->btd", attn, wdense(layer, "wo", cfg.dtype)
        )
        h2 = _rmsnorm(x, layer["ln2_scale"])
        if "moe" in layer:
            from .moe import moe_mlp

            # Capacity policy (moe_drop_free is static at trace time):
            # - prefill: the TRAINING capacity factor — exactly
            #   transformer.forward's semantics, drops included, so
            #   prefill logits match the full forward for any config,
            #   and dispatch stays [T, E, C] with C = T*factor/E (the
            #   drop-free cap == T would make it quadratic in prompt
            #   tokens).
            # - decode steps: drop-free (cap == T == batch). A drop
            #   here would silently skip a generated token's MLP; the
            #   [b, E, b] dispatch is tiny.
            factor = (
                float(cfg.moe_experts) if moe_drop_free
                else cfg.moe_capacity_factor
            )
            y, _ = moe_mlp(h2, layer["moe"], factor, mesh=None)
            x = x + y
        else:
            h2 = jax.nn.gelu(
                jnp.einsum(
                    "btd,df->btf", h2, wdense(layer, "w1", cfg.dtype)
                )
            )
            x = x + jnp.einsum(
                "btf,fd->btd", h2, wdense(layer, "w2", cfg.dtype)
            )
    x = _rmsnorm(x, params["final_norm_scale"])
    logits = jnp.einsum(
        "btd,dv->btv", x, wdense(params, "lm_head", cfg.dtype)
    ).astype(jnp.float32)
    new_len = (
        cache.length + t if positions is None and ring is None
        else cache.length
    )
    return logits, KVCache(k=new_k, v=new_v, length=new_len)


def _sample(logits, key, temperature: float, top_k: int, top_p: float):
    """logits [b, vocab] -> token ids [b].

    top-k and nucleus top-p share ONE full-vocab sort (this runs inside
    the decode scan body, so the sort is per generated token): both
    filters reduce to a per-row cutoff value in the descending order,
    and the final mask is a single compare against the raw logits."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0 or 0.0 < top_p < 1.0:
        ranked = jnp.sort(logits, axis=-1)[:, ::-1]
        if top_k > 0:
            # rank-space mask: positions past top_k drop out of the
            # nucleus distribution below (softmax gives them 0 mass)
            pos = jnp.arange(ranked.shape[-1])
            ranked = jnp.where(pos[None] < top_k, ranked, NEG_INF)
        if 0.0 < top_p < 1.0:
            # keep the smallest prefix of the descending order whose
            # mass reaches top_p: a position stays while the mass
            # strictly BEFORE it is short of top_p (so the first token
            # is always kept). keep_count <= top_k when both are on —
            # masked positions carry ~full prefix mass — so the cutoff
            # is always a real logit value.
            probs = jax.nn.softmax(ranked, axis=-1)
            before = jnp.cumsum(probs, axis=-1) - probs
            keep_count = jnp.sum(before < top_p, axis=-1)  # [b], >= 1
            cutoff = jnp.take_along_axis(
                ranked, keep_count[:, None] - 1, axis=-1
            )
        else:
            cutoff = ranked[:, top_k - 1][:, None]
        logits = jnp.where(logits >= cutoff, logits, NEG_INF)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _sample_rowwise(logits, key, temperature, top_k, top_p):
    """Per-ROW sampling params: logits [b, vocab], temperature [b]
    float, top_k [b] int (0 = off), top_p [b] float (0 or 1 = off) ->
    token ids [b].

    The serving engine's step batches requests with different sampling
    configs into one program, so the params are traced arrays, not the
    static Python scalars _sample closes over — one compiled step
    serves every mix. Rows with temperature == 0 take the exact argmax
    (same as _sample's greedy path); the rest share _sample's
    one-sort top-k/top-p algebra with per-row cutoffs."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    b, vocab = logits.shape
    t = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / t
    ranked = jnp.sort(scaled, axis=-1)[:, ::-1]
    pos = jnp.arange(vocab)
    # top_k <= 0 means "keep all": effective k = vocab for those rows
    k_eff = jnp.where(top_k > 0, top_k, vocab)[:, None]
    in_k = pos[None] < k_eff
    ranked_k = jnp.where(in_k, ranked, NEG_INF)
    probs = jax.nn.softmax(ranked_k, axis=-1)
    before = jnp.cumsum(probs, axis=-1) - probs
    p_on = (top_p > 0.0) & (top_p < 1.0)
    p_eff = jnp.where(p_on, top_p, 1.0)[:, None]
    # smallest prefix whose mass reaches p_eff (first position always
    # kept); the in_k conjunct keeps float residue at masked positions
    # from sneaking past the compare when p_eff == 1
    keep_count = jnp.maximum(
        jnp.sum((before < p_eff) & in_k, axis=-1), 1
    )
    cutoff = jnp.take_along_axis(
        ranked_k, keep_count[:, None] - 1, axis=-1
    )
    masked = jnp.where(scaled >= cutoff, scaled, NEG_INF)
    sampled = jax.random.categorical(key, masked, axis=-1).astype(
        jnp.int32
    )
    return jnp.where(temperature <= 0.0, greedy, sampled)


def generate(
    params: Dict,
    prompt: jax.Array,
    cfg: ModelConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    key: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
    mesh=None,
) -> jax.Array:
    """Generate continuations. prompt [b, p] -> [b, p + max_new_tokens].

    With ``mesh`` (serving decode over devices): pass params already
    placed by ``decode_shardings``; the KV cache is constrained
    batch-over-dp and kv-heads-over-tp, and XLA partitions the whole
    prefill+scan (the per-step all-reduce over tp rides ICI). A batch
    that doesn't divide "dp" still works — GSPMD pads — but the padded
    rows burn HBM and compute on a real mesh, so size batch as a
    multiple of dp.

    Greedy when temperature == 0 (default), else temperature sampling
    with optional top-k and/or nucleus top-p truncation. Compiles to
    prefill + ONE scan; all shapes static. Accepts float params or the
    int8 weight-only form from quantize.quantize_params. MoE: prefill
    applies the training capacity policy (drops included — identical
    to transformer.forward on the same tokens); per-token decode steps
    are drop-free, which can IMPROVE on a capacity-dropped full
    forward — exact decode-vs-forward equivalence therefore holds only
    for configs whose capacity never drops (capacity_factor >=
    n_experts).
    """
    b, p = prompt.shape
    total = p + max_new_tokens
    max_len = max_len or total
    assert max_len >= total, (max_len, total)
    if cfg.pos == "learned":
        # only the learned table bounds the length; rope extrapolates
        assert cfg.max_seq >= max_len, (
            f"cfg.max_seq {cfg.max_seq} < requested length {max_len}"
        )
    if key is None:
        key = jax.random.key(0)

    if max_new_tokens == 0:
        return prompt
    run = _build_run(
        cfg, b, max_new_tokens, temperature, top_k, top_p, max_len, mesh
    )
    return run(params, prompt, key)


def decode_shardings(
    mesh, cfg: ModelConfig, params: Optional[Dict] = None
) -> Tuple[Dict, "KVCache"]:
    """(param shardings, KVCache shardings) for serving decode on a
    mesh: batch over "dp", kv heads over "tp" (cache layout
    [L, b, s, g, h]). Place params with ``jax.device_put(params,
    shardings)`` and pass the mesh to generate().

    For an int8 tree (quantize.quantize_params), pass the ACTUAL
    params: each quantized leaf becomes {"q": weight's sharding,
    "s": that sharding with size-1 (keepdims) axes unpartitioned} —
    the float shardings alone would try to split the scale's
    singleton axes over tp."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .quantize import is_quantized
    from .transformer import _full_param_shardings

    tp = mesh.shape.get("tp", 1)
    assert cfg.kv_heads % tp == 0, (
        f"kv_heads {cfg.kv_heads} must divide over tp={tp} "
        "(the cache shards its kv-head axis)"
    )
    p_shard = _full_param_shardings(mesh, cfg)
    if params is not None:
        def leaf_shard(leaf, ns):
            if not is_quantized(leaf):
                return ns
            spec = ns.spec
            s_spec = P(*(
                None if dim == 1 else ax
                for dim, ax in zip(
                    leaf["s"].shape,
                    tuple(spec) + (None,) * (
                        leaf["s"].ndim - len(spec)
                    ),
                )
            ))
            return {"q": ns, "s": NamedSharding(mesh, s_spec)}

        p_shard = jax.tree_util.tree_map(
            leaf_shard,
            params,
            _broadcast_like(params, p_shard),
            is_leaf=is_quantized,
        )
    cache_ns = NamedSharding(mesh, P(None, "dp", None, "tp", None))
    return p_shard, KVCache(
        k=cache_ns, v=cache_ns, length=NamedSharding(mesh, P())
    )


def _broadcast_like(params: Dict, shardings: Dict) -> Dict:
    """Expand a shardings tree onto params' exact structure (the layer
    list in shardings is full-length already; this only aligns leaf
    granularity so tree_map can pair quantized dict-leaves 1:1)."""
    return {
        **{k: v for k, v in shardings.items() if k != "layers"},
        "layers": [
            {k: layer_s[k] for k in layer_p}
            for layer_p, layer_s in zip(
                params["layers"], shardings["layers"]
            )
        ],
    }


@functools.lru_cache(maxsize=64)
def _build_run(
    cfg: ModelConfig, b: int, max_new_tokens: int,
    temperature: float, top_k: int, top_p: float, max_len: int,
    mesh=None,
):
    """Cached jitted decode program per (config, shape, sampling, mesh)
    key — a fresh closure per generate() call would retrace and
    recompile the whole prefill+scan on every invocation."""

    @jax.jit
    def run(params, prompt, key):
        cache = KVCache.empty(cfg, b, max_len)
        if mesh is not None:
            cache_shard = decode_shardings(mesh, cfg)[1]
            cache = KVCache(
                k=jax.lax.with_sharding_constraint(cache.k, cache_shard.k),
                v=jax.lax.with_sharding_constraint(cache.v, cache_shard.v),
                length=cache.length,
            )
        logits, cache = _forward_chunk(params, prompt, cache, cfg)
        first = _sample(logits[:, -1], key, temperature, top_k, top_p)

        def step(carry, _):
            cache, tok, key = carry
            key, sub = jax.random.split(key)
            logits, cache = _forward_chunk(
                params, tok[:, None], cache, cfg, moe_drop_free=True
            )
            nxt = _sample(logits[:, -1], sub, temperature, top_k, top_p)
            return (cache, nxt, key), nxt

        # prefill's sample is generated token 1; the scan emits tokens
        # 2..N in N-1 steps — no final forward whose sample is discarded
        _, toks = jax.lax.scan(
            step, (cache, first, key), None, length=max_new_tokens - 1
        )
        gen = jnp.concatenate(
            [first[:, None], jnp.moveaxis(toks, 0, 1)], axis=1
        )  # [b, max_new_tokens]
        return jnp.concatenate([prompt, gen], axis=1)

    return run


def decode_logits_reference(
    params: Dict, tokens: jax.Array, cfg: ModelConfig
) -> jax.Array:
    """Oracle: full-recompute logits for a whole sequence (no cache)."""
    from .transformer import forward

    return forward(params, tokens, cfg).astype(jnp.float32)
