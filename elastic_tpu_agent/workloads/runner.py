"""In-pod workload runner: what a JAX pod executes under the agent.

Reads the env contract the hook injected (/run/elastic-tpu/env — visible
chips, HBM quota, priority, slice topology), applies it, forms the device
mesh (joining the multi-host slice via jax.distributed when slice env is
present), runs the flagship transformer train loop, and reports
throughput. This is the measurable payload for BASELINE configs 2-5.

Usage (inside the container):
    python -m elastic_tpu_agent.workloads.runner --steps 20 --batch 8 \
        --seq 256 --preset small
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import sys
import time

import numpy as np

ENV_FILE = "/run/elastic-tpu/env"

PRESETS = {
    "tiny": dict(vocab=2048, d_model=256, n_heads=4, n_layers=2, d_ff=1024),
    "small": dict(vocab=32768, d_model=512, n_heads=8, n_layers=8, d_ff=2048),
    "medium": dict(vocab=32768, d_model=1024, n_heads=16, n_layers=12,
                   d_ff=4096),
}


def load_alloc_env(path: str = "") -> dict:
    """Apply the hook-written env file (KEY=VALUE lines) to this process.

    ``path`` defaults to $ELASTIC_TPU_ENV_FILE (resolved at call time,
    for non-standard mounts and tests) or the in-container ENV_FILE.

    Agent values OVERRIDE ambient env: this file is the pod's allocation
    truth, the moral equivalent of kubelet injecting the device plugin's
    Allocate envs (reference gpushare.go:79-82) — image baselines like a
    pre-set single-host TPU_WORKER_HOSTNAMES must not shadow the slice
    the scheduler actually assigned."""
    path = path or os.environ.get("ELASTIC_TPU_ENV_FILE", ENV_FILE)
    applied = {}
    if not os.path.exists(path):
        return applied
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or "=" not in line:
                continue
            key, _, value = line.partition("=")
            os.environ[key] = value
            applied[key] = value
    return applied


def apply_hbm_quota() -> None:
    """Cooperative HBM quota (BASELINE config 4): on TPU there is no driver
    interception, so translate the agent's quota into the libtpu/XLA knobs
    that exist and expose it for the training code's own budgeting."""
    frac = os.environ.get("ELASTIC_TPU_HBM_FRACTION")
    if frac:
        # libtpu honors TPU_MEM_FRACTION on recent releases; keep the
        # generic knob set either way so workloads can self-limit.
        os.environ.setdefault("TPU_MEM_FRACTION", frac)


def maybe_join_slice() -> None:
    """Multi-host slice: when the agent injected TPU_WORKER_ID/HOSTNAMES,
    initialize jax.distributed so the hosts form one slice (config 5)."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if "," not in hostnames:
        return  # single host
    import jax

    worker_id = int(os.environ.get("TPU_WORKER_ID", "0"))
    port = os.environ.get("ELASTIC_TPU_COORD_PORT", "8476")
    coordinator = f"{hostnames.split(',')[0]}:{port}"
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=len(hostnames.split(",")),
        process_id=worker_id,
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=256)
    parser.add_argument("--preset", choices=sorted(PRESETS), default="small")
    parser.add_argument(
        "--kv-heads", type=int, default=0,
        help="grouped-query attention: shared k/v heads "
             "(0 = MHA; must divide the preset's n_heads)",
    )
    parser.add_argument("--dp", type=int, default=None)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--tp", type=int, default=None)
    parser.add_argument(
        "--pp", type=int, default=1,
        help="pipeline-parallel stages (layer stack staged over a 'pp' "
             "mesh axis; n_layers must divide evenly)",
    )
    parser.add_argument(
        "--pp-schedule", choices=("gpipe", "1f1b"), default="gpipe",
        help="pipeline schedule: GPipe (autodiff backward) or 1F1B "
             "(interleaved, O(pp) activation memory)",
    )
    parser.add_argument(
        "--n-micro", type=int, default=4,
        help="microbatches per step in pipeline mode (--pp > 1)",
    )
    parser.add_argument(
        "--data", default="",
        help="ETPU token dataset (workloads/data.py) to train on; "
             "default: synthetic random tokens",
    )
    parser.add_argument(
        "--checkpoint-dir", default="",
        help="enable preemption-tolerant checkpoint/resume (orbax)",
    )
    parser.add_argument("--checkpoint-every", type=int, default=10)
    parser.add_argument(
        "--precopy-every", type=int, default=5,
        help="pre-copy migration (workloads/checkpointing.py "
             "DeltaCheckpointer): on a drain signal, stream a delta "
             "snapshot every N steps WHILE TRAINING CONTINUES and "
             "pause only for the final delta at the coordinator's "
             "cutover signal; 0 = classic checkpoint-and-exit on the "
             "drain signal",
    )
    parser.add_argument(
        "--profile-dir", default="",
        help="capture a JAX/XLA profiler trace of the timed steps "
             "(open with tensorboard or xprof)",
    )
    parser.add_argument(
        "--accum-steps", type=int, default=1,
        help="gradient accumulation: split --batch into this many "
             "micro-batches per optimizer update (activation HBM drops "
             "to one micro-batch; not supported with --pp)",
    )
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument(
        "--master-weights", action="store_true",
        help="store live params in the model dtype (bf16) with f32 "
             "masters inside the optimizer state: halves weight HBM "
             "reads and removes the per-step f32->bf16 casts. "
             "dp/sp/tp mode only",
    )
    parser.add_argument(
        "--zero1", action="store_true",
        help="shard optimizer state (moments, masters, EMA) over the "
             "dp mesh axis (ZeRO-1): optimizer HBM drops to 1/dp per "
             "rank. dp/sp/tp mode only",
    )
    parser.add_argument(
        "--ema-decay", type=float, default=0.0,
        help="keep an EMA of params in the optimizer state (e.g. "
             "0.999) and save it as its own checkpoint item; export "
             "with `export --ema`. dp/sp/tp mode only",
    )
    parser.add_argument(
        "--warmup-steps", type=int, default=0,
        help="linear warmup to --lr then cosine decay to 10%% over "
             "--total-steps (0 = constant lr); dp/sp/tp mode only",
    )
    parser.add_argument(
        "--total-steps", type=int, default=0,
        help="schedule horizon across ALL invocations of a "
             "checkpoint-resumed run (default: this run's --steps); "
             "pass the same value on every resume so the lr curve "
             "matches an uninterrupted run",
    )
    parser.add_argument(
        "--eval-every", type=int, default=0,
        help="held-out eval loss every N steps (0 = off; dp/sp/tp "
             "mode only). With --data the LAST --eval-frac of the "
             "file is held out of training",
    )
    parser.add_argument("--eval-batches", type=int, default=2)
    parser.add_argument("--eval-frac", type=float, default=0.1)
    parser.add_argument(
        "--mode", choices=("train", "decode"), default="train",
        help="train: timed optimizer steps (default); decode: KV-cache "
             "generation throughput, optionally from a checkpoint",
    )
    parser.add_argument(
        "--prompt-len", type=int, default=32,
        help="decode mode: synthetic prompt length",
    )
    parser.add_argument(
        "--new-tokens", type=int, default=64,
        help="decode mode: tokens generated per sequence",
    )
    parser.add_argument(
        "--int8", action="store_true",
        help="decode mode: int8 weight-only quantization "
             "(workloads/quantize.py)",
    )
    parser.add_argument(
        "--params-dir", default="",
        help="decode mode: serve an exported artifact "
             "(workloads/export.py); its config overrides --preset",
    )
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top-k", type=int, default=0)
    parser.add_argument("--top-p", type=float, default=0.0)
    parser.add_argument(
        "--flight-recorder", default="",
        help="write per-step flight-recorder JSONL here (default: "
             "$ELASTIC_TPU_FLIGHT_RECORDER, or in-memory only); records "
             "carry the agent-propagated ELASTIC_TPU_TRACE_ID",
    )
    args = parser.parse_args(argv)

    applied = load_alloc_env()
    apply_hbm_quota()
    maybe_join_slice()

    import jax

    # Honor JAX_PLATFORMS even when something imported jax before this
    # process's env was in place (e.g. an image-level sitecustomize): the
    # config snapshot would otherwise win over the user's env.
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from .transformer import ModelConfig, make_mesh, make_train_step

    cfg = ModelConfig(
        max_seq=args.seq, n_kv_heads=args.kv_heads, **PRESETS[args.preset]
    )
    if args.mode == "decode":
        if args.pp > 1 or args.sp != 1:
            parser.error("decode mode shards over dp/tp only")
        return run_decode(args, cfg, applied)
    if args.pp > 1:
        from .pipeline import make_pipeline_mesh
        from .transformer_pipeline import make_pipeline_transformer_step

        if args.accum_steps > 1:
            parser.error(
                "--accum-steps composes with the dp/sp/tp step only; "
                "pipeline mode already micro-batches via --n-micro"
            )
        if args.warmup_steps > 0:
            parser.error(
                "--warmup-steps is not supported with --pp "
                "(the pipeline step takes a constant --lr)"
            )
        if args.ema_decay > 0:
            parser.error("--ema-decay is not supported with --pp")
        if args.master_weights or args.zero1:
            parser.error(
                "--master-weights/--zero1 compose with the dp/sp/tp "
                "step only (the pipeline step owns its own state)"
            )
        if args.sp != 1 or (args.tp or 1) != 1:
            parser.error(
                "--pp composes with --dp only; --sp/--tp are not supported "
                "in pipeline mode (the pp mesh has axes pp x dp)"
            )
        dp = args.dp or max(1, len(jax.devices()) // args.pp)
        mesh = make_pipeline_mesh(pp=args.pp, dp=dp)
        train_step, init_all = make_pipeline_transformer_step(
            cfg, mesh, n_micro=args.n_micro, schedule=args.pp_schedule,
            learning_rate=args.lr,
        )
        assert args.batch % args.n_micro == 0, (
            f"--batch {args.batch} must divide into --n-micro {args.n_micro}"
        )
        assert (args.batch // args.n_micro) % dp == 0, (
            f"microbatch size {args.batch // args.n_micro} must be "
            f"divisible by dp={dp}"
        )
        tokens = jax.random.randint(
            jax.random.key(1),
            (args.n_micro, args.batch // args.n_micro, args.seq + 1),
            0, cfg.vocab,
        )
    else:
        mesh = make_mesh(dp=args.dp, sp=args.sp, tp=args.tp)
        if args.accum_steps < 1:
            parser.error(f"--accum-steps {args.accum_steps} must be >= 1")
        if not 0.0 <= args.ema_decay < 1.0:
            parser.error(
                f"--ema-decay {args.ema_decay} must be in [0, 1)"
            )
        if args.accum_steps > 1 and args.batch % args.accum_steps:
            parser.error(
                f"--accum-steps {args.accum_steps} must divide "
                f"--batch {args.batch}"
            )
        if args.warmup_steps > 0:
            import optax

            # The schedule horizon is --total-steps (default: this
            # invocation's --steps). The optimizer's restored step
            # count indexes the schedule, so a checkpoint-resumed run
            # continues the SAME curve — provided every invocation
            # passes the same --total-steps (a resumed run passing
            # only its remaining --steps would compress the decay).
            horizon = args.total_steps or args.steps
            lr = optax.warmup_cosine_decay_schedule(
                init_value=0.0, peak_value=args.lr,
                warmup_steps=args.warmup_steps,
                decay_steps=max(args.warmup_steps + 1, horizon),
                end_value=args.lr * 0.1,
            )
        else:
            lr = args.lr
        train_step, init_all, _ = make_train_step(
            cfg, mesh, learning_rate=lr, accum_steps=args.accum_steps,
            ema_decay=args.ema_decay,
            master_weights=args.master_weights, zero1=args.zero1,
        )
        shape = (
            (args.batch, args.seq + 1) if args.accum_steps == 1
            else (
                args.accum_steps, args.batch // args.accum_steps,
                args.seq + 1,
            )
        )
        tokens = jax.random.randint(
            jax.random.key(1), shape, 0, cfg.vocab
        )
    params, opt_state = init_all(jax.random.key(0))

    dataset = None
    if args.data:
        from .data import TokenDataset

        dataset = TokenDataset(args.data)
        # full-file scan: a single out-of-range token ANYWHERE silently
        # corrupts training via clamped gathers, so sampling is not enough
        assert dataset.max_token(sample=None) < cfg.vocab, (
            f"dataset tokens exceed model vocab {cfg.vocab}"
        )

    from jax.sharding import NamedSharding, PartitionSpec as P

    token_sharding = NamedSharding(
        mesh,
        P(None, "dp", None) if (args.pp > 1 or args.accum_steps > 1)
        else P("dp", None),
    )

    def replicate_global(arr, sharding):
        """Assemble a process-replicated value (every process computed
        the SAME array, e.g. from a shared seed) into a global
        jax.Array: each process contributes the slices its devices
        own. (A raw numpy/single-device array into a cross-process
        jit is rejected by JAX.)"""
        arr_np = np.asarray(arr)
        return jax.make_array_from_callback(
            arr_np.shape, sharding, lambda idx: arr_np[idx]
        )

    if dataset is None and jax.process_count() > 1:
        tokens = replicate_global(tokens, token_sharding)

    # Held-out eval: the file's LAST --eval-frac sequence windows never
    # enter training, so the eval number measures generalization.
    # dp/sp/tp mode only (the pipeline mesh has no tp/sp axes for the
    # eval fn's shardings).
    train_region = eval_region = None
    eval_fn = None
    if args.eval_every > 0:
        if args.pp > 1:
            parser.error("--eval-every is not supported with --pp")
        from .transformer import make_eval_fn

        eval_fn = make_eval_fn(cfg, mesh)
        if dataset is not None:
            train_region, eval_region = dataset.split_regions(
                args.seq, args.eval_frac
            )
        eval_sharding = NamedSharding(mesh, P("dp", None))

        def eval_batch(j):
            if dataset is None:
                # synthetic: a fixed batch disjoint from the training
                # key stream (assembled globally under multi-host, as
                # for the training tokens)
                b = jax.random.randint(
                    jax.random.key(10_000 + j),
                    (args.batch, args.seq + 1), 0, cfg.vocab,
                )
                if jax.process_count() == 1:
                    return b
                return replicate_global(b, eval_sharding)
            b = dataset.batch(
                j, args.batch, args.seq,
                dp_rank=jax.process_index(),
                dp_size=jax.process_count(),
                region=eval_region,
            )
            if jax.process_count() == 1:
                return b
            return jax.make_array_from_process_local_data(
                eval_sharding, b
            )

    def tokens_for(step):
        """Per-step batch: deterministic dataset shard (this process's
        slice of the global batch) or the fixed synthetic tokens."""
        if dataset is None:
            return tokens
        b = dataset.batch(
            step, args.batch, args.seq,
            dp_rank=jax.process_index(), dp_size=jax.process_count(),
            region=train_region,
        )
        if args.pp > 1:
            b = b.reshape(args.n_micro, args.batch // args.n_micro, -1)
        elif args.accum_steps > 1:
            b = b.reshape(
                args.accum_steps, args.batch // args.accum_steps, -1
            )
        if jax.process_count() == 1:
            return b  # one process: the local batch IS the global batch
        # Multi-host: each process holds only ITS shard of the global
        # batch. Assemble the distributed array explicitly — handing the
        # local numpy to jit would be reinterpreted as a (wrong) global
        # value and sliced a second time by device ownership.
        return jax.make_array_from_process_local_data(token_sharding, b)

    # Preemption-tolerant resume (TPU pods are preemptible; the elastic
    # scheduler may also move us): restore the latest checkpoint onto the
    # live mesh shardings, and save on SIGTERM before dying.
    #
    # Migration handshake (workloads/lifecycle.py): the watcher polls
    # the alloc spec for the agent's drain signal / slice-epoch bump —
    # either checkpoints NOW and acknowledges with an atomic ack file,
    # so the agent can reclaim the chips the moment the work is safe
    # instead of at the deadline. A replacement pod finds the
    # destination agent's ELASTIC_TPU_RESTORE_DIR stamp, restores from
    # the migrated checkpoint and acks the resume for verification.
    from .lifecycle import (
        SIGNAL_CUTOVER,
        SIGNAL_DRAIN,
        SIGNAL_REFORM,
        LifecycleWatcher,
    )

    watcher = LifecycleWatcher()
    restore_req = watcher.restore_request() if watcher.enabled else None
    if watcher.enabled and restore_req is None:
        # The destination agent stamps the restore env up to one
        # migration tick AFTER the bind; a fast-starting replacement
        # must not race past the stamp and silently train from
        # scratch. Wait briefly — but not at all when a populated
        # local checkpoint dir already answers where to resume from.
        has_local = False
        if args.checkpoint_dir and os.path.isdir(args.checkpoint_dir):
            try:
                has_local = bool(os.listdir(args.checkpoint_dir))
            except OSError:
                has_local = False
        wait_s = 0.0 if has_local else float(
            os.environ.get("ELASTIC_TPU_RESTORE_WAIT_S", "5")
        )
        deadline = time.monotonic() + wait_s
        while restore_req is None and time.monotonic() < deadline:
            time.sleep(0.2)
            restore_req = watcher.restore_request()
    ckpt_dir = args.checkpoint_dir
    if not ckpt_dir and restore_req:
        ckpt_dir = restore_req["checkpoint_dir"]
    ckpt = None
    start_step = 0
    resumed = False
    preempted = {"flag": False}
    lifecycle_sig = {"sig": None}
    if ckpt_dir:
        from .checkpointing import TrainCheckpointer

        ckpt = TrainCheckpointer(ckpt_dir)
        # A pre-copy source leaves a delta CHAIN (workloads/
        # checkpointing.DeltaCheckpointer) whose final round is newer
        # than any periodic orbax save: prefer it when present, fall
        # back to orbax on a torn/corrupt chain (the chain digests make
        # torn detectable, never silently restorable).
        from .checkpointing import DeltaCheckpointer, bytes_to_tree

        delta_ck = DeltaCheckpointer(ckpt_dir)
        delta_step = delta_ck.latest_step
        if delta_step is not None and (
            ckpt.latest_step is None or delta_step >= ckpt.latest_step
        ):
            try:
                payload, manifest = delta_ck.load()
                params, opt_state = bytes_to_tree(
                    payload, (params, opt_state)
                )
                start_step = int(manifest["step"]) + 1
                resumed = True
            except (ValueError, OSError):
                delta_step = None  # torn chain: orbax below
        if not resumed and ckpt.latest_step is not None:
            params, opt_state, start_step = ckpt.restore(params, opt_state)
            start_step += 1
            resumed = True

        def on_sigterm(signum, frame):  # noqa: ARG001
            preempted["flag"] = True

        signal.signal(signal.SIGTERM, on_sigterm)
    if restore_req is not None and watcher.enabled:
        # The resume ack completes the handshake: the destination agent
        # verifies step >= the record's acked step and that the world
        # size matches the pod's CURRENT stamped slice env.
        watcher.ack_resume(
            start_step - 1 if resumed else None, checkpoint_dir=ckpt_dir
        )

    # AOT-compile instead of a warmup execution: a real warmup step would
    # apply an optimizer update the step accounting never sees, so a
    # resumed run would silently drift from an uninterrupted one. Compile
    # against the REAL first batch (dataset batches in multi-host runs are
    # globally process_count× larger than the synthetic shape — compiling
    # the wrong shape would push a full recompile into the timed loop).
    train_step.lower(params, opt_state, tokens_for(start_step)).compile()

    every = max(0, args.checkpoint_every)  # 0 = save only on preemption
    # Flight recorder (telemetry.py): per-step wall time, tokens/s, jit
    # recompiles and device-memory stats, tagged with the trace id the
    # agent propagated through the env file — load_alloc_env() above
    # already applied it, so the default constructor picks it up.
    from .telemetry import FlightRecorder

    # dataset mode feeds a global batch of local*process_count rows;
    # synthetic mode replicates one global batch of args.batch rows
    global_batch = args.batch * (
        jax.process_count() if dataset is not None else 1
    )
    tokens_per_step = global_batch * args.seq
    recorder = FlightRecorder(
        path=args.flight_recorder or None, jit_fns=(train_step,)
    )
    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    t0 = time.perf_counter()
    ran = 0
    loss = None
    last_saved_step = None
    eval_hist = []
    eval_s = 0.0  # eval wall time, subtracted from step accounting
    # Pre-copy migration (ISSUE 20): on a drain signal, instead of the
    # classic checkpoint-and-exit, keep training and stream delta
    # snapshots (changed blocks only, digest-chained) every
    # --precopy-every steps; pause only when the coordinator stamps
    # ELASTIC_TPU_CUTOVER — or, as a workload-side safety net, when
    # the drain deadline's final quarter arrives with no stamp.
    precopy = {
        "active": False, "round": 0, "delta": None, "sig": None,
        "deadline_ts": None, "seen_ts": None,
    }
    try:
        for step in range(start_step, start_step + args.steps):
            with recorder.step(step, tokens=tokens_per_step):
                params, opt_state, loss = train_step(
                    params, opt_state, tokens_for(step)
                )
            ran += 1
            if eval_fn is not None and (step + 1) % args.eval_every == 0:
                te = time.perf_counter()
                vals = [
                    float(eval_fn(params, eval_batch(j)))
                    for j in range(max(1, args.eval_batches))
                ]
                ev_dt = time.perf_counter() - te
                eval_s += ev_dt
                eval_hist.append({
                    "step": step,
                    "loss": sum(vals) / len(vals),
                })
                recorder.record(
                    "eval", step=step, loss=eval_hist[-1]["loss"],
                    duration_ms=round(ev_dt * 1000, 3),
                )
            sig = watcher.poll()
            if (
                sig is not None and sig.kind == SIGNAL_DRAIN
                and args.precopy_every > 0 and ckpt is not None
                and not precopy["active"]
            ):
                # pre-copy drain: training CONTINUES; deltas stream
                # below until the cutover signal ends the round trip
                from .checkpointing import DeltaCheckpointer

                precopy.update(
                    active=True, sig=sig, round=0,
                    deadline_ts=sig.deadline_ts, seen_ts=time.time(),
                    delta=DeltaCheckpointer(ckpt_dir),
                )
            elif sig is not None and sig.kind in (
                SIGNAL_DRAIN, SIGNAL_REFORM
            ):
                # checkpoint-and-exit: a drain means the chips go away;
                # a reform means the world size changed and the process
                # must restart to re-form the mesh. Either way the save
                # below runs this iteration and the ack lands once the
                # checkpoint is durable (after ckpt.wait()).
                lifecycle_sig["sig"] = sig
                preempted["flag"] = True
            if precopy["active"] and not preempted["flag"]:
                cut = sig is not None and sig.kind == SIGNAL_CUTOVER
                if not cut and precopy["deadline_ts"]:
                    budget = max(
                        0.0, precopy["deadline_ts"] - precopy["seen_ts"]
                    )
                    cut = time.time() >= (
                        precopy["deadline_ts"] - 0.25 * budget
                    )
                if cut:
                    # cutover: training pauses HERE; only the blocks
                    # dirtied since the last streamed round ship inside
                    # the pause window (a full orbax save would put the
                    # whole state back on the critical path)
                    from .checkpointing import tree_to_bytes

                    t_cut = time.perf_counter()
                    summary = precopy["delta"].save(
                        step, tree_to_bytes((params, opt_state)),
                        round_=precopy["round"],
                    )
                    precopy["final"] = summary
                    precopy["cutover_ms"] = round(
                        (time.perf_counter() - t_cut) * 1000, 3
                    )
                    last_saved_step = step
                    lifecycle_sig["sig"] = precopy["sig"]
                    preempted["flag"] = True
                elif (step + 1) % max(1, args.precopy_every) == 0:
                    from .checkpointing import tree_to_bytes

                    summary = precopy["delta"].save(
                        step, tree_to_bytes((params, opt_state)),
                        round_=precopy["round"],
                    )
                    watcher.ack_precopy(
                        step, precopy["round"], checkpoint_dir=ckpt_dir,
                        delta_bytes=summary["delta_bytes"],
                        total_bytes=summary["total_bytes"],
                        digest=summary["chain"],
                        signal=precopy["sig"].value,
                    )
                    precopy["round"] += 1
            if ckpt is not None and (
                (preempted["flag"] and precopy.get("final") is None)
                or (every > 0 and (step + 1) % every == 0)
            ):
                if args.ema_decay > 0:
                    from .transformer import ema_params

                    ckpt.save(
                        step, params, opt_state,
                        ema=ema_params(opt_state),
                    )
                else:
                    ckpt.save(step, params, opt_state)
                last_saved_step = step
            if preempted["flag"]:
                break
        if loss is not None:
            jax.block_until_ready(loss)
    finally:
        # stop even on a mid-loop failure — the crashed run is exactly
        # the one whose trace you want readable
        if args.profile_dir:
            jax.profiler.stop_trace()
    dt = time.perf_counter() - t0 - eval_s
    if ckpt is not None:
        ckpt.wait()
        if precopy["active"] and precopy.get("final") is None and ran:
            # the step budget ran out mid-stream with no cutover stamp:
            # close the stream with a final delta anyway so the agent
            # gets its cutover ack instead of waiting out the deadline
            from .checkpointing import tree_to_bytes

            t_cut = time.perf_counter()
            precopy["final"] = precopy["delta"].save(
                step, tree_to_bytes((params, opt_state)),
                round_=precopy["round"],
            )
            precopy["cutover_ms"] = round(
                (time.perf_counter() - t_cut) * 1000, 3
            )
            last_saved_step = step
            lifecycle_sig["sig"] = lifecycle_sig["sig"] or precopy["sig"]
        sig = lifecycle_sig["sig"]
        if sig is not None and last_saved_step is not None:
            digest = None
            extra = None
            if precopy.get("final") is not None:
                summary = precopy["final"]
                digest = summary["chain"]
                extra = {
                    "precopy_rounds": precopy["round"],
                    "delta_bytes": summary["delta_bytes"],
                    "full_bytes": summary["total_bytes"],
                    "cutover_ms": precopy["cutover_ms"],
                }
            # the checkpoint is durable (wait() returned) — only now is
            # the ack honest: the agent reclaims the chips on it
            watcher.ack(
                last_saved_step, checkpoint_dir=ckpt_dir,
                signal=sig.value, epoch=sig.epoch,
                digest=digest, extra=extra,
            )
        ckpt.close()

    report = {
        "platform": jax.devices()[0].platform,
        "devices": len(jax.devices()),
        "mesh": dict(mesh.shape),
        "steps": ran,
        "start_step": start_step,
        "final_loss": float(loss) if loss is not None else None,
        "step_time_ms": dt / max(1, ran) * 1000,
        "tokens_per_s": tokens_per_step * ran / dt,
        "alloc_env": applied,
        "preempted": preempted["flag"],
        "lifecycle_signal": (
            lifecycle_sig["sig"].kind if lifecycle_sig["sig"] else None
        ),
        "resumed_from_migration": restore_req is not None,
        "precopy_rounds": precopy["round"] if precopy["active"] else 0,
    }
    if eval_hist:
        report["eval"] = eval_hist
    if args.warmup_steps > 0:
        report["lr_schedule"] = {
            "peak": args.lr, "warmup_steps": args.warmup_steps,
        }
    recorder.record("run_summary", **{
        k: report[k] for k in ("steps", "step_time_ms", "tokens_per_s")
    })
    report["flight_recorder"] = recorder.summary()
    recorder.close()
    print(json.dumps(report))
    return 0


def run_decode(args, cfg, applied) -> int:
    """Decode-mode body: synthetic prompts -> KV-cache generation
    throughput. Weights come from --params-dir (a serving artifact,
    workloads/export.py — its config overrides --preset), from
    --checkpoint-dir (restore-only), or fresh init; --int8 quantizes
    on the way in. Shards over dp/tp via decode_shardings when the
    mesh has more than one device."""
    import jax

    from .generate import decode_shardings, generate
    from .transformer import init_params, make_mesh

    if jax.process_count() > 1:
        raise SystemExit(
            "decode mode is single-host: sharded params are created by "
            "device_put from host arrays, which cannot target a "
            "cross-process mesh (train mode initializes inside jit)"
        )

    artifact_params = None
    if args.params_dir:
        if args.checkpoint_dir:
            raise SystemExit(
                "--params-dir and --checkpoint-dir are exclusive "
                "(an artifact already IS the exported checkpoint)"
            )
        from .export import load_artifact

        artifact_params, cfg = load_artifact(args.params_dir)

    max_len = args.prompt_len + args.new_tokens
    if cfg.pos == "learned" and cfg.max_seq < max_len:
        if args.checkpoint_dir or args.params_dir:
            # a trained position table has the trained length; widening
            # the restore template would shape-mismatch orbax, and a
            # learned table can't extrapolate anyway
            raise SystemExit(
                f"decode length {max_len} exceeds the trained "
                f"max_seq {cfg.max_seq}; shorten --prompt-len/"
                "--new-tokens or retrain with a longer --seq"
            )
        cfg = dataclasses.replace(cfg, max_seq=max_len)

    restored_step = None
    if artifact_params is not None:
        params = artifact_params
        restored_step = "artifact"
    else:
        params = init_params(cfg, jax.random.key(0))
    if args.checkpoint_dir:
        from .checkpointing import TrainCheckpointer

        ckpt = TrainCheckpointer(args.checkpoint_dir)
        if ckpt.latest_step is None:
            # decode mode is restore-only: falling through to random
            # init would silently benchmark an untrained model
            raise SystemExit(
                f"--checkpoint-dir {args.checkpoint_dir} holds no "
                "checkpoint (decode mode serves trained params; train "
                "first or drop the flag)"
            )
        # params-only restore tolerating either optimizer form
        # (float lr vs schedule) the training run used
        params, restored_step = ckpt.restore_params(params)
        ckpt.close()

    if args.int8:
        from .quantize import quantize_params

        params = jax.jit(quantize_params)(params)
        jax.block_until_ready(params)

    # multi-device hosts shard by default, mirroring train mode (an
    # unsharded run would still REPORT all devices — misattributing
    # single-chip throughput to the whole host)
    mesh = None
    n_dev = len(jax.devices())
    if n_dev > 1:
        mesh = make_mesh(dp=args.dp, sp=1, tp=args.tp, ep=1)
        p_shard, _ = decode_shardings(mesh, cfg, params=params)
        params = jax.device_put(params, p_shard)

    prompt = jax.random.randint(
        jax.random.key(1), (args.batch, args.prompt_len), 0, cfg.vocab
    )

    def timed(n):
        def once():
            out = generate(
                params, prompt, cfg, max_new_tokens=n,
                temperature=args.temperature, top_k=args.top_k,
                top_p=args.top_p, mesh=mesh,
                max_len=args.prompt_len + args.new_tokens,
            )
            jax.block_until_ready(out)
            return out

        once()  # compile + warmup
        t0 = time.perf_counter()
        out = once()
        return out, time.perf_counter() - t0

    # prefill+1 isolates the prompt pass: quoting full wall time over
    # new_tokens would bill the prefill to the per-token decode rate
    _, dt_prefill = timed(1)
    out, dt_full = timed(args.new_tokens)
    decode_dt = dt_full - dt_prefill
    decode_steps = args.new_tokens - 1
    # two independent wall clocks: when prefill dominates, their noise
    # can exceed the decode time — report null rather than a rate
    # computed from a sub-noise (or negative) denominator
    measurable = decode_steps > 0 and decode_dt > 0.02 * dt_full

    report = {
        "mode": "decode",
        "platform": jax.devices()[0].platform,
        "devices": n_dev,
        "mesh": dict(mesh.shape) if mesh is not None else None,
        "batch": args.batch,
        "prompt_len": args.prompt_len,
        "new_tokens": args.new_tokens,
        "int8": bool(args.int8),
        "restored_step": restored_step,
        "prefill_ms": dt_prefill * 1000,
        "decode_tokens_per_s": (
            args.batch * decode_steps / decode_dt if measurable else None
        ),
        "ms_per_token": (
            decode_dt / decode_steps * 1000 if measurable else None
        ),
        "end_to_end_s": dt_full,
        "sample_tail": [int(t) for t in out[0, -5:]],
        "alloc_env": applied,
    }
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
