"""Serving partitioner: mesh construction + sharding rules for the
tensor-parallel ServingEngine.

Millions-of-users traffic is served by engines WIDER than one chip:
a model that fits one chip's HBM still wants its per-step matmuls and
KV reads spread over a slice so decode latency scales down with chips.
This module is the serving counterpart of generate.decode_shardings,
shaped after the two reference patterns in SNIPPETS.md: [2]'s
logical-axis -> mesh-axis rule table over an (dp, mp) mesh, and [3]'s
Partitioner object that owns the mesh and the placement decisions so
engine code never touches PartitionSpecs directly.

The engine's tensor-parallel layout:
- attention heads (wq/wqkv, wo) and GQA kv heads (wkv) split over
  "mp";
- the MLP hidden axis (w1/w2) and the lm_head vocab axis split over
  "mp" (one all-reduce per step rides the mesh after wo/w2, the
  standard Megatron shape);
- the paged KV POOL [L, n_blocks, bs, kv_heads, h] splits its kv-head
  axis over "mp" — each chip holds its heads' slice of every block, so
  pool BOOKKEEPING (allocator, tables, refcounts) is identical to the
  single-device engine and occupancy matches it block for block;
- embeddings/norms replicate ("mp" collectives stay in the layer
  body), and "dp" is a fleet-of-engines axis: one ServingEngine owns
  one continuous batch, so in-engine batch stays unsharded.

Exercised on CPU via --xla_force_host_platform_device_count (the same
harness the sharded-decode and multihost tests use).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Logical-axis -> mesh-axis rules (SNIPPETS [2] DEFAULT_RULES shape).
RULES = {
    "batch": None,
    "heads": "mp",
    "embed": None,
    "mlp": "mp",
    "kv_heads": "mp",
    "seq": None,
    "vocab": "mp",
}

# Per-leaf PartitionSpecs derived from RULES against init_params'
# shapes (transformer.py): wqkv [d, 3, n, h], wq [d, n, h],
# wkv [d, 2, g, h], wo [n, h, d], w1 [d, f], w2 [f, d],
# lm_head [d, v]. Everything absent here replicates.
_LEAF_SPECS = {
    "wqkv": (None, None, RULES["heads"], None),
    "wq": (None, RULES["heads"], None),
    "wkv": (None, None, RULES["kv_heads"], None),
    "wo": (RULES["heads"], None, None),
    "w1": (None, RULES["mlp"]),
    "w2": (RULES["mlp"], None),
    "lm_head": (None, RULES["vocab"]),
}

# The paged pool [L, n_blocks, block, kv_heads, head_dim]: kv heads
# over "mp", everything else replicated (the block axis is addressed by
# host-side tables, splitting it would shard the allocator too).
POOL_SPEC = (None, None, None, RULES["kv_heads"], None)


def make_serving_mesh(
    mp: Optional[int] = None, n_devices: Optional[int] = None
) -> Mesh:
    """(dp, mp) mesh over the visible devices; default mp = all of
    them (one tensor-parallel engine spanning the slice)."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        # fail loudly here, not as an opaque reshape error below (a
        # preset XLA_FLAGS with a smaller device count is the usual
        # culprit)
        raise ValueError(
            f"requested n_devices={n} but only {len(devices)} "
            "visible (check --xla_force_host_platform_device_count)"
        )
    devices = devices[:n]
    if mp is None:
        mp = n
    if n % mp:
        raise ValueError(f"mp={mp} does not divide {n} devices")
    arr = np.array(devices).reshape(n // mp, mp)
    return Mesh(arr, axis_names=("dp", "mp"))


class ServingPartitioner:
    """Owns the serving engine's mesh and placement (SNIPPETS [3]'s
    Partitioner shape). ``mesh=None`` is the single-device
    partitioner: every method is a no-op passthrough, so the engine
    has ONE code path."""

    def __init__(self, mesh: Optional[Mesh], cfg) -> None:
        self.mesh = mesh
        self.cfg = cfg
        if mesh is None:
            return
        if "mp" not in mesh.shape:
            raise ValueError(
                "serving mesh needs an 'mp' axis; build it with "
                "partitioner.make_serving_mesh"
            )
        if cfg.moe_experts:
            raise ValueError(
                "tensor-parallel serving supports dense models (MoE "
                "expert parallelism is a different mesh axis)"
            )
        mp = mesh.shape["mp"]
        for name, dim in (
            ("n_heads", cfg.n_heads),
            ("kv_heads", cfg.kv_heads),
            ("d_ff", cfg.d_ff),
            ("vocab", cfg.vocab),
        ):
            if dim % mp:
                raise ValueError(
                    f"cfg.{name} {dim} must divide over mp={mp} "
                    "(heads/mlp/vocab all split on that axis)"
                )

    @property
    def mp(self) -> int:
        return 1 if self.mesh is None else self.mesh.shape["mp"]

    def _ns(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    # -- params -------------------------------------------------------

    def _leaf_sharding(self, name: str, leaf):
        from .quantize import is_quantized

        spec = _LEAF_SPECS.get(name)
        ns = self._ns(*spec) if spec else self._ns()
        if is_quantized(leaf):
            # int8 weight-only tree: the scale keeps keepdims axes
            # unpartitioned (generate.decode_shardings' rule)
            padded = tuple(spec or ()) + (None,) * (
                leaf["s"].ndim - len(spec or ())
            )
            s_spec = tuple(
                None if dim == 1 else ax
                for dim, ax in zip(leaf["s"].shape, padded)
            )
            return {"q": ns, "s": self._ns(*s_spec)}
        return ns

    def param_shardings(self, params: Dict) -> Dict:
        """NamedSharding tree matching ``params`` exactly (device_put
        rejects any structural mismatch, so a new param leaf that
        needs a rule fails loudly here rather than silently
        replicating)."""

        def shard_container(container: Dict) -> Dict:
            return {
                name: self._leaf_sharding(name, leaf)
                for name, leaf in container.items()
            }

        out = {
            name: self._leaf_sharding(name, leaf)
            for name, leaf in params.items()
            if name != "layers"
        }
        out["layers"] = [
            shard_container(layer) for layer in params["layers"]
        ]
        return out

    def shard_params(self, params: Dict) -> Dict:
        if self.mesh is None:
            return params
        return jax.device_put(params, self.param_shardings(params))

    # -- the paged KV pool --------------------------------------------

    def pool_sharding(self):
        return None if self.mesh is None else self._ns(*POOL_SPEC)

    def place_pool(self, pool):
        """Place one pool side (array, or the int8 {"q","s"} pytree —
        the scale's trailing keepdims axis is size 1 and replicates
        under the same spec)."""
        if self.mesh is None:
            return pool
        ns = self._ns(*POOL_SPEC)
        if isinstance(pool, dict):
            return {
                "q": jax.device_put(pool["q"], ns),
                "s": jax.device_put(pool["s"], ns),
            }
        return jax.device_put(pool, ns)
