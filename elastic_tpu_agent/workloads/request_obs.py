"""Request-level serving observatory: per-request SLO telemetry with a
conservation invariant, the PR-16 latency contract applied to the data
plane.

The agent side accounts for every millisecond of a bind (latency.py)
and every second of fleet downtime (goodput.py); this module gives the
serving engine the same discipline at *request* granularity. Every
admission gets an observatory-minted request id and a gap-free time
partition over a fixed phase vocabulary:

- ``queued``   — admission claimed, prefill not yet started (the
  chunked-prefill queue; ~0 for synchronous ``admit``),
- ``prefill``  — prompt compute, from first chunk to first token,
- ``decode``   — steady-state token generation,
- ``stalled``  — live-and-decoding but blocked behind another
  request's synchronous prefill (the unified-mode head-of-line hazard
  disaggregation exists to remove),
- ``handoff``  — disaggregated only: published by the prefill engine,
  not yet adopted by the decode engine.

Phases are closed interval-to-interval at shared timestamps, so for
every finished request ``sum(phase_seconds) + residual == wall`` holds
by construction with residual ~0 — the conservation contract tests pin.

Disaggregated requests are STITCHED across roles: the prefill engine
publishes the record alongside its blocks through ``SharedKVPool``
(keyed by the prompt's block-chain digests — the same keys the prefix
cache uses, and the routing key a future gateway would hash), the
decode engine adopts it at the auto-cache hit that IS the handoff, and
one id yields one contiguous partition spanning both engines with the
handoff latency its own phase.

Per request the observatory also attributes prefix-cache economics
(cached vs computed prefill tokens, the chain digest) and KV-pool byte
occupancy; per step it keeps a bounded breakdown of batch occupancy,
admissions vs evictions, and prefill-vs-decode compute share.

Surfacing follows the house pattern: histograms are observed at source
(``elastic_tpu_request_ttft_seconds{slo}`` /
``_tpot_seconds{slo}`` / ``_phase_seconds{phase}`` — label vocabularies
are FIXED, so cardinality is bounded no matter what callers send),
gauges read at scrape via ``AgentMetrics.attach_requests``, and
``status()`` feeds the loopback ``/debug/requests`` endpoint and the
doctor bundle's ``requests`` block. SLO classes come from a
request-carried annotation (``slo="ttft"|"tpot"|"batch"``, default
``batch``); junk values coerce to ``batch`` and are counted, never
minted into label space.
"""

from __future__ import annotations

import logging
from collections import deque
from typing import Dict, List, Optional

from ..common import SYSTEM_CLOCK, Clock

logger = logging.getLogger(__name__)

PHASES = ("queued", "prefill", "decode", "stalled", "handoff")
SLO_CLASSES = ("ttft", "tpot", "batch")
DEFAULT_SLO: str = "batch"

# Per-class latency targets (seconds) used for attainment accounting.
# ``batch`` has no latency target — a batch request attains its SLO by
# finishing at all. Values sit on histogram bucket bounds so fleet-side
# attainment (computed from merged cumulative buckets) agrees with the
# node-side ledger.
DEFAULT_SLO_TARGETS: Dict[str, Dict[str, float]] = {
    "ttft": {"ttft_s": 0.25},
    "tpot": {"tpot_s": 0.05},
    "batch": {},
}

DEFAULT_MAX_FINISHED = 512
DEFAULT_MAX_PENDING_HANDOFF = 256
DEFAULT_STEP_WINDOW = 256
DEFAULT_SAMPLE_WINDOW = 1024


def normalize_slo(slo: Optional[str]) -> str:
    """The effective SLO class for any caller-supplied annotation:
    unknown/absent values coerce to the default — label space is a
    fixed vocabulary, never caller input."""
    return slo if slo in SLO_CLASSES else DEFAULT_SLO


def _quantile(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank quantile on a sorted copy (same shape latency.py
    and the goodput ledger use — no interpolation surprises)."""
    if not values:
        return None
    vs = sorted(values)
    idx = min(len(vs) - 1, int(round(q * (len(vs) - 1))))
    return vs[idx]


class RequestRecord:
    """One request's partition. Lives in exactly one observatory's
    ``_live`` (or ``_pending_handoff``) set at a time; travels between
    observatories only through SharedKVPool publication."""

    __slots__ = (
        "uid", "slo", "owner", "engine_key", "start_ts", "phase",
        "phase_start", "phase_seconds", "first_token_ts",
        "last_token_ts", "tokens", "cached_tokens", "computed_tokens",
        "prefix_digest", "chain_digests", "kv_blocks", "kv_bytes",
        "finish_ts", "finish_reason", "stitched", "stall_resume",
    )

    def __init__(self, uid: int, slo: str, owner: "RequestObservatory",
                 engine_key: object, now: float) -> None:
        self.uid = uid
        self.slo = slo
        self.owner = owner
        self.engine_key = engine_key
        self.start_ts = now
        self.phase: Optional[str] = None
        self.phase_start = now
        self.phase_seconds: Dict[str, float] = {}
        self.first_token_ts: Optional[float] = None
        self.last_token_ts: Optional[float] = None
        self.tokens = 0
        self.cached_tokens = 0
        self.computed_tokens = 0
        self.prefix_digest = ""
        self.chain_digests: tuple = ()
        self.kv_blocks = 0
        self.kv_bytes = 0
        self.finish_ts: Optional[float] = None
        self.finish_reason: Optional[str] = None
        self.stitched = False
        self.stall_resume: Optional[str] = None

    # -- partition mechanics ------------------------------------------

    def transition(self, new_phase: Optional[str], now: float) -> None:
        """Close the open phase at ``now`` and open ``new_phase`` at the
        SAME timestamp — the shared boundary is what makes the
        partition gap-free by construction."""
        if self.phase is not None:
            dt = max(0.0, now - self.phase_start)
            self.phase_seconds[self.phase] = (
                self.phase_seconds.get(self.phase, 0.0) + dt
            )
        self.phase = new_phase
        self.phase_start = now

    @property
    def wall_s(self) -> Optional[float]:
        if self.finish_ts is None:
            return None
        return self.finish_ts - self.start_ts

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.start_ts

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean per-token decode interval; needs >= 2 tokens."""
        if (
            self.first_token_ts is None
            or self.last_token_ts is None
            or self.tokens < 2
        ):
            return None
        return (
            (self.last_token_ts - self.first_token_ts)
            / (self.tokens - 1)
        )

    @property
    def residual_s(self) -> Optional[float]:
        """wall - sum(phases). Defined so the conservation identity
        ``sum(phase_seconds) + residual == wall`` is EXACT; the
        invariant with teeth is that residual itself is ~0 (no gaps),
        which transition() guarantees and tests pin."""
        wall = self.wall_s
        if wall is None:
            return None
        return wall - sum(self.phase_seconds.values())

    def attained(self, targets: Dict[str, Dict[str, float]]) -> bool:
        tgt = targets.get(self.slo, {})
        if "ttft_s" in tgt:
            ttft = self.ttft_s
            return ttft is not None and ttft <= tgt["ttft_s"]
        if "tpot_s" in tgt:
            tpot = self.tpot_s
            # single-token requests have no inter-token interval to
            # miss with
            return tpot is None or tpot <= tgt["tpot_s"]
        return True  # batch: finishing is attaining

    def to_dict(self) -> dict:
        out = {
            "id": self.uid,
            "slo": self.slo,
            "phase": self.phase,
            "phases_ms": {
                k: round(v * 1000, 3)
                for k, v in self.phase_seconds.items()
            },
            "tokens": self.tokens,
            "cached_tokens": self.cached_tokens,
            "computed_tokens": self.computed_tokens,
            "prefix_digest": self.prefix_digest,
            "kv_blocks": self.kv_blocks,
            "kv_bytes": self.kv_bytes,
            "stitched": self.stitched,
        }
        for name, val in (
            ("wall_ms", self.wall_s),
            ("ttft_ms", self.ttft_s),
            ("tpot_ms", self.tpot_s),
            ("residual_ms", self.residual_s),
        ):
            out[name] = (
                round(val * 1000, 3) if val is not None else None
            )
        if self.finish_reason is not None:
            out["finish_reason"] = self.finish_reason
        return out


class RequestObservatory:
    """Per-request SLO ledger for one node's serving engines.

    One observatory serves any number of engines (pass the same
    instance to a disaggregated prefill/decode pair so stitched
    partitions live in one ledger). All timestamps come from the
    injected clock — ManualClock-driven tests control every duration.

    Memory is bounded everywhere: live records by engine slots + queue
    depth, finished records by ``max_finished``, pending handoffs by
    ``max_pending_handoff`` (overflow finishes oldest as
    ``handoff_expired`` — a publication nobody adopts must not leak),
    per-class/per-phase quantile samples and the step ring by fixed
    windows, and histogram labels by the fixed SLO/phase vocabularies.
    """

    def __init__(
        self,
        clock: Clock = SYSTEM_CLOCK,
        metrics=None,
        recorder=None,
        targets: Optional[Dict[str, Dict[str, float]]] = None,
        max_finished: int = DEFAULT_MAX_FINISHED,
        max_pending_handoff: int = DEFAULT_MAX_PENDING_HANDOFF,
        step_window: int = DEFAULT_STEP_WINDOW,
        sample_window: int = DEFAULT_SAMPLE_WINDOW,
    ) -> None:
        self._clock = clock
        self._metrics = metrics
        self.recorder = recorder
        self.targets = dict(DEFAULT_SLO_TARGETS)
        if targets:
            self.targets.update(targets)
        self._next_uid = 0
        self._live: Dict[int, RequestRecord] = {}
        self._pending_handoff: "Dict[int, RequestRecord]" = {}
        self._max_pending_handoff = max_pending_handoff
        self._finished: "deque[RequestRecord]" = deque(
            maxlen=max_finished
        )
        self.finished_total = 0
        self.slo_coerced = 0
        self.stitched_total = 0
        self.handoffs_published = 0
        self.handoffs_adopted = 0
        self.finish_reasons: Dict[str, int] = {}
        # per-class rolling samples for status() quantiles
        self._ttft_samples: Dict[str, deque] = {
            c: deque(maxlen=sample_window) for c in SLO_CLASSES
        }
        self._tpot_samples: Dict[str, deque] = {
            c: deque(maxlen=sample_window) for c in SLO_CLASSES
        }
        self._class_finished: Dict[str, int] = dict.fromkeys(
            SLO_CLASSES, 0
        )
        self._class_attained: Dict[str, int] = dict.fromkeys(
            SLO_CLASSES, 0
        )
        self._phase_samples: Dict[str, deque] = {
            p: deque(maxlen=sample_window) for p in PHASES
        }
        self._phase_totals: Dict[str, float] = dict.fromkeys(
            PHASES, 0.0
        )
        self._worst_residual_s = 0.0
        # per-engine stall nesting depth
        self._stall_depth: Dict[object, int] = {}
        # bounded per-step engine breakdown
        self._steps: "deque[dict]" = deque(maxlen=step_window)
        self.steps_total = 0
        self._step_acc = {
            "emitted_tokens": 0, "activated": 0, "evicted": 0,
            "prefill_s": 0.0, "decode_s": 0.0, "occupancy_sum": 0.0,
        }

    # -- wiring -------------------------------------------------------

    def bind_metrics(self, metrics) -> None:
        """Called by AgentMetrics.attach_requests: histograms are
        observed at source, gauges read at scrape."""
        self._metrics = metrics

    @property
    def clock(self) -> Clock:
        return self._clock

    @property
    def live_count(self) -> int:
        return len(self._live)

    @property
    def pending_handoff_count(self) -> int:
        return len(self._pending_handoff)

    # -- engine-facing lifecycle --------------------------------------

    def admit(self, engine_key: object, slo: Optional[str] = None) -> int:
        """A claim succeeded: mint an id, open the partition in
        ``queued``. Junk SLO annotations coerce to the default class —
        label space never grows with caller input."""
        if slo is None:
            slo = DEFAULT_SLO
        elif slo not in SLO_CLASSES:
            self.slo_coerced += 1
            slo = DEFAULT_SLO
        uid = self._next_uid
        self._next_uid += 1
        rec = RequestRecord(
            uid, slo, self, engine_key, self._clock.monotonic()
        )
        rec.transition("queued", rec.start_ts)
        self._live[uid] = rec
        return uid

    def prefill_start(self, uid: int) -> None:
        rec = self._live.get(uid)
        if rec is None or rec.phase == "prefill":
            return
        rec.transition("prefill", self._clock.monotonic())

    def prefill_done(
        self,
        uid: int,
        cached_tokens: int = 0,
        computed_tokens: int = 0,
        prefix_digest: str = "",
        chain_digests: tuple = (),
        kv_blocks: int = 0,
        kv_bytes: int = 0,
    ) -> None:
        """Attribution only (no phase change): cached vs computed
        prefill tokens and the block-chain digest. Accumulates, so a
        stitched request sums both roles' contributions."""
        rec = self._live.get(uid)
        if rec is None:
            return
        rec.cached_tokens += int(cached_tokens)
        rec.computed_tokens += int(computed_tokens)
        if prefix_digest:
            rec.prefix_digest = prefix_digest
        if chain_digests:
            rec.chain_digests = tuple(chain_digests)
        if kv_blocks:
            rec.kv_blocks = int(kv_blocks)
            rec.kv_bytes = int(kv_bytes)

    def first_token(self, uid: int) -> None:
        """Prefill produced the first emitted token: enter decode and
        stamp TTFT. For a stitched request this fires on the DECODE
        side, so TTFT spans prefill + handoff + tail prefill — the
        latency the client actually saw."""
        rec = self._live.get(uid)
        if rec is None:
            return
        now = self._clock.monotonic()
        rec.first_token_ts = now
        rec.last_token_ts = now
        rec.tokens = max(rec.tokens, 1)
        rec.transition("decode", now)
        depth = self._stall_depth.get(rec.engine_key, 0)
        if depth > 0:
            # born inside a stall window (its own synchronous prefill):
            # it decodes only once the window closes
            rec.stall_resume = "decode"
            rec.transition("stalled", now)

    def tokens_emitted(self, uid: int, n: int) -> None:
        rec = self._live.get(uid)
        if rec is None or n <= 0:
            return
        rec.tokens += int(n)
        rec.last_token_ts = self._clock.monotonic()

    # -- stall windows (unified-mode head-of-line) --------------------

    def stall_begin(self, engine_key: object) -> None:
        """A synchronous prefill is about to block this engine: every
        live decoding request on it stops making progress — attribute
        that time to ``stalled``, not ``decode``."""
        depth = self._stall_depth.get(engine_key, 0)
        self._stall_depth[engine_key] = depth + 1
        if depth > 0:
            return
        now = self._clock.monotonic()
        for rec in self._live.values():
            if rec.engine_key == engine_key and rec.phase == "decode":
                rec.stall_resume = "decode"
                rec.transition("stalled", now)

    def stall_end(self, engine_key: object) -> None:
        depth = self._stall_depth.get(engine_key, 0)
        if depth <= 0:
            return
        self._stall_depth[engine_key] = depth - 1
        if depth > 1:
            return
        now = self._clock.monotonic()
        for rec in self._live.values():
            if (
                rec.engine_key == engine_key
                and rec.phase == "stalled"
                and rec.stall_resume
            ):
                rec.transition(rec.stall_resume, now)
                rec.stall_resume = None

    # -- disaggregated stitching --------------------------------------

    def handoff_begin(self, uid: int) -> Optional[RequestRecord]:
        """Prefill role finished its half: the partition stays OPEN in
        ``handoff`` awaiting adoption. Returns the record for the
        engine to publish through SharedKVPool."""
        rec = self._live.pop(uid, None)
        if rec is None:
            return None
        rec.transition("handoff", self._clock.monotonic())
        self._pending_handoff[uid] = rec
        self.handoffs_published += 1
        while len(self._pending_handoff) > self._max_pending_handoff:
            # a publication nobody adopted: close it out rather than
            # leak an open partition forever
            stale_uid = next(iter(self._pending_handoff))
            self.finish(stale_uid, "handoff_expired")
        return rec

    def adopt(self, rec: RequestRecord, engine_key: object) -> int:
        """Decode role adopted a published record at the auto-cache
        hit: close the handoff phase, continue the SAME partition here.
        Works across observatory instances (the record migrates to the
        adopting ledger)."""
        rec.owner._pending_handoff.pop(rec.uid, None)
        rec.owner = self
        rec.engine_key = engine_key
        rec.stitched = True
        rec.transition("prefill", self._clock.monotonic())
        if rec.uid in self._live:  # defensive: uid collision across
            rec.uid = self._next_uid  # observatories — remint
            self._next_uid += 1
        self._next_uid = max(self._next_uid, rec.uid + 1)
        self._live[rec.uid] = rec
        self.handoffs_adopted += 1
        self.stitched_total += 1
        return rec.uid

    # -- finish -------------------------------------------------------

    def finish(
        self,
        uid: int,
        reason: str = "released",
        kv_blocks: Optional[int] = None,
        kv_bytes: Optional[int] = None,
    ) -> Optional[RequestRecord]:
        """Close the partition — the single exit for every path
        (release, stop token, max_len, pool eviction, drain, handoff
        expiry). Observes histograms, records ``request_finish``,
        rolls the record into the bounded ledgers."""
        rec = self._live.pop(uid, None)
        if rec is None:
            rec = self._pending_handoff.pop(uid, None)
        if rec is None:
            return None
        now = self._clock.monotonic()
        rec.transition(None, now)
        rec.finish_ts = now
        rec.finish_reason = reason
        if kv_blocks is not None:
            rec.kv_blocks = int(kv_blocks)
        if kv_bytes is not None:
            rec.kv_bytes = int(kv_bytes)
        self._finished.append(rec)
        self.finished_total += 1
        self.finish_reasons[reason] = (
            self.finish_reasons.get(reason, 0) + 1
        )
        residual = rec.residual_s or 0.0
        if abs(residual) > abs(self._worst_residual_s):
            self._worst_residual_s = residual
        ttft = rec.ttft_s
        tpot = rec.tpot_s
        self._class_finished[rec.slo] += 1
        if rec.attained(self.targets):
            self._class_attained[rec.slo] += 1
        if ttft is not None:
            self._ttft_samples[rec.slo].append(ttft)
        if tpot is not None:
            self._tpot_samples[rec.slo].append(tpot)
        for phase, secs in rec.phase_seconds.items():
            if phase in self._phase_samples:
                self._phase_samples[phase].append(secs)
                self._phase_totals[phase] += secs
        self._observe_metrics(rec, ttft, tpot)
        self._record_finish(rec, ttft, tpot)
        return rec

    def _observe_metrics(self, rec, ttft, tpot) -> None:
        m = self._metrics
        if m is None:
            return
        try:
            if ttft is not None:
                m.request_ttft.labels(slo=rec.slo).observe(ttft)
            if tpot is not None:
                m.request_tpot.labels(slo=rec.slo).observe(tpot)
            for phase, secs in rec.phase_seconds.items():
                m.request_phase_seconds.labels(
                    phase=phase
                ).observe(secs)
        except Exception:  # noqa: BLE001 - metrics never break serving
            logger.debug("request metrics observe failed", exc_info=True)

    def _record_finish(self, rec, ttft, tpot) -> None:
        if self.recorder is None:
            return
        try:
            self.recorder.record(
                "request_finish",
                request_id=rec.uid,
                slo=rec.slo,
                reason=rec.finish_reason,
                wall_ms=round((rec.wall_s or 0.0) * 1000, 3),
                ttft_ms=(
                    round(ttft * 1000, 3) if ttft is not None else None
                ),
                tpot_ms=(
                    round(tpot * 1000, 3) if tpot is not None else None
                ),
                tokens=rec.tokens,
                cached_tokens=rec.cached_tokens,
                computed_tokens=rec.computed_tokens,
                prefix_digest=rec.prefix_digest,
                kv_bytes=rec.kv_bytes,
                stitched=rec.stitched,
                phases_ms={
                    k: round(v * 1000, 3)
                    for k, v in rec.phase_seconds.items()
                },
            )
        except Exception:  # noqa: BLE001 - telemetry, best-effort
            logger.debug("request_finish record failed", exc_info=True)

    # -- per-step engine breakdown ------------------------------------

    def step(
        self,
        engine_key: object,
        live: int = 0,
        slots: int = 0,
        pending: int = 0,
        activated: int = 0,
        evicted: int = 0,
        emitted_tokens: int = 0,
        prefill_s: float = 0.0,
        decode_s: float = 0.0,
    ) -> None:
        occupancy = (live / slots) if slots else 0.0
        self._steps.append({
            "engine": str(engine_key),
            "live": live,
            "slots": slots,
            "pending": pending,
            "occupancy": round(occupancy, 4),
            "activated": activated,
            "evicted": evicted,
            "emitted_tokens": emitted_tokens,
            "prefill_ms": round(prefill_s * 1000, 3),
            "decode_ms": round(decode_s * 1000, 3),
        })
        self.steps_total += 1
        acc = self._step_acc
        acc["emitted_tokens"] += emitted_tokens
        acc["activated"] += activated
        acc["evicted"] += evicted
        acc["prefill_s"] += prefill_s
        acc["decode_s"] += decode_s
        acc["occupancy_sum"] += occupancy

    # -- reading ------------------------------------------------------

    def attainment(self, slo: str) -> Optional[float]:
        n = self._class_finished.get(slo, 0)
        if not n:
            return None
        return self._class_attained[slo] / n

    def status(
        self,
        request_id: Optional[int] = None,
        slo: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> dict:
        classes = {}
        for c in SLO_CLASSES:
            n = self._class_finished[c]
            if not n and not self._ttft_samples[c]:
                continue
            att = self.attainment(c)
            classes[c] = {
                "finished": n,
                "attained": self._class_attained[c],
                "attainment": (
                    round(att, 4) if att is not None else None
                ),
                "ttft_p50_ms": _ms(
                    _quantile(list(self._ttft_samples[c]), 0.5)
                ),
                "ttft_p99_ms": _ms(
                    _quantile(list(self._ttft_samples[c]), 0.99)
                ),
                "tpot_p50_ms": _ms(
                    _quantile(list(self._tpot_samples[c]), 0.5)
                ),
                "tpot_p99_ms": _ms(
                    _quantile(list(self._tpot_samples[c]), 0.99)
                ),
            }
        phase_total = sum(self._phase_totals.values())
        phases = {}
        for p in PHASES:
            samples = list(self._phase_samples[p])
            if not samples:
                continue
            phases[p] = {
                "count": len(samples),
                "p50_ms": _ms(_quantile(samples, 0.5)),
                "p99_ms": _ms(_quantile(samples, 0.99)),
                "share": (
                    round(self._phase_totals[p] / phase_total, 4)
                    if phase_total > 0 else 0.0
                ),
            }
        acc = self._step_acc
        compute = acc["prefill_s"] + acc["decode_s"]
        steps = {
            "count": self.steps_total,
            "occupancy_mean": (
                round(acc["occupancy_sum"] / self.steps_total, 4)
                if self.steps_total else None
            ),
            "admissions": acc["activated"],
            "evictions": acc["evicted"],
            "emitted_tokens": acc["emitted_tokens"],
            "prefill_share": (
                round(acc["prefill_s"] / compute, 4)
                if compute > 0 else None
            ),
            "decode_share": (
                round(acc["decode_s"] / compute, 4)
                if compute > 0 else None
            ),
            "recent": list(self._steps)[-8:],
        }
        recent: List[dict] = []
        pool = list(self._finished)[::-1]  # newest first
        live = [
            r for r in list(self._live.values())
            + list(self._pending_handoff.values())
        ]
        for rec in live + pool:
            if request_id is not None and rec.uid != request_id:
                continue
            if slo is not None and rec.slo != slo:
                continue
            recent.append(rec.to_dict())
            if limit is not None and len(recent) >= limit:
                break
        out = {
            "requests_total": self._next_uid,
            "live": len(self._live),
            "pending_handoff": len(self._pending_handoff),
            "finished": self.finished_total,
            "stitched": self.stitched_total,
            "handoffs_published": self.handoffs_published,
            "handoffs_adopted": self.handoffs_adopted,
            "slo_coerced": self.slo_coerced,
            "finish_reasons": dict(self.finish_reasons),
            "targets": {
                c: dict(t) for c, t in self.targets.items()
            },
            "classes": classes,
            "phases": phases,
            "conservation": {
                "checked": self.finished_total,
                "worst_residual_ms": round(
                    self._worst_residual_s * 1000, 6
                ),
            },
            "steps": steps,
            "requests": recent,
        }
        if self.recorder is not None and self.recorder.trace_id:
            out["trace_id"] = self.recorder.trace_id
        return out


def _ms(seconds: Optional[float]) -> Optional[float]:
    return round(seconds * 1000, 3) if seconds is not None else None
