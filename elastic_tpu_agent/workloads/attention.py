"""Fused causal attention for TPU — Pallas flash-attention kernels.

The reference repo has no model/kernel code at all (SURVEY.md §2: it is a
k8s node agent); this module is part of the TPU-native *workload* stack
that makes the agent's graded configs measurable. Design is TPU-first:

- Flash attention (online softmax) as Pallas kernels: the s×s score
  matrix never touches HBM, so long sequences fit in VMEM-sized tiles
  and the HBM traffic drops from O(s²) to O(s·h) per head.
- MXU-shaped tiles: block_q × head_dim and block_k × head_dim blocks
  with head_dim a multiple of 128 (lane width), block sizes multiples
  of the bf16 sublane tile.
- Custom VJP: the backward pass recomputes scores from (q, k, lse) in
  two more Pallas kernels (dkdv, dq) instead of saving probabilities.
- `reference_attention` is the plain einsum path (used on CPU, for
  unaligned shapes, and as the numerical oracle in tests).

All kernels run in interpret mode on CPU for hermetic CI.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp/where NaN-free


@dataclasses.dataclass(frozen=True)
class FlashConfig:
    """Static kernel parameters (hashable: used as a nondiff argnum)."""

    causal: bool = True
    block_q: int = 256
    block_k: int = 256
    sm_scale: Optional[float] = None  # default 1/sqrt(head_dim)
    interpret: bool = False  # run kernels interpreted (CPU/testing)
    # Sliding-window attention: each query attends only the last
    # ``window`` positions (0 = unlimited). Requires causal. The kernels
    # skip kv blocks entirely outside the window, so compute per query
    # is O(window·h) regardless of sequence length.
    window: int = 0


def supports_flash(seq: int, head_dim: int, cfg: FlashConfig) -> bool:
    """Shape gate: tiles must divide evenly and fill MXU lanes."""
    return (
        seq % cfg.block_q == 0
        and seq % cfg.block_k == 0
        and head_dim % 128 == 0
    )


def auto_flash_config(seq: int, interpret: bool = False) -> FlashConfig:
    """Largest square block that tiles ``seq``. Measured on v5e-1
    ([16,1024,8,128] fwd+bwd): 512-blocks 4.75 ms vs 256-blocks 5.17 ms
    vs materialized-scores 6.44 ms — bigger tiles amortize the online-
    softmax bookkeeping; equal q/k blocks keep the causal fast path
    (kernel skips kv blocks above the diagonal)."""
    for blk in (512, 256, 128):
        if seq % blk == 0:
            return FlashConfig(block_q=blk, block_k=blk, interpret=interpret)
    return FlashConfig(interpret=interpret)  # supports_flash will reject


# -- reference (oracle / fallback) path ---------------------------------------


def reference_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
    sm_scale: Optional[float] = None, window: int = 0,
) -> jax.Array:
    """Plain materialized-scores attention. [b, s, n, h] → [b, s, n, h].
    ``window`` > 0 limits each query to the last ``window`` positions."""
    scale = sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("bsnh,btnh->bnst", q, k) * scale
    s, t = logits.shape[-2], logits.shape[-1]
    if causal:
        mask = jnp.tril(jnp.ones((s, t), dtype=bool))
        if window > 0:
            rows = jnp.arange(s)[:, None]
            cols = jnp.arange(t)[None, :]
            mask &= rows - cols < window
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum(
        "bnst,btnh->bsnh", probs.astype(v.dtype), v
    )


# -- forward kernel -----------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, cfg: FlashConfig,
                n_kv_blocks: int, scale: float):
    """One (batch·head, q-block) grid cell: online-softmax over kv blocks."""
    bq = q_ref.shape[1]
    bk = cfg.block_k
    h = q_ref.shape[2]
    qi = pl.program_id(1)
    q = q_ref[0]  # [bq, h]

    def body(j, carry):
        m, l, acc = carry
        kj = k_ref[0, pl.ds(j * bk, bk), :]  # [bk, h]
        vj = v_ref[0, pl.ds(j * bk, bk), :]
        s_ij = jax.lax.dot_general(
            q, kj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if cfg.causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = rows >= cols
            if cfg.window > 0:
                keep &= rows - cols < cfg.window
            s_ij = jnp.where(keep, s_ij, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s_ij, axis=-1, keepdims=True))
        p = jnp.exp(s_ij - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(vj.dtype), vj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc * corr + pv

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, h), jnp.float32)
    lower = 0
    if cfg.causal and cfg.block_q == cfg.block_k:
        # q block i only ever sees kv blocks 0..i
        upper = qi + 1
        if cfg.window > 0:
            # earliest visible column is row_min - window + 1
            lower = jnp.maximum(0, (qi * bq - cfg.window + 1) // bk)
    else:
        upper = n_kv_blocks
    m, l, acc = jax.lax.fori_loop(lower, upper, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)  # fully-masked rows: avoid 0/0
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)


def _flash_fwd(
    q: jax.Array, k: jax.Array, v: jax.Array, cfg: FlashConfig
) -> Tuple[jax.Array, jax.Array]:
    """q,k,v: [bn, s, h] → (o [bn, s, h], lse [bn, s] f32)."""
    bn, s, h = q.shape
    nq = s // cfg.block_q
    nk = s // cfg.block_k
    scale = (
        cfg.sm_scale if cfg.sm_scale is not None else 1.0 / np.sqrt(h)
    )
    kernel = functools.partial(
        _fwd_kernel, cfg=cfg, n_kv_blocks=nk, scale=scale
    )
    return pl.pallas_call(
        kernel,
        grid=(bn, nq),
        in_specs=[
            pl.BlockSpec((1, cfg.block_q, h), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, h), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, h), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, cfg.block_q, h), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, cfg.block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, s, h), q.dtype),
            jax.ShapeDtypeStruct((bn, s, 1), jnp.float32),
        ],
        interpret=cfg.interpret,
    )(q, k, v)


# -- backward kernels ---------------------------------------------------------


def _dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, *, cfg: FlashConfig, n_q_blocks: int,
                 scale: float):
    """One (batch·head, kv-block) cell: accumulate dK,dV over q blocks."""
    bk = k_ref.shape[1]
    bq = cfg.block_q
    h = k_ref.shape[2]
    kj = pl.program_id(1)
    kblk = k_ref[0]  # [bk, h]
    vblk = v_ref[0]

    def body(i, carry):
        dk, dv = carry
        qi = q_ref[0, pl.ds(i * bq, bq), :]  # [bq, h]
        doi = do_ref[0, pl.ds(i * bq, bq), :]
        lsei = lse_ref[0, pl.ds(i * bq, bq), 0]  # [bq]
        deltai = delta_ref[0, pl.ds(i * bq, bq), 0]
        s_ij = jax.lax.dot_general(
            qi, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if cfg.causal:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = rows >= cols
            if cfg.window > 0:
                keep &= rows - cols < cfg.window
            s_ij = jnp.where(keep, s_ij, NEG_INF)
        p = jnp.exp(s_ij - lsei[:, None])  # [bq, bk]
        dv_new = dv + jax.lax.dot_general(
            p.astype(doi.dtype), doi, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, h]
        dp = jax.lax.dot_general(
            doi, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        ds = p * (dp - deltai[:, None]) * scale
        dk_new = dk + jax.lax.dot_general(
            ds.astype(qi.dtype), qi, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, h]
        return dk_new, dv_new

    upper = n_q_blocks
    if cfg.causal and cfg.block_q == cfg.block_k:
        lower = kj  # q blocks before the diagonal are fully masked
        if cfg.window > 0:
            # the last row that can see this kv block's first column is
            # kj*bk + window - 1 + (bk - 1); beyond it, fully masked
            last_row = (kj + 1) * bk + cfg.window - 2
            upper = jnp.minimum(n_q_blocks, last_row // bq + 1)
    else:
        lower = 0
    dk0 = jnp.zeros((bk, h), jnp.float32)
    dv0 = jnp.zeros((bk, h), jnp.float32)
    dk, dv = jax.lax.fori_loop(lower, upper, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               cfg: FlashConfig, n_kv_blocks: int, scale: float):
    """One (batch·head, q-block) cell: accumulate dQ over kv blocks."""
    bq = q_ref.shape[1]
    bk = cfg.block_k
    h = q_ref.shape[2]
    qi_idx = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0, :, 0]  # [bq]
    delta = delta_ref[0, :, 0]

    def body(j, dq):
        kj = k_ref[0, pl.ds(j * bk, bk), :]
        vj = v_ref[0, pl.ds(j * bk, bk), :]
        s_ij = jax.lax.dot_general(
            q, kj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if cfg.causal:
            rows = qi_idx * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0
            )
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            keep = rows >= cols
            if cfg.window > 0:
                keep &= rows - cols < cfg.window
            s_ij = jnp.where(keep, s_ij, NEG_INF)
        p = jnp.exp(s_ij - lse[:, None])
        dp = jax.lax.dot_general(
            do, vj, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds.astype(kj.dtype), kj, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    lower = 0
    if cfg.causal and cfg.block_q == cfg.block_k:
        upper = qi_idx + 1
        if cfg.window > 0:
            lower = jnp.maximum(0, (qi_idx * bq - cfg.window + 1) // bk)
    else:
        upper = n_kv_blocks
    dq0 = jnp.zeros((bq, h), jnp.float32)
    dq = jax.lax.fori_loop(lower, upper, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, cfg: FlashConfig, dlse=None):
    bn, s, h = q.shape
    nq = s // cfg.block_q
    nk = s // cfg.block_k
    scale = (
        cfg.sm_scale if cfg.sm_scale is not None else 1.0 / np.sqrt(h)
    )
    # delta_i = rowsum(dO ⊙ O): cheap elementwise — let XLA fuse it.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
        keepdims=True,
    )  # [bn, s, 1]
    if dlse is not None:
        # lse is a *returned* output (ring attention's merge consumes it):
        # d loss/d s_ij gains the term p_ij·dlse_i on top of the usual
        # p_ij·(dp_ij − delta_i), so folding −dlse into delta routes the
        # whole thing through the existing kernels unchanged.
        delta = delta - dlse.astype(jnp.float32).reshape(bn, s, 1)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkdv_kernel, cfg=cfg, n_q_blocks=nq, scale=scale
        ),
        grid=(bn, nk),
        in_specs=[
            pl.BlockSpec((1, s, h), lambda b, j: (b, 0, 0)),  # q
            pl.BlockSpec((1, cfg.block_k, h), lambda b, j: (b, j, 0)),  # k
            pl.BlockSpec((1, cfg.block_k, h), lambda b, j: (b, j, 0)),  # v
            pl.BlockSpec((1, s, h), lambda b, j: (b, 0, 0)),  # do
            pl.BlockSpec((1, s, 1), lambda b, j: (b, 0, 0)),  # lse
            pl.BlockSpec((1, s, 1), lambda b, j: (b, 0, 0)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, cfg.block_k, h), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, cfg.block_k, h), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, s, h), k.dtype),
            jax.ShapeDtypeStruct((bn, s, h), v.dtype),
        ],
        interpret=cfg.interpret,
    )(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, cfg=cfg, n_kv_blocks=nk, scale=scale
        ),
        grid=(bn, nq),
        in_specs=[
            pl.BlockSpec((1, cfg.block_q, h), lambda b, i: (b, i, 0)),  # q
            pl.BlockSpec((1, s, h), lambda b, i: (b, 0, 0)),  # k
            pl.BlockSpec((1, s, h), lambda b, i: (b, 0, 0)),  # v
            pl.BlockSpec((1, cfg.block_q, h), lambda b, i: (b, i, 0)),  # do
            pl.BlockSpec((1, cfg.block_q, 1), lambda b, i: (b, i, 0)),  # lse
            pl.BlockSpec((1, cfg.block_q, 1), lambda b, i: (b, i, 0)),  # delta
        ],
        out_specs=pl.BlockSpec(
            (1, cfg.block_q, h), lambda b, i: (b, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((bn, s, h), q.dtype),
        interpret=cfg.interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# -- public op ----------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_attention_bnsh(q, k, v, cfg: FlashConfig):
    o, _ = _flash_fwd(q, k, v, cfg)
    return o


def _flash_attention_fwd_rule(q, k, v, cfg: FlashConfig):
    o, lse = _flash_fwd(q, k, v, cfg)
    return o, (q, k, v, o, lse)


def _flash_attention_bwd_rule(cfg: FlashConfig, res, do):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, do, cfg)


_flash_attention_bnsh.defvjp(
    _flash_attention_fwd_rule, _flash_attention_bwd_rule
)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_attention_lse_bnsh(q, k, v, cfg: FlashConfig):
    """Like `_flash_attention_bnsh` but ALSO returns lse [bn, s] — the
    ring-attention building block, whose log-sum-exp merge needs each
    chunk's lse and therefore its gradient (handled via the delta fold in
    `_flash_bwd`)."""
    o, lse = _flash_fwd(q, k, v, cfg)
    return o, lse[..., 0]


def _flash_attention_lse_fwd_rule(q, k, v, cfg: FlashConfig):
    o, lse = _flash_fwd(q, k, v, cfg)
    return (o, lse[..., 0]), (q, k, v, o, lse)


def _flash_attention_lse_bwd_rule(cfg: FlashConfig, res, cotangents):
    q, k, v, o, lse = res
    do, dlse = cotangents
    return _flash_bwd(q, k, v, o, lse, do, cfg, dlse=dlse)


_flash_attention_lse_bnsh.defvjp(
    _flash_attention_lse_fwd_rule, _flash_attention_lse_bwd_rule
)


def flash_attention_with_lse(
    q: jax.Array, k: jax.Array, v: jax.Array,
    cfg: FlashConfig = FlashConfig(),
) -> Tuple[jax.Array, jax.Array]:
    """Flash attention returning (o [b,s,n,h], lse [b,n,s]) — the shapes
    ring_attention's online-softmax merge consumes. Requires the flash
    shape gate (callers dispatch; no silent fallback here)."""
    b, s, n, h = q.shape

    def to_bnsh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * n, s, h)

    o, lse = _flash_attention_lse_bnsh(
        to_bnsh(q), to_bnsh(k), to_bnsh(v), cfg
    )
    return (
        o.reshape(b, n, s, h).transpose(0, 2, 1, 3),
        lse.reshape(b, n, s),
    )


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    cfg: FlashConfig = FlashConfig(),
) -> jax.Array:
    """Causal flash attention. [b, s, n, h] → [b, s, n, h].

    Falls back to `reference_attention` when the shape gate fails (tile
    misalignment) so callers never need their own dispatch.
    """
    b, s, n, h = q.shape
    if cfg.window > 0:
        assert cfg.causal, "sliding-window attention requires causal"
    if not supports_flash(s, h, cfg):
        return reference_attention(
            q, k, v, causal=cfg.causal, sm_scale=cfg.sm_scale,
            window=cfg.window,
        )
    def to_bnsh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * n, s, h)
    o = _flash_attention_bnsh(to_bnsh(q), to_bnsh(k), to_bnsh(v), cfg)
    return o.reshape(b, n, s, h).transpose(0, 2, 1, 3)
