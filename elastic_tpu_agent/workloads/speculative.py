"""Speculative decoding: a small draft model proposes, the target
verifies — decode latency drops toward the draft's, output quality
stays the target's.

TPU-first shape discipline (everything under ONE jit):

- The draft proposes ``gamma`` tokens with its own KV cache (a scan of
  single-token steps); the target then scores all ``gamma + 1``
  positions in ONE chunked forward — MXU-shaped verification instead
  of gamma sequential target steps. That one-chunk-verify is the whole
  speedup.
- Acceptance length varies per round, so generation runs in a
  ``lax.while_loop`` over STATIC-shape state: a padded output buffer
  written with ``dynamic_update_slice`` at a traced cursor, and both
  KV caches "rolled back" by resetting their length scalar only —
  entries past the accepted point are stale but unreachable (attention
  masks by position) and are overwritten by the next round's writes at
  the same slots.
- Greedy mode is EXACT: the emitted stream equals target-only greedy
  decoding token for token (pinned by tests). Sampling mode implements
  the Leviathan accept/reject rule: accept draft token i with
  probability min(1, p_i/q_i), on first rejection resample from
  ``normalize(max(p - q, 0))``, and when all gamma survive, sample the
  bonus token from the target's last-position distribution — the
  output distribution equals target-only sampling.

Batch is 1 (asserted): per-row acceptance lengths would need per-row
cache positions; the latency story speculative decoding exists for is
the interactive single-stream case.

No reference counterpart (the reference agent has no model code);
part of the TPU workload stack like generate.py.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .generate import KVCache, _forward_chunk
from .transformer import ModelConfig


class SpecStats(NamedTuple):
    """rounds: verify rounds run; drafted: gamma * rounds proposed;
    accepted: drafted tokens that survived verification."""

    rounds: jax.Array
    drafted: jax.Array
    accepted: jax.Array

    def stats(self) -> dict:
        """Host-side observability summary (ServingEngine.stats()'s
        'speculative' block uses the same shape): accepted/rejected
        split plus the acceptance rate the spec gauges export."""
        rounds = int(self.rounds)
        drafted = int(self.drafted)
        accepted = int(self.accepted)
        return {
            "rounds": rounds,
            "drafted_tokens": drafted,
            "accepted_tokens": accepted,
            "rejected_tokens": drafted - accepted,
            "acceptance_rate": (
                round(accepted / drafted, 4) if drafted else None
            ),
        }


def speculative_generate(
    params: Dict,
    draft_params: Dict,
    cfg: ModelConfig,
    draft_cfg: ModelConfig,
    prompt: jax.Array,
    max_new_tokens: int,
    gamma: int = 4,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
    max_len: Optional[int] = None,
) -> Tuple[jax.Array, SpecStats]:
    """prompt [1, p] -> ([1, p + max_new_tokens], SpecStats).

    Greedy when temperature == 0 (exact match with generate()); else
    speculative sampling (target-distribution-preserving). The two
    configs must share the vocab; the draft is typically a narrower /
    shallower model.
    """
    assert prompt.shape[0] == 1, "speculative decode is single-stream"
    assert cfg.vocab == draft_cfg.vocab, "vocabularies must match"
    assert cfg.moe_experts == 0 and draft_cfg.moe_experts == 0, (
        "speculative decode supports dense models"
    )
    b, p = prompt.shape
    total = p + max_new_tokens
    # every round may write up to gamma+1 tokens past the cursor; pad
    # the buffer so the final round's overshoot never wraps
    buf_len = total + gamma + 1
    max_len = max_len or buf_len
    assert max_len >= buf_len, (max_len, buf_len)
    if cfg.pos == "learned":
        assert cfg.max_seq >= max_len
    if draft_cfg.pos == "learned":
        assert draft_cfg.max_seq >= max_len
    if key is None:
        key = jax.random.key(0)
    if max_new_tokens == 0:
        return prompt, SpecStats(
            jnp.int32(0), jnp.int32(0), jnp.int32(0)
        )

    run = _build_spec_run(
        cfg, draft_cfg, p, max_new_tokens, gamma, temperature, max_len
    )
    return run(params, draft_params, prompt, key)


def _sample_from_probs(probs, key):
    return jax.random.categorical(
        key, jnp.log(jnp.maximum(probs, 1e-30)), axis=-1
    ).astype(jnp.int32)


@functools.lru_cache(maxsize=32)
def _build_spec_run(
    cfg: ModelConfig, draft_cfg: ModelConfig, p: int,
    max_new_tokens: int, gamma: int, temperature: float, max_len: int,
):
    total = p + max_new_tokens
    buf_len = total + gamma + 1
    greedy = temperature == 0.0

    def probs_of(logits):
        if greedy:
            # one-hot argmax: the same accept/resample algebra then
            # reduces to exact greedy matching
            return jax.nn.one_hot(
                jnp.argmax(logits, axis=-1), cfg.vocab, dtype=jnp.float32
            )
        return jax.nn.softmax(logits / temperature, axis=-1)

    @jax.jit
    def run(params, draft_params, prompt, key):
        tcache = KVCache.empty(cfg, 1, max_len)
        dcache = KVCache.empty(draft_cfg, 1, max_len)

        # prefill BOTH models on the prompt; the target's last-position
        # distribution seeds the emitted stream
        tlogits, tcache = _forward_chunk(params, prompt, tcache, cfg)
        _, dcache = _forward_chunk(
            draft_params, prompt, dcache, draft_cfg
        )
        key, sub = jax.random.split(key)
        first = _sample_from_probs(probs_of(tlogits[:, -1]), sub)[0]

        buf = jnp.zeros((buf_len,), jnp.int32)
        buf = jax.lax.dynamic_update_slice(
            buf, prompt[0].astype(jnp.int32), (0,)
        )
        buf = buf.at[p].set(first)

        # cursor: index of the NEXT slot to fill; buf[p..cursor) is
        # committed output. last committed token = buf[cursor-1].
        state = dict(
            buf=buf,
            cursor=jnp.int32(p + 1),
            tcache=tcache,
            dcache=dcache,
            key=key,
            rounds=jnp.int32(0),
            accepted=jnp.int32(0),
        )

        def cond(s):
            return s["cursor"] < total

        def body(s):
            key = s["key"]
            last = jax.lax.dynamic_slice(s["buf"], (s["cursor"] - 1,), (1,))

            # -- draft proposes gamma tokens (sequential, cheap) -----
            def draft_step(carry, _):
                dcache, tok, key = carry
                key, sub = jax.random.split(key)
                logits, dcache = _forward_chunk(
                    draft_params, tok[None], dcache, draft_cfg
                )
                q = probs_of(logits[:, -1])[0]
                nxt = _sample_from_probs(q[None], sub)[0:1]
                return (dcache, nxt, key), (nxt[0], q)

            key, dkey = jax.random.split(key)
            (dcache, _, _), (draft_toks, draft_q) = jax.lax.scan(
                draft_step, (s["dcache"], last, dkey), None, length=gamma
            )
            # the scan cached K/V for [last, d_1..d_{gamma-1}] but never
            # fed d_gamma; when all gamma survive verification the next
            # round needs d_gamma's cache entry, so feed it now (logits
            # discarded; on partial acceptance the entry is past the
            # rolled-back length and harmlessly stale)
            _, dcache = _forward_chunk(
                draft_params, draft_toks[gamma - 1][None, None],
                dcache, draft_cfg,
            )

            # -- target verifies all gamma+1 positions in ONE chunk --
            chunk = jnp.concatenate([last, draft_toks])[None]  # [1, g+1]
            tlogits, tcache = _forward_chunk(
                params, chunk, s["tcache"], cfg
            )
            target_p = probs_of(tlogits[0])  # [g+1, vocab]

            # -- accept/reject (Leviathan); greedy reduces to match --
            p_i = jax.vmap(lambda pr, t: pr[t])(
                target_p[:gamma], draft_toks
            )
            q_i = jax.vmap(lambda qr, t: qr[t])(draft_q, draft_toks)
            key, ukey = jax.random.split(key)
            u = jax.random.uniform(ukey, (gamma,))
            ok = u < jnp.minimum(1.0, p_i / jnp.maximum(q_i, 1e-30))
            # longest accepted PREFIX: a rejection cuts everything after
            n_acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32)))

            # correction token: resample from (p - q)+ at the first
            # rejected position, or the bonus distribution after a
            # full acceptance
            all_ok = n_acc == gamma
            resid = jnp.maximum(
                target_p[jnp.minimum(n_acc, gamma - 1)]
                - draft_q[jnp.minimum(n_acc, gamma - 1)],
                0.0,
            )
            resid_sum = jnp.sum(resid)
            # degenerate p == q: residual is empty; fall back to p
            resid = jnp.where(
                resid_sum > 0,
                resid / jnp.maximum(resid_sum, 1e-30),
                target_p[jnp.minimum(n_acc, gamma - 1)],
            )
            correction_dist = jnp.where(
                all_ok, target_p[gamma], resid
            )
            key, ckey = jax.random.split(key)
            correction = _sample_from_probs(correction_dist[None], ckey)[0]

            # -- commit: draft_toks[:n_acc] then the correction ------
            # slot i < n_acc takes d_{i+1}; every slot >= n_acc takes
            # the correction value — only slot n_acc of those is real,
            # the rest sit past the new cursor and are overwritten by
            # the next round or sliced off at the end
            emit = jnp.concatenate([draft_toks, correction[None]])
            shifted = jnp.where(
                jnp.arange(gamma + 1) < n_acc, emit, correction
            )
            buf = jax.lax.dynamic_update_slice(
                s["buf"], shifted, (s["cursor"],)
            )
            n_emit = n_acc + 1
            cursor = s["cursor"] + n_emit

            # -- roll caches back to the committed stream ------------
            # target consumed last + gamma drafts from cursor-1-n_emit
            # ... keep exactly the committed positions: the cache must
            # cover buf[0..cursor-1) as context; the NEXT round re-feeds
            # buf[cursor-1] as its chunk head.
            tcache = KVCache(
                k=tcache.k, v=tcache.v, length=cursor - 1
            )
            dcache = KVCache(
                k=dcache.k, v=dcache.v, length=cursor - 1
            )
            return dict(
                buf=buf,
                cursor=cursor,
                tcache=tcache,
                dcache=dcache,
                key=key,
                rounds=s["rounds"] + 1,
                # the final round may overshoot the requested budget
                # (its committed tokens are truncated to ``total``), so
                # only count accepted drafts that actually land in the
                # emitted stream — acceptance rate stays honest for
                # short generations
                accepted=s["accepted"] + jnp.minimum(
                    n_acc, total - s["cursor"]
                ),
            )

        s = jax.lax.while_loop(cond, body, state)
        stats = SpecStats(
            rounds=s["rounds"],
            drafted=s["rounds"] * gamma,
            accepted=s["accepted"],
        )
        return s["buf"][None, :total], stats

    return run
