"""Pipeline parallelism: GPipe-style microbatching over a "pp" mesh axis.

TPU-first design (the scaling-book recipe, not a port of anything):

- Stage weights are **stacked** with a leading [pp] dim and sharded over
  the "pp" axis, so each device holds exactly its stage's parameters.
- The schedule is a single differentiable ``lax.scan`` over
  ``n_micro + pp - 1`` ticks; at every tick each stage computes its local
  microbatch and hands its activation to the next stage with one
  ``lax.ppermute`` hop over ICI. Bubble fraction is the textbook
  ``(pp-1)/(n_micro+pp-1)``.
- Everything runs under ``jax.shard_map``: XLA sees static shapes, the
  ppermute lowers to neighbor ICI transfers, and reverse-mode AD gives
  the backward pipeline for free (ppermute transposes to the inverse
  permutation).
- A "dp" mesh axis composes orthogonally: microbatches are sharded over
  it, gradients all-reduce over it outside the shard_map like any GSPMD
  data-parallel program.

The reference repo has no parallelism code of any kind (SURVEY.md §2:
"Parallelism-strategy inventory: NONE present"); this module exists so
the agent's multi-host slices have a first-class pipeline workload, and
so every axis the framework claims (dp/sp/tp/ep/pp) is exercised by an
executable training step.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_pipeline_mesh(pp: int, dp: int = 1) -> Mesh:
    """2-axis ("pp", "dp") mesh over the first pp*dp visible devices."""
    devices = jax.devices()
    assert pp * dp <= len(devices), (
        f"need {pp * dp} devices, have {len(devices)}"
    )
    arr = np.array(devices[: pp * dp]).reshape(pp, dp)
    return Mesh(arr, axis_names=("pp", "dp"))


def pipeline_apply(
    mesh: Mesh,
    stage_fn: Callable,
    stacked_params,
    microbatches: jax.Array,
) -> jax.Array:
    """Run ``stage_fn`` as a pp-deep pipeline.

    stacked_params: pytree whose leaves have leading dim pp (stage i's
    weights at index i), sharded over "pp".
    microbatches: [n_micro, batch, ...]; batch is sharded over "dp".
    Returns [n_micro, batch, ...]: the last stage's outputs, in
    microbatch order.
    """
    pp = mesh.shape["pp"]

    def shard_body(params, xs):
        # Local views: params leaves [1, ...] (this stage), xs sharded on dp.
        params = jax.tree.map(lambda a: a[0], params)
        idx = lax.axis_index("pp")
        m = xs.shape[0]
        steps = m + pp - 1
        # stage i -> i+1 ring; the wraparound edge only carries drained
        # values stage 0 never reads (it ingests fresh microbatches).
        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            buf, outs = carry
            x_in = jnp.where(idx == 0, xs[jnp.minimum(t, m - 1)], buf)
            y = stage_fn(params, x_in)
            out_t = t - (pp - 1)
            ct = jnp.clip(out_t, 0, m - 1)
            outs = jnp.where(
                (idx == pp - 1) & (out_t >= 0), outs.at[ct].set(y), outs
            )
            buf = lax.ppermute(y, "pp", perm)
            return (buf, outs), None

        buf = jnp.zeros_like(xs[0])
        outs = jnp.zeros_like(xs)
        (buf, outs), _ = lax.scan(tick, (buf, outs), jnp.arange(steps))
        # Only the last stage holds real outputs; broadcast over "pp" so
        # the unsharded-out contract holds on every rank.
        outs = lax.psum(
            jnp.where(idx == pp - 1, outs, jnp.zeros_like(outs)), "pp"
        )
        return outs

    param_specs = jax.tree.map(lambda _: P("pp"), stacked_params)
    return jax.shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(param_specs, P(None, "dp")),
        out_specs=P(None, "dp"),
        check_vma=False,
    )(stacked_params, microbatches)


# -- a small pipelined model + train step (demo/dryrun/test vehicle) ----------


def init_stage_params(
    key: jax.Array, pp: int, d_model: int, d_ff: int
) -> Dict:
    """pp stacked residual gelu-MLP blocks: leaves carry leading [pp]."""
    k1, k2 = jax.random.split(key)
    init = jax.nn.initializers.normal(0.02)
    return {
        "w1": init(k1, (pp, d_model, d_ff), jnp.float32),
        "w2": init(k2, (pp, d_ff, d_model), jnp.float32),
    }


def stage_block(params: Dict, x: jax.Array) -> jax.Array:
    """One stage: residual MLP block in the input dtype."""
    h = jax.nn.gelu(jnp.einsum("bd,df->bf", x, params["w1"].astype(x.dtype)))
    return x + jnp.einsum("bf,fd->bd", h, params["w2"].astype(x.dtype))


def make_pipeline_train_step(
    mesh: Mesh, d_model: int, d_ff: int, learning_rate: float = 1e-2
):
    """Regression train step over the pipelined block stack:
    (params, opt_state, x [m,b,d], y [m,b,d]) -> (params, opt_state, loss).
    """
    pp = mesh.shape["pp"]
    optimizer = optax.adam(learning_rate)
    params_struct = jax.eval_shape(
        lambda k: init_stage_params(k, pp, d_model, d_ff), jax.random.key(0)
    )
    p_shard = jax.tree.map(
        lambda _: NamedSharding(mesh, P("pp", None, None)), params_struct
    )
    data_shard = NamedSharding(mesh, P(None, "dp", None))
    repl = NamedSharding(mesh, P())

    def loss_fn(params, x, y):
        out = pipeline_apply(mesh, stage_block, params, x)
        return jnp.mean(jnp.square(out - y))

    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    # Optimizer moments are param-shaped ([pp, ...]): shard them on "pp"
    # like the params; scalars (step count) replicate.
    opt_struct = jax.eval_shape(optimizer.init, params_struct)
    o_shard = jax.tree.map(
        lambda leaf: (
            NamedSharding(mesh, P("pp", None, None))
            if getattr(leaf, "ndim", 0) == 3 else repl
        ),
        opt_struct,
    )

    def init_all(key):
        params = jax.jit(
            lambda k: init_stage_params(k, pp, d_model, d_ff),
            out_shardings=p_shard,
        )(key)
        opt_state = jax.jit(optimizer.init, out_shardings=o_shard)(params)
        return params, opt_state

    train_step = jax.jit(
        step,
        in_shardings=(p_shard, o_shard, data_shard, data_shard),
        out_shardings=(p_shard, o_shard, repl),
        donate_argnums=(0, 1),
    )
    return train_step, init_all
