"""Ring attention: causal attention with the sequence axis sharded.

Long-context sequence/context parallelism for the workload stack. Each
device of the "sp" mesh axis holds one contiguous sequence shard of
q/k/v; k/v chunks rotate around the ring via `jax.lax.ppermute` (XLA
lowers it to ICI neighbor exchanges), and partial attention outputs are
merged with the online-softmax log-sum-exp rule. Peak memory per device
is O(s_local²) for one block-pair of scores instead of O(s²) — and the
k/v rotation overlaps with the block computation in XLA's schedule.

The reference repo has no sequence-parallel or attention code at all
(SURVEY.md §2 "Parallelism-strategy inventory: NONE"); this implements
the capability TPU-first rather than translating anything.

Differentiable end-to-end: the ring is a `lax.scan` of jnp ops +
`ppermute`, so JAX autodiff derives the backward ring (gradients rotate
the opposite way) without a custom VJP.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .attention import NEG_INF


def _block_attn(
    q: jax.Array, k: jax.Array, v: jax.Array,
    q_off: jax.Array, k_off: jax.Array,
    scale: float, causal: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Attention of a local q shard against one k/v chunk.

    q: [b, sq, n, h]; k,v: [b, sk, n, h]; offsets are the chunks' global
    sequence starts (traced scalars). Returns (o [b, sq, n, h] normalized
    within the chunk, lse [b, n, sq] f32).
    """
    sq, sk = q.shape[1], k.shape[1]
    logits = jnp.einsum("bsnh,btnh->bnst", q, k).astype(jnp.float32) * scale
    if causal:
        rows = q_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        cols = k_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        logits = jnp.where((rows >= cols)[None, None], logits, NEG_INF)
    lse = jax.nn.logsumexp(logits, axis=-1)  # [b, n, sq]
    probs = jnp.exp(logits - lse[..., None])
    o = jnp.einsum("bnst,btnh->bsnh", probs.astype(v.dtype), v)
    return o, lse


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Local view (call inside `jax.shard_map`): q/k/v are the sequence
    shards [b, s_local, n, h]; returns the local output shard."""
    import functools

    size = jax.lax.psum(1, axis_name)  # static axis size
    idx = jax.lax.axis_index(axis_name)
    s_loc = q.shape[1]
    scale = (
        sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    )
    perm = [(i, (i + 1) % size) for i in range(size)]
    # Checkpoint each block: scan autodiff would otherwise stack every
    # step's score/prob residuals — an O(s_loc·s) slab per device, which
    # is exactly what ring attention exists to avoid. Recomputing the
    # block in backward keeps peak memory at one block-pair.
    block = jax.checkpoint(
        functools.partial(_block_attn, scale=scale, causal=causal)
    )

    def merge(o, lse, o_b, lse_b):
        new_lse = jnp.logaddexp(lse, lse_b)
        w_old = jnp.exp(lse - new_lse)  # [b, n, sq]
        w_new = jnp.exp(lse_b - new_lse)
        # weights are [b, n, sq] but o is [b, sq, n, h]
        o = (
            o * w_old.transpose(0, 2, 1)[..., None]
            + o_b.astype(jnp.float32) * w_new.transpose(0, 2, 1)[..., None]
        )
        return o, new_lse

    # Step 0 (the local diagonal chunk) is peeled out of the scan so the
    # ring does exactly size-1 exchanges — a rotate after the last block
    # would ship a full k/v shard over ICI just to be discarded.
    o_b, lse_b = block(q, k, v, idx * s_loc, idx * s_loc)
    o0 = o_b.astype(jnp.float32)
    lse0 = lse_b

    def step(carry, t):
        o, lse, kt, vt = carry
        kt = jax.lax.ppermute(kt, axis_name, perm)
        vt = jax.lax.ppermute(vt, axis_name, perm)
        src = (idx - t) % size  # which shard kt/vt originally came from
        o_b, lse_b = block(q, kt, vt, idx * s_loc, src * s_loc)
        o, lse = merge(o, lse, o_b, lse_b)
        return (o, lse, kt, vt), None

    if size > 1:
        (o, _, _, _), _ = jax.lax.scan(
            step, (o0, lse0, k, v), jnp.arange(1, size)
        )
    else:
        o = o0
    return o.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array, k: jax.Array, v: jax.Array,
    mesh: jax.sharding.Mesh,
    causal: bool = True,
    sm_scale: Optional[float] = None,
) -> jax.Array:
    """Global view: q/k/v [b, s, n, h] with b on "dp", s on "sp", heads on
    "tp". Wraps `ring_attention` in shard_map over the full mesh."""
    from jax.sharding import PartitionSpec as P

    spec = P("dp", "sp", "tp", None)
    return jax.shard_map(
        lambda q, k, v: ring_attention(
            q, k, v, axis_name="sp", causal=causal, sm_scale=sm_scale
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
