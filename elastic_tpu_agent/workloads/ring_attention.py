"""Ring attention: causal attention with the sequence axis sharded.

Long-context sequence/context parallelism for the workload stack. Each
device of the "sp" mesh axis holds one contiguous sequence shard of
q/k/v; k/v chunks rotate around the ring via `jax.lax.ppermute` (XLA
lowers it to ICI neighbor exchanges), and partial attention outputs are
merged with the online-softmax log-sum-exp rule.

Inside each ring step the (q-shard × kv-chunk) block runs the Pallas
flash kernel (attention.py) whenever the shape gate passes, so NO
s_loc×s_loc score tensor is ever materialized — peak memory per device is
O(block_q·block_k) kernel tiles plus the rotating k/v shard, i.e. O(s·h)
per device overall. Because shards are contiguous and equal-sized, the
chunk-offset causal mask collapses to three block cases dispatched with
`lax.switch`:

  future chunk (k_off > q_off)  -> fully masked: skip the kernel entirely
  diagonal     (k_off == q_off) -> causal flash kernel (local tri mask)
  past chunk   (k_off < q_off)  -> non-causal flash kernel (no mask)

The einsum fallback (`_block_attn`) remains for unaligned shapes.

The reference repo has no sequence-parallel or attention code at all
(SURVEY.md §2 "Parallelism-strategy inventory: NONE"); this implements
the capability TPU-first rather than translating anything.

Differentiable end-to-end: the ring is a `lax.scan` of blocks +
`ppermute`; the flash block is a custom-VJP primitive that returns lse
and takes its cotangent (attention._flash_attention_lse_bnsh), so JAX
autodiff derives the backward ring without a hand-written outer VJP.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .attention import (
    NEG_INF,
    FlashConfig,
    auto_flash_config,
    flash_attention_with_lse,
    supports_flash,
)


def _block_attn(
    q: jax.Array, k: jax.Array, v: jax.Array,
    q_off: jax.Array, k_off: jax.Array,
    scale: float, causal: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Einsum fallback: attention of a local q shard against one k/v
    chunk, materializing the [sq, sk] score block (only used when the
    flash shape gate fails).

    q: [b, sq, n, h]; k,v: [b, sk, n, h]; offsets are the chunks' global
    sequence starts (traced scalars). Returns (o [b, sq, n, h] normalized
    within the chunk, lse [b, n, sq] f32).
    """
    sq, sk = q.shape[1], k.shape[1]
    logits = jnp.einsum("bsnh,btnh->bnst", q, k).astype(jnp.float32) * scale
    if causal:
        rows = q_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        cols = k_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        logits = jnp.where((rows >= cols)[None, None], logits, NEG_INF)
    lse = jax.nn.logsumexp(logits, axis=-1)  # [b, n, sq]
    probs = jnp.exp(logits - lse[..., None])
    o = jnp.einsum("bnst,btnh->bsnh", probs.astype(v.dtype), v)
    return o, lse


def _flash_block(
    q: jax.Array, k: jax.Array, v: jax.Array,
    q_off: jax.Array, k_off: jax.Array,
    cfg: FlashConfig, causal: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Flash-kernel block with the chunk-offset causal mask expressed as
    a three-way switch (see module docstring). Offsets are traced, so the
    case index is data-dependent — `lax.switch` compiles all three
    branches once and executes exactly one per ring step per device."""
    b, sq, n, h = q.shape

    def future(q, k, v):  # noqa: ARG001 - fully masked: no kernel at all
        return (
            jnp.zeros((b, sq, n, h), q.dtype),
            jnp.full((b, n, sq), NEG_INF, jnp.float32),
        )

    def diagonal(q, k, v):
        return flash_attention_with_lse(
            q, k, v, dataclasses.replace(cfg, causal=True)
        )

    def past(q, k, v):
        return flash_attention_with_lse(
            q, k, v, dataclasses.replace(cfg, causal=False)
        )

    if not causal:
        return past(q, k, v)
    case = (1 + jnp.sign(q_off - k_off)).astype(jnp.int32)
    return jax.lax.switch(case, [future, diagonal, past], q, k, v)


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    axis_name: str = "sp",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    flash: Union[FlashConfig, bool, None] = None,
) -> jax.Array:
    """Local view (call inside `jax.shard_map`): q/k/v are the sequence
    shards [b, s_local, n, h]; returns the local output shard.

    ``flash``: None = auto (Pallas kernels when the shape gate passes,
    interpret mode off-TPU); False = force the einsum fallback; or an
    explicit FlashConfig."""
    size = jax.lax.psum(1, axis_name)  # static axis size
    idx = jax.lax.axis_index(axis_name)
    s_loc = q.shape[1]
    scale = (
        sm_scale if sm_scale is not None else 1.0 / np.sqrt(q.shape[-1])
    )
    use_flash = False
    if flash is not False:
        interpret = jax.default_backend() != "tpu"
        cfg = (
            flash if isinstance(flash, FlashConfig)
            else auto_flash_config(s_loc, interpret=interpret)
        )
        if sm_scale is None and cfg.sm_scale is not None:
            scale = cfg.sm_scale  # einsum fallback must agree with it
        else:
            # an explicit sm_scale argument wins over the config's; fill
            # the config so both paths use the same value
            cfg = dataclasses.replace(cfg, sm_scale=scale)
        use_flash = supports_flash(s_loc, q.shape[-1], cfg)
    perm = [(i, (i + 1) % size) for i in range(size)]
    # Checkpoint each block: scan autodiff would otherwise stack every
    # step's residuals; recomputing the block in backward keeps peak
    # memory at one block-pair. (The flash kernel recomputes from lse
    # anyway; checkpoint also covers the einsum fallback.)
    if use_flash:
        block = jax.checkpoint(
            functools.partial(_flash_block, cfg=cfg, causal=causal)
        )
    else:
        block = jax.checkpoint(
            functools.partial(_block_attn, scale=scale, causal=causal)
        )

    def merge(o, lse, o_b, lse_b):
        new_lse = jnp.logaddexp(lse, lse_b)
        w_old = jnp.exp(lse - new_lse)  # [b, n, sq]
        w_new = jnp.exp(lse_b - new_lse)
        # weights are [b, n, sq] but o is [b, sq, n, h]
        o = (
            o * w_old.transpose(0, 2, 1)[..., None]
            + o_b.astype(jnp.float32) * w_new.transpose(0, 2, 1)[..., None]
        )
        return o, new_lse

    # Step 0 (the local diagonal chunk) is peeled out of the scan so the
    # ring does exactly size-1 exchanges — a rotate after the last block
    # would ship a full k/v shard over ICI just to be discarded.
    o_b, lse_b = block(q, k, v, idx * s_loc, idx * s_loc)
    o0 = o_b.astype(jnp.float32)
    lse0 = lse_b

    def step(carry, t):
        o, lse, kt, vt = carry
        kt = jax.lax.ppermute(kt, axis_name, perm)
        vt = jax.lax.ppermute(vt, axis_name, perm)
        src = (idx - t) % size  # which shard kt/vt originally came from
        o_b, lse_b = block(q, kt, vt, idx * s_loc, src * s_loc)
        o, lse = merge(o, lse, o_b, lse_b)
        return (o, lse, kt, vt), None

    if size > 1:
        (o, _, _, _), _ = jax.lax.scan(
            step, (o0, lse0, k, v), jnp.arange(1, size)
        )
    else:
        o = o0
    return o.astype(q.dtype)


def ring_attention_sharded(
    q: jax.Array, k: jax.Array, v: jax.Array,
    mesh: jax.sharding.Mesh,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    flash: Union[FlashConfig, bool, None] = None,
) -> jax.Array:
    """Global view: q/k/v [b, s, n, h] with b on "dp", s on "sp", heads on
    "tp". Wraps `ring_attention` in shard_map over the full mesh."""
    from jax.sharding import PartitionSpec as P

    spec = P("dp", "sp", "tp", None)
    return jax.shard_map(
        lambda q, k, v: ring_attention(
            q, k, v, axis_name="sp", causal=causal, sm_scale=sm_scale,
            flash=flash,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)
