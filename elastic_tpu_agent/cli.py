"""Agent process entry point.

Capability parity with ``cmd/main.go`` (SURVEY.md §1 L1): flags -> manager
-> run -> block on exit signals, with a SIGUSR1 stack-dump side channel.
The reference's broken default (-gpuPluginName=qgpu, unsupported by its own
factory) is not replicated: defaults here are runnable.

Usage:
    python -m elastic_tpu_agent.cli --node-name $NODE_NAME \
        --db-file /host/var/lib/elastic-tpu/meta.db --operator tpuvm
"""

from __future__ import annotations

import argparse
import logging
import sys
import threading

from .common import install_dump_signal, wait_for_exit_signal
from .manager import ManagerOptions, TPUManager


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="elastic-tpu-agent")
    p.add_argument("--node-name", default="", help="k8s node this agent runs on")
    p.add_argument(
        "--db-file",
        default="/host/var/lib/elastic-tpu/meta.db",
        help="checkpoint db path (hostPath-mounted to survive restarts)",
    )
    p.add_argument("--kubeconf", default="", help="kubeconfig path (default: in-cluster)")
    p.add_argument(
        "--plugin", default="tpushare", help="plugin kind (tpushare)"
    )
    p.add_argument(
        "--operator",
        default="tpuvm",
        help="device operator: tpuvm | stub | stub:<accel-type> | "
             "exclusive | exclusive:<inner> (whole-chip, no virtual nodes)",
    )
    p.add_argument("--dev-root", default="/host/dev", help="host /dev mount")
    p.add_argument(
        "--device-plugin-dir",
        default="/var/lib/kubelet/device-plugins",
        help="kubelet device-plugin socket dir",
    )
    p.add_argument(
        "--pod-resources-socket",
        default="/var/lib/kubelet/pod-resources/kubelet.sock",
        help="kubelet pod-resources socket",
    )
    p.add_argument(
        "--alloc-spec-dir",
        default="/host/var/lib/elastic-tpu/alloc",
        help="where allocation specs for the OCI hook are written",
    )
    p.add_argument(
        "--nri-socket", default="",
        help="containerd NRI socket; when set the agent registers as an "
             "NRI plugin and injects devices at CreateContainer "
             "(containerd/GKE activation; typical: /var/run/nri/nri.sock)",
    )
    p.add_argument(
        "--nri-libtpu", default="",
        help="host libtpu.so to bind-mount into TPU containers via NRI",
    )
    p.add_argument(
        "--nri-evict-on-chip-failure", action="store_true",
        help="policy: evict containers bound to a chip that goes "
             "unhealthy (via NRI UpdateContainers) so kubelet restarts "
             "them onto healthy chips",
    )
    p.add_argument("--metrics-port", type=int, default=9478,
                   help="observability HTTP port serving /metrics, "
                        "/debug/traces and /healthz (0 = off)")
    p.add_argument("--metrics-addr", default="127.0.0.1",
                   help="bind address for the observability endpoint "
                        "(default loopback; set 0.0.0.0 to allow "
                        "off-host Prometheus scrapes, as the shipped "
                        "DaemonSet does)")
    p.add_argument("--no-events", action="store_true",
                   help="disable k8s Event emission (e.g. RBAC without "
                        "events:create)")
    p.add_argument("--no-crd", action="store_true",
                   help="disable ElasticTPU CRD publication")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)
    if args.nri_evict_on_chip_failure and not args.nri_socket:
        p.error(
            "--nri-evict-on-chip-failure requires --nri-socket (evictions "
            "go through the NRI session)"
        )
    return args


def main(argv=None) -> int:
    args = parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s %(message)s",
        stream=sys.stderr,
    )
    install_dump_signal()

    metrics = None
    if args.metrics_port:
        from .metrics import AgentMetrics, MetricsServerError

        metrics = AgentMetrics()
        try:
            metrics.serve(args.metrics_port, addr=args.metrics_addr)
        except MetricsServerError as e:
            # A busy port must not take the allocation path down with it:
            # keep the agent (and its in-process metric objects, which
            # gauges/events still update) and run without the endpoint.
            logging.getLogger(__name__).error(
                "%s — continuing WITHOUT the observability endpoint", e
            )

    manager = TPUManager(
        ManagerOptions(
            node_name=args.node_name,
            db_path=args.db_file,
            kubeconfig=args.kubeconf,
            plugin_kind=args.plugin,
            operator_kind=args.operator,
            dev_root=args.dev_root,
            device_plugin_dir=args.device_plugin_dir,
            pod_resources_socket=args.pod_resources_socket,
            alloc_spec_dir=args.alloc_spec_dir,
            nri_socket=args.nri_socket,
            nri_libtpu=args.nri_libtpu,
            nri_evict_on_chip_failure=args.nri_evict_on_chip_failure,
            metrics=metrics,
            enable_events=not args.no_events,
            enable_crd=not args.no_crd,
        )
    )
    run_thread = threading.Thread(
        target=manager.run, kwargs={"block": True}, daemon=True, name="manager"
    )
    run_thread.start()
    sig = wait_for_exit_signal()
    logging.getLogger(__name__).info("exiting on signal %s", sig)
    manager.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
