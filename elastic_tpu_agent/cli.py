"""Agent process entry point + the node-doctor diagnostics subcommand.

Capability parity with ``cmd/main.go`` (SURVEY.md §1 L1): flags -> manager
-> run -> block on exit signals, with a SIGUSR1 stack-dump side channel.
The reference's broken default (-gpuPluginName=qgpu, unsupported by its own
factory) is not replicated: defaults here are runnable.

Usage:
    python -m elastic_tpu_agent.cli --node-name $NODE_NAME \
        --db-file /host/var/lib/elastic-tpu/meta.db --operator tpuvm

    # one-shot diagnostics bundle for support escalation
    python -m elastic_tpu_agent.cli node-doctor \
        --agent-url http://127.0.0.1:9478 > bundle.json
    python -m elastic_tpu_agent.cli node-doctor --validate bundle.json
"""

from __future__ import annotations

import argparse
import json
import logging
import math
import os
import re
import sys
import threading
import time

from .common import install_dump_signal, wait_for_exit_signal
from .manager import ManagerOptions, TPUManager


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="elastic-tpu-agent")
    p.add_argument("--node-name", default="", help="k8s node this agent runs on")
    p.add_argument(
        "--db-file",
        default="/host/var/lib/elastic-tpu/meta.db",
        help="checkpoint db path (hostPath-mounted to survive restarts)",
    )
    p.add_argument("--kubeconf", default="", help="kubeconfig path (default: in-cluster)")
    p.add_argument(
        "--plugin", default="tpushare", help="plugin kind (tpushare)"
    )
    p.add_argument(
        "--operator",
        default="tpuvm",
        help="device operator: tpuvm | stub | stub:<accel-type> | "
             "exclusive | exclusive:<inner> (whole-chip, no virtual nodes)",
    )
    p.add_argument("--dev-root", default="/host/dev", help="host /dev mount")
    p.add_argument(
        "--device-plugin-dir",
        default="/var/lib/kubelet/device-plugins",
        help="kubelet device-plugin socket dir",
    )
    p.add_argument(
        "--pod-resources-socket",
        default="/var/lib/kubelet/pod-resources/kubelet.sock",
        help="kubelet pod-resources socket",
    )
    p.add_argument(
        "--alloc-spec-dir",
        default="/host/var/lib/elastic-tpu/alloc",
        help="where allocation specs for the OCI hook are written",
    )
    p.add_argument(
        "--nri-socket", default="",
        help="containerd NRI socket; when set the agent registers as an "
             "NRI plugin and injects devices at CreateContainer "
             "(containerd/GKE activation; typical: /var/run/nri/nri.sock)",
    )
    p.add_argument(
        "--nri-libtpu", default="",
        help="host libtpu.so to bind-mount into TPU containers via NRI",
    )
    p.add_argument(
        "--nri-evict-on-chip-failure", action="store_true",
        help="policy: evict containers bound to a chip that goes "
             "unhealthy (via NRI UpdateContainers) so kubelet restarts "
             "them onto healthy chips",
    )
    p.add_argument("--metrics-port", type=int, default=9478,
                   help="observability HTTP port serving /metrics, "
                        "/debug/traces and /healthz (0 = off)")
    p.add_argument("--metrics-addr", default="127.0.0.1",
                   help="bind address for the observability endpoint "
                        "(default loopback; set 0.0.0.0 to allow "
                        "off-host Prometheus scrapes, as the shipped "
                        "DaemonSet does)")
    p.add_argument("--dp-pool-size", type=int, default=8,
                   help="gRPC worker threads per device-plugin resource "
                        "server; kubelet binds containers concurrently, "
                        "so size this to the expected bind burst "
                        "(visible in /debug/allocations under 'bind')")
    p.add_argument("--sampler-period", type=float, default=10.0,
                   help="seconds between utilization/health samples "
                        "(sampler.py)")
    p.add_argument("--no-sampler", action="store_true",
                   help="disable the utilization & health sampler")
    p.add_argument("--no-events", action="store_true",
                   help="disable k8s Event emission (e.g. RBAC without "
                        "events:create)")
    p.add_argument("--no-crd", action="store_true",
                   help="disable ElasticTPU CRD publication")
    p.add_argument("--timeline-cap", type=int, default=None,
                   help="ring cap on the durable lifecycle-event "
                        "journal (timeline.py; default 4096). Evictions "
                        "are counted durably either way — see "
                        "node-doctor timeline")
    p.add_argument("--reconcile-period", type=float, default=30.0,
                   help="seconds between continuous-reconciler passes "
                        "(store <-> kubelet <-> disk <-> live-pod drift "
                        "repair; jittered 0.75x-1.25x)")
    p.add_argument("--reconcile-dry-run", action="store_true",
                   help="reconciler observes and reports divergences "
                        "(/debug/allocations 'reconcile' block, doctor "
                        "bundle) without repairing; the boot-time restore "
                        "pass still repairs")
    p.add_argument("--drain-deadline", type=float, default=300.0,
                   help="graceful-drain checkpoint deadline (seconds): "
                        "on a maintenance event / preemption notice / "
                        "operator drain, resident pods get this long "
                        "after the ELASTIC_TPU_DRAIN signal before "
                        "their bindings are reclaimed (drain.py)")
    p.add_argument("--drain-period", type=float, default=2.0,
                   help="seconds between drain-orchestrator trigger "
                        "polls (jittered 0.75x-1.25x)")
    p.add_argument("--preemption-notice", type=float, default=30.0,
                   help="spot preemption notice window (seconds): a "
                        "preemption-triggered drain clamps its budget "
                        "to min(--drain-deadline, this) so checkpoint "
                        "cutover always beats the platform reclaim; "
                        "0 disables the clamp")
    p.add_argument("--goodput-period", type=float, default=10.0,
                   help="seconds between goodput-ledger journal replays "
                        "(per-pod productive/downtime partition + "
                        "downtime-by-cause metrics; goodput.py)")
    p.add_argument("--repartition-period", type=float, default=10.0,
                   help="seconds between repartition-controller policy "
                        "passes (live quota renegotiation for pods that "
                        "opt in via elasticgpu.io/repartition; jittered "
                        "0.75x-1.25x)")
    p.add_argument("--no-repartition", action="store_true",
                   help="disable live re-partitioning and QoS "
                        "throttle/evict enforcement (static grants + "
                        "overcommit alarms only)")
    p.add_argument("--qos-evict-after", type=float, default=300.0,
                   help="seconds between the overcommit throttle clamp "
                        "and binding reclaim for a pod that stays over "
                        "quota (repartition.py)")
    p.add_argument("--migration-period", type=float, default=2.0,
                   help="seconds between migration-coordinator ticks "
                        "(ack consumption, early drain reclaim, "
                        "MigrationRecord publication, inbound resume "
                        "verification; jittered 0.75x-1.25x)")
    p.add_argument("--no-migration", action="store_true",
                   help="disable the checkpoint-handshake migration "
                        "coordinator (drains run to their deadline and "
                        "nothing verifies workload checkpoints/resumes)")
    p.add_argument("--maintenance-poll-ttl", type=float, default=None,
                   help="seconds one GCE maintenance-event/preempted "
                        "metadata fetch stays cached (default 30; env "
                        "ELASTIC_TPU_MAINTENANCE_POLL_TTL also honored "
                        "— lower it for faster drain reaction, at the "
                        "cost of metadata-server traffic)")
    p.add_argument("--slice-membership-ttl", type=float, default=5.0,
                   help="seconds one apiserver slice-membership snapshot "
                        "stays fresh (slices/registry.py) — bounds the "
                        "slice orchestrator's apiserver traffic; lower it "
                        "for faster member-loss detection")
    p.add_argument("--storage-batch-window", type=float, default=0.0,
                   help="group-commit window (seconds) for checkpoint-"
                        "store writes (storage/batcher.py): 0 = every "
                        "write commits itself; >0 (e.g. 0.005) coalesces "
                        "commits — load-bearing writes still block until "
                        "their covering commit is durable, timeline/"
                        "intent-commit traffic batches async. Cuts "
                        "sqlite write amplification ~5x under bind churn")
    p.add_argument("--sink-flush-window", type=float, default=0.0,
                   help="coalescing window (seconds) for the async CRD/"
                        "event sinks: after waking with work the sink "
                        "lingers this long so a bind's burst of "
                        "apiserver writes batches and same-object "
                        "updates dedup (0 = drain immediately)")
    p.add_argument("--no-event-bus", action="store_true",
                   help="disable the in-process event bus (events.py): "
                        "every loop reverts to its pre-event jittered "
                        "poll at the base period (poll-only fallback "
                        "mode — the correctness baseline the safety-net "
                        "sweep preserves)")
    p.add_argument("--event-safety-net-factor", type=float, default=10.0,
                   help="how much a loop stretches its periodic "
                        "safety-net sweep while the event bus is "
                        "healthy and the loop is quiet (events.py; "
                        "clamped to >= 1). The sweep remains the "
                        "correctness backstop for dropped events")
    p.add_argument("--slow-span-ms", type=float, default=None,
                   help="log + journal any trace span slower than this "
                        "many milliseconds as a slow_span timeline event "
                        "(default: tracer built-in, 250ms; also "
                        "ELASTIC_TPU_SLOW_SPAN_MS)")
    p.add_argument("--profile-hz", type=float, default=0.0,
                   help="continuous self-profiler sampling rate in Hz "
                        "(0 = off). Samples every thread's stack and "
                        "serves the hottest stacks at /debug/profile; "
                        "measured overhead is exported as "
                        "elastic_tpu_profiler_overhead_ratio")
    p.add_argument("--crash-loop-threshold", type=int, default=5,
                   help="supervisor circuit breaker: crashes of one "
                        "subsystem within the sliding window before it is "
                        "marked failed (critical subsystems then flip "
                        "/healthz to 503 for the liveness probe)")
    p.add_argument("--faults", default="",
                   help="TEST-ONLY fault injection spec "
                        "(point=spec,point=spec; e.g. "
                        "'gc.sweep=die-thread:1,storage.save=delay:0.5'); "
                        "also read from ELASTIC_TPU_FAULTS. Never set in "
                        "production")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)
    if args.nri_evict_on_chip_failure and not args.nri_socket:
        p.error(
            "--nri-evict-on-chip-failure requires --nri-socket (evictions "
            "go through the NRI session)"
        )
    return args


# -- node-doctor shared plumbing ----------------------------------------------


_SINCE_UNITS = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def since_arg(value: str, _now=None) -> float:
    """argparse type for ``--since``: unix epoch seconds, OR a relative
    duration like ``15m`` / ``2h`` / ``90s`` / ``1d`` (resolved against
    now). Junk raises ArgumentTypeError, so argparse exits non-zero
    with a usage message — pinned in tests."""
    raw = value.strip()
    try:
        ts = float(raw)
    except ValueError:
        pass
    else:
        # 'nan'/'inf' parse as floats but make the ts >= ? filter
        # silently match nothing — an operator typo must be an error,
        # not an empty-but-successful read
        if math.isfinite(ts):
            return ts
        raise argparse.ArgumentTypeError(
            f"{value!r} is not a finite timestamp"
        )
    m = re.fullmatch(r"(\d+(?:\.\d+)?)([smhd])", raw)
    if not m:
        raise argparse.ArgumentTypeError(
            f"{value!r} is neither unix seconds nor a relative duration "
            "(15m, 2h, 90s, 1d)"
        )
    now = time.time() if _now is None else _now
    return now - float(m.group(1)) * _SINCE_UNITS[m.group(2)]


# -- node-doctor timeline -----------------------------------------------------


def parse_timeline_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="elastic-tpu-agent node-doctor timeline",
        description="Reconstruct a causally-linked lifecycle history "
                    "for one pod/slice/chip/node straight from the "
                    "checkpoint db's durable event journal — works "
                    "against a dead agent's db, exactly like the "
                    "open-intent reader.",
    )
    p.add_argument(
        "--db-file", default="/host/var/lib/elastic-tpu/meta.db",
        help="checkpoint db holding the timeline table",
    )
    p.add_argument("--pod", default=None, metavar="NS/NAME",
                   help="history of one pod (bare names accepted)")
    p.add_argument("--slice", dest="slice_id", default=None,
                   help="history of one slice id")
    p.add_argument("--chip", type=int, default=None,
                   help="history of one chip index")
    p.add_argument("--node", default=None,
                   help="filter to one node name (merged fleet dbs)")
    p.add_argument("--trace", default=None,
                   help="history of one trace/correlation id")
    p.add_argument("--kind", action="append", default=None,
                   help="keep only these event kinds (repeatable)")
    p.add_argument("--since", type=since_arg, default=None,
                   help="unix-seconds lower bound, or a relative "
                        "duration (15m, 2h, 90s, 1d)")
    p.add_argument("--limit", type=int, default=None,
                   help="newest-N cap on the reconstructed history")
    p.add_argument("--no-causal", action="store_true",
                   help="direct key matches only — skip the causal "
                        "expansion along shared trace/slice ids")
    return p.parse_args(argv)


def timeline_main(argv=None) -> int:
    args = parse_timeline_args(argv)
    logging.basicConfig(
        level=logging.WARNING,
        format="%(levelname).1s %(name)s %(message)s",
        stream=sys.stderr,
    )
    if not os.path.exists(args.db_file):
        print(f"no db at {args.db_file}", file=sys.stderr)
        return 1
    from .storage import Storage
    from .timeline import Timeline

    with Storage(args.db_file) as storage:
        view = Timeline(storage)
        events = view.events(
            pod=args.pod, slice_id=args.slice_id, chip=args.chip,
            node=args.node, trace=args.trace, kinds=args.kind,
            since=args.since, limit=args.limit,
            causal=not args.no_causal,
        )
        status = view.status()
        # The cap the WRITING agent ran with (persisted alongside the
        # events), not this reader process's default — an operator
        # judging "could the ring have trimmed history?" needs the
        # real bound.
        status["cap"] = storage.timeline_cap_stored()
    entity = {
        k: v for k, v in (
            ("pod", args.pod), ("slice", args.slice_id),
            ("chip", args.chip), ("node", args.node),
            ("trace", args.trace),
        ) if v is not None
    }
    json.dump({
        "db_file": args.db_file,
        "entity": entity,
        "events": events,
        "journal": {
            "cap": status["cap"],
            "total_events": status["total_events"],
            "evicted_total": status["evicted_total"],
        },
    }, sys.stdout, indent=2, sort_keys=True)
    sys.stdout.write("\n")
    return 0


# -- node-doctor goodput ------------------------------------------------------


def parse_goodput_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="elastic-tpu-agent node-doctor goodput",
        description="Replay the checkpoint db's durable event journal "
                    "into the goodput ledger: per-pod partitions of "
                    "wall time into productive/checkpointing/migrating/"
                    "draining/throttled/queued/unattributed, each "
                    "non-productive interval causally attributed — "
                    "works against a dead agent's db, exactly like "
                    "node-doctor timeline.",
    )
    p.add_argument(
        "--db-file", default="/host/var/lib/elastic-tpu/meta.db",
        help="checkpoint db holding the timeline journal + goodput "
             "anchors",
    )
    p.add_argument("--pod", default=None, metavar="NS/NAME",
                   help="one pod's ledger (bare names accepted)")
    p.add_argument("--slice", dest="slice_id", default=None,
                   help="ledgers of one slice's member pods")
    p.add_argument("--since", type=since_arg, default=None,
                   help="keep pods whose lifetime reaches past this "
                        "bound: unix seconds or a relative duration "
                        "(15m, 2h, 90s, 1d)")
    return p.parse_args(argv)


def goodput_main(argv=None) -> int:
    args = parse_goodput_args(argv)
    logging.basicConfig(
        level=logging.WARNING,
        format="%(levelname).1s %(name)s %(message)s",
        stream=sys.stderr,
    )
    if not os.path.exists(args.db_file):
        print(f"no db at {args.db_file}", file=sys.stderr)
        return 1
    from .goodput import build_goodput_block
    from .storage import Storage

    with Storage(args.db_file) as storage:
        block = build_goodput_block(
            storage, pod=args.pod, slice_id=args.slice_id,
            since=args.since,
        )
    entity = {
        k: v for k, v in (
            ("pod", args.pod), ("slice", args.slice_id),
            ("since", args.since),
        ) if v is not None
    }
    json.dump({
        "db_file": args.db_file,
        "entity": entity,
        "goodput": block,
    }, sys.stdout, indent=2, sort_keys=True)
    sys.stdout.write("\n")
    # Conservation is the contract: a ledger that cannot account for a
    # pod's lifetime is a finding, and the exit code says so.
    return 1 if block.get("conservation_problems") else 0


# -- node-doctor --------------------------------------------------------------


def parse_doctor_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="elastic-tpu-agent node-doctor",
        description="Snapshot devices, health, error counters, "
                    "allocations, sampler windows and recent traces into "
                    "one JSON diagnostics bundle (stdout).",
    )
    p.add_argument("--node-name", default="", help="node name for the bundle")
    p.add_argument(
        "--operator", default="tpuvm",
        help="device operator to inspect: tpuvm | stub[:<type>] | "
             "exclusive[:<inner>]",
    )
    p.add_argument("--dev-root", default="/host/dev", help="host /dev mount")
    p.add_argument(
        "--db-file", default="/host/var/lib/elastic-tpu/meta.db",
        help="checkpoint db to read allocations from (skipped if absent)",
    )
    p.add_argument(
        "--alloc-spec-dir", default="/host/var/lib/elastic-tpu/alloc",
        help="alloc-spec dir (trace-id correlation)",
    )
    p.add_argument(
        "--agent-url", default="",
        help="base URL of a running agent's observability endpoint "
             "(e.g. http://127.0.0.1:9478) to include live traces and "
             "the live allocation table",
    )
    p.add_argument(
        "--samples", type=int, default=3,
        help="utilization samples to take before bundling",
    )
    p.add_argument(
        "--interval", type=float, default=1.0,
        help="seconds between samples",
    )
    p.add_argument(
        "--trace-limit", type=int, default=50,
        help="max traces pulled into the bundle",
    )
    p.add_argument(
        "--validate", default="", metavar="BUNDLE_JSON",
        help="validate an existing bundle file against the schema and "
             "exit (no snapshot is taken)",
    )
    return p.parse_args(argv)


def parse_profile_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="elastic-tpu-agent node-doctor profile",
        description="Fetch /debug/profile from a running agent and "
                    "render the hottest stacks (continuous self-"
                    "profiler; enable with --profile-hz on the agent).",
    )
    p.add_argument(
        "--agent-url", required=True,
        help="base URL of a running agent's observability endpoint "
             "(e.g. http://127.0.0.1:9478)",
    )
    p.add_argument("--top", type=int, default=30,
                   help="stacks to show (hottest first)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw /debug/profile payload instead of "
                        "the rendered view")
    p.add_argument("--timeout", type=float, default=3.0,
                   help="HTTP timeout in seconds")
    return p.parse_args(argv)


def profile_main(argv=None) -> int:
    from .profiler import render_profile
    from .sampler import _fetch_json

    args = parse_profile_args(argv)
    url = f"{args.agent_url.rstrip('/')}/debug/profile?top={args.top}"
    try:
        payload = _fetch_json(url, args.timeout)
    except Exception as e:  # noqa: BLE001 - one fetch, report and exit
        print(f"cannot fetch {url}: {e}", file=sys.stderr)
        return 1
    if "error" in payload and "samples_total" not in payload:
        # The endpoint answers JSON on every status; a 503 here means
        # the agent is up but the profiler isn't attached yet.
        print(f"agent error: {payload['error']}", file=sys.stderr)
        return 1
    if args.json:
        json.dump(payload, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_profile(payload, top=args.top) + "\n")
    return 0


def doctor_main(argv=None) -> int:
    if argv and argv[0] == "timeline":
        return timeline_main(argv[1:])
    if argv and argv[0] == "goodput":
        return goodput_main(argv[1:])
    if argv and argv[0] == "profile":
        return profile_main(argv[1:])
    from .sampler import (
        UtilizationSampler,
        build_diagnostics_bundle,
        validate_bundle,
    )

    args = parse_doctor_args(argv)
    # Keep stdout pure JSON — everything else goes to stderr.
    logging.basicConfig(
        level=logging.WARNING,
        format="%(levelname).1s %(name)s %(message)s",
        stream=sys.stderr,
    )
    if args.validate:
        try:
            with open(args.validate) as f:
                bundle = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read bundle {args.validate}: {e}", file=sys.stderr)
            return 1
        problems = validate_bundle(bundle)
        if problems:
            for problem in problems:
                print(f"INVALID: {problem}", file=sys.stderr)
            return 1
        print(f"bundle {args.validate} is valid", file=sys.stderr)
        return 0

    from .manager import build_operator

    operator = build_operator(
        ManagerOptions(operator_kind=args.operator, dev_root=args.dev_root)
    )
    storage = None
    if os.path.exists(args.db_file):
        from .storage import Storage

        storage = Storage(args.db_file)
    sampler = UtilizationSampler(
        operator,
        storage=storage,
        alloc_spec_dir=args.alloc_spec_dir,
        period_s=max(args.interval, 0.0),
    )
    for i in range(max(1, args.samples)):
        sampler.sample_once()
        if i + 1 < max(1, args.samples) and args.interval > 0:
            time.sleep(args.interval)
    from .tracing import get_tracer

    bundle = build_diagnostics_bundle(
        operator,
        sampler=sampler,
        tracer=None if args.agent_url else get_tracer(),
        node_name=args.node_name,
        agent_url=args.agent_url,
        trace_limit=args.trace_limit,
        storage=storage,
    )
    if storage is not None:
        storage.close()
    json.dump(bundle, sys.stdout, indent=2, sort_keys=True)
    sys.stdout.write("\n")
    return 0


def parse_perf_gate_args(argv=None) -> argparse.Namespace:
    from . import bench_history as bh

    p = argparse.ArgumentParser(
        prog="elastic-tpu-agent perf-gate",
        description="Perf-regression ledger: parse the committed "
                    "BENCH_r*.json trajectory into per-leg time series "
                    "and fail when a tracked latency regresses beyond "
                    "tolerance against the recent-median baseline.",
    )
    p.add_argument("--root", default=".",
                   help="directory holding BENCH_r*.json rounds")
    p.add_argument("--include", action="append", default=[],
                   metavar="FILE",
                   help="extra bench JSON file(s) to fold into the "
                        "history (repeatable; e.g. a fresh uncommitted "
                        "round)")
    p.add_argument("--tolerance", type=float,
                   default=bh.DEFAULT_TOLERANCE,
                   help="allowed fractional regression over the "
                        "baseline median (0.5 = +50%%)")
    p.add_argument("--floor-ms", type=float, default=bh.DEFAULT_FLOOR_MS,
                   help="absolute slack added to every limit — keeps "
                        "sub-millisecond legs from tripping on noise")
    p.add_argument("--window", type=int, default=bh.DEFAULT_WINDOW,
                   help="prior rounds whose median forms the baseline")
    p.add_argument("--series", action="store_true",
                   help="print the parsed per-leg time series before "
                        "gating (debugging aid)")
    p.add_argument("--self-test", action="store_true",
                   help="also seed a synthetic regression on top of the "
                        "real history and fail unless the gate catches "
                        "it on every tracked series")
    return p.parse_args(argv)


def perf_gate_main(argv=None) -> int:
    from . import bench_history as bh

    args = parse_perf_gate_args(argv)
    rounds, problems = bh.load_history(args.root, include=args.include)
    problems.extend(bh.validate_history(rounds))
    if not problems:
        if args.series:
            all_tracked = (
                *bh.TRACKED, *bh.TRACKED_RATIOS, *bh.TRACKED_EVENT,
                *bh.TRACKED_MIGRATION,
            )
            for name, points in sorted(
                bh.series(rounds, all_tracked).items()
            ):
                path = " ".join(
                    f"r{n:02d}={v:.3f}" for n, v in points
                )
                print(f"# {name}: {path}", file=sys.stderr)
        problems.extend(bh.perf_gate(
            rounds, tolerance=args.tolerance,
            floor_ms=args.floor_ms, window=args.window,
        ))
        if args.self_test:
            problems.extend(bh.self_test(
                rounds, tolerance=args.tolerance,
                floor_ms=args.floor_ms, window=args.window,
            ))
    if problems:
        for problem in problems:
            print(f"PERF-GATE: {problem}", file=sys.stderr)
        return 1
    tracked = ", ".join(
        name for name, _ in
        (*bh.TRACKED, *bh.TRACKED_RATIOS, *bh.TRACKED_EVENT,
         *bh.TRACKED_MIGRATION)
    )
    print(
        f"perf-gate OK: {len(rounds)} round(s), tracked [{tracked}]"
        + (" + self-test" if args.self_test else ""),
        file=sys.stderr,
    )
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "node-doctor":
        return doctor_main(argv[1:])
    if argv and argv[0] == "perf-gate":
        return perf_gate_main(argv[1:])
    args = parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s %(message)s",
        stream=sys.stderr,
    )
    install_dump_signal()

    fault_spec = args.faults or os.environ.get("ELASTIC_TPU_FAULTS", "")
    if fault_spec:
        from . import faults

        logging.getLogger(__name__).warning(
            "fault injection ARMED (test-only): %s", fault_spec
        )
        faults.get_registry().arm_spec(fault_spec)

    metrics = None
    if args.metrics_port:
        from .metrics import AgentMetrics

        metrics = AgentMetrics()
        # A busy port must not take the allocation path down with it: the
        # agent keeps running and the endpoint keeps retrying the bind —
        # required now that the DaemonSet liveness probe hits /healthz
        # (a permanent no-endpoint state would probe-restart forever).
        metrics.serve_with_retry(args.metrics_port, addr=args.metrics_addr)
    # Process-wide net: threads nobody registered with the supervisor
    # still can't die unobserved (elastic_tpu_thread_crashes_total).
    from .supervisor import install_thread_excepthook

    install_thread_excepthook(metrics)

    manager = TPUManager(
        ManagerOptions(
            node_name=args.node_name,
            db_path=args.db_file,
            kubeconfig=args.kubeconf,
            plugin_kind=args.plugin,
            operator_kind=args.operator,
            dev_root=args.dev_root,
            device_plugin_dir=args.device_plugin_dir,
            pod_resources_socket=args.pod_resources_socket,
            alloc_spec_dir=args.alloc_spec_dir,
            nri_socket=args.nri_socket,
            nri_libtpu=args.nri_libtpu,
            nri_evict_on_chip_failure=args.nri_evict_on_chip_failure,
            metrics=metrics,
            enable_events=not args.no_events,
            enable_crd=not args.no_crd,
            enable_sampler=not args.no_sampler,
            sampler_period_s=args.sampler_period,
            dp_pool_size=args.dp_pool_size,
            crash_loop_threshold=args.crash_loop_threshold,
            reconcile_period_s=args.reconcile_period,
            reconcile_dry_run=args.reconcile_dry_run,
            slice_membership_ttl_s=args.slice_membership_ttl,
            drain_deadline_s=args.drain_deadline,
            preemption_notice_s=args.preemption_notice,
            drain_period_s=args.drain_period,
            enable_repartition=not args.no_repartition,
            repartition_period_s=args.repartition_period,
            qos_evict_after_s=args.qos_evict_after,
            enable_migration=not args.no_migration,
            migration_period_s=args.migration_period,
            maintenance_poll_ttl_s=args.maintenance_poll_ttl,
            goodput_period_s=args.goodput_period,
            slow_span_ms=args.slow_span_ms,
            profile_hz=args.profile_hz,
            storage_batch_window_s=args.storage_batch_window,
            sink_flush_window_s=args.sink_flush_window,
            enable_event_bus=not args.no_event_bus,
            event_safety_net_factor=args.event_safety_net_factor,
            **(
                {"timeline_cap": args.timeline_cap}
                if args.timeline_cap is not None else {}
            ),
        )
    )
    run_thread = threading.Thread(
        target=manager.run, kwargs={"block": True}, daemon=True, name="manager"
    )
    run_thread.start()
    sig = wait_for_exit_signal()
    logging.getLogger(__name__).info("exiting on signal %s", sig)
    manager.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
