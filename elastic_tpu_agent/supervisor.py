"""Subsystem supervision: every background loop gets a named guardian.

The agent runs ~8 background loops (sitter, GC, device-health poller,
utilization sampler, NRI plugin, CRD/event sink workers, the allocatable
cross-check, and one device-plugin serve loop per resource). Before this
module, each was a bare daemon thread: an uncaught exception silently
evaporated the thread and the node kept advertising fractional
tpu-core/tpu-memory with stale health, no reclamation, or a dead
ListAndWatch — the "agent is a single point of failure per node" risk
(SURVEY §5.2). The supervisor gives the agent reflexes:

- every subsystem is a registered, *supervised* task with an
  uncaught-exception trap (including BaseException, so even
  fault-injected ``DieThread`` deaths are caught);
- crashes restart with jittered exponential backoff (a loop that dies
  against a broken dependency must not spin the CPU);
- a crash-loop circuit breaker: >= ``crash_loop_threshold`` crashes
  inside a sliding window marks the subsystem ``failed`` instead of
  thrashing forever;
- a criticality class decides what a circuit-broken subsystem means:
  ``critical`` failures (device-plugin serve loops, GC, sitter) flip
  ``/healthz`` to 503 so the DaemonSet liveness probe restarts the pod,
  while ``degraded`` failures (sampler, health poller, CRD/events, NRI)
  keep binding alive and surface per-subsystem state via the
  ``/healthz`` JSON, ``elastic_tpu_subsystem_*`` metrics, and the
  node-doctor bundle.

The supervisor also owns the agent's *terminal event*: set when the
global stop event fires or when a critical subsystem circuit-breaks.
``TPUManager.run(block=True)`` blocks on it — previously it blocked on
the GC thread alone, so a crashed GC exited (or wedged) the whole agent
arbitrarily.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from . import faults
from .common import JitteredBackoff

logger = logging.getLogger(__name__)

# criticality classes
CRITICAL = "critical"
DEGRADED = "degraded"

# subsystem states
STATE_PENDING = "pending"      # registered, supervisor not started yet
STATE_RUNNING = "running"
STATE_BACKOFF = "backoff"      # crashed; waiting to restart
STATE_FAILED = "failed"        # circuit breaker open: no more restarts
STATE_STOPPED = "stopped"      # clean exit (global stop / owner stop)
STATE_DONE = "done"            # one-shot task completed

DEFAULT_CRASH_LOOP_THRESHOLD = 5
DEFAULT_CRASH_LOOP_WINDOW_S = 300.0
DEFAULT_BACKOFF_MIN_S = 0.5
DEFAULT_BACKOFF_MAX_S = 30.0


class _Subsystem:
    def __init__(
        self,
        name: str,
        target: Callable[[threading.Event], None],
        criticality: str,
        one_shot: bool,
        clean_exit: Optional[Callable[[], bool]],
    ) -> None:
        self.name = name
        self.target = target
        self.criticality = criticality
        self.one_shot = one_shot
        self.clean_exit = clean_exit
        self.state = STATE_PENDING
        self.restarts = 0          # crashes that led to a restart
        self.crash_loops = 0       # times the circuit breaker opened
        self.last_error: Optional[str] = None
        self.last_crash_monotonic: Optional[float] = None
        self.started_monotonic: Optional[float] = None
        self.crash_times: List[float] = []   # sliding window
        self.thread: Optional[threading.Thread] = None


class Supervisor:
    """Registry + restart engine for the agent's background loops.

    ``register()`` may be called before or after ``start()``; targets
    registered after start are spawned immediately (the manager starts
    the sitter before the plugins, with restore() in between).
    """

    def __init__(
        self,
        metrics=None,
        crash_loop_threshold: int = DEFAULT_CRASH_LOOP_THRESHOLD,
        crash_loop_window_s: float = DEFAULT_CRASH_LOOP_WINDOW_S,
        backoff_min_s: float = DEFAULT_BACKOFF_MIN_S,
        backoff_max_s: float = DEFAULT_BACKOFF_MAX_S,
        timeline=None,
    ) -> None:
        self._metrics = metrics
        # Lifecycle timeline (timeline.py): restarts and breaker trips
        # journaled so a history can say "the reconciler died twice
        # right before this pod's repairs stopped".
        self._timeline = timeline
        self._crash_loop_threshold = max(1, crash_loop_threshold)
        self._crash_loop_window_s = crash_loop_window_s
        self._backoff_min_s = backoff_min_s
        self._backoff_max_s = backoff_max_s
        self._lock = threading.Lock()
        self._subsystems: "Dict[str, _Subsystem]" = {}
        self._stop: Optional[threading.Event] = None
        self._started = False
        # Set on global stop OR when a critical subsystem circuit-breaks.
        self.terminal = threading.Event()

    # -- registration ---------------------------------------------------------

    def register(
        self,
        name: str,
        target: Callable[[threading.Event], None],
        criticality: str = DEGRADED,
        one_shot: bool = False,
        clean_exit: Optional[Callable[[], bool]] = None,
    ) -> None:
        """Register ``target(stop_event)`` as a supervised subsystem.

        ``target`` is expected to run until the stop event is set (or, for
        ``one_shot`` tasks, to run to completion once). A return before
        stop without ``one_shot``/``clean_exit`` is treated as a crash —
        silently-evaporating loops are exactly the failure mode this
        module exists to catch. ``clean_exit`` is polled on return to
        recognize owner-initiated shutdowns (e.g. a sink's drain-stop).
        """
        if criticality not in (CRITICAL, DEGRADED):
            raise ValueError(f"unknown criticality {criticality!r}")
        with self._lock:
            if name in self._subsystems:
                raise ValueError(f"subsystem {name!r} already registered")
            sub = _Subsystem(name, target, criticality, one_shot, clean_exit)
            self._subsystems[name] = sub
            started = self._started
        if started:
            self._spawn(sub)

    # -- lifecycle ------------------------------------------------------------

    def start(self, stop: threading.Event) -> None:
        """Activate supervision; spawns every registered subsystem and
        arranges for ``terminal`` to fire when ``stop`` does."""
        with self._lock:
            if self._started:
                return
            self._stop = stop
            self._started = True
            pending = list(self._subsystems.values())
        threading.Thread(
            target=self._watch_stop, daemon=True, name="supervisor-terminal"
        ).start()
        for sub in pending:
            self._spawn(sub)

    def _watch_stop(self) -> None:
        self._stop.wait()
        self.terminal.set()

    def wait_terminal(self, timeout: Optional[float] = None) -> bool:
        return self.terminal.wait(timeout)

    def join(self, name: str, timeout: Optional[float] = None) -> None:
        """Join one subsystem's supervision thread (shutdown ordering)."""
        with self._lock:
            sub = self._subsystems.get(name)
            thread = sub.thread if sub is not None else None
        if thread is not None:
            thread.join(timeout)

    def _spawn(self, sub: _Subsystem) -> None:
        t = threading.Thread(
            target=self._supervise, args=(sub,), daemon=True,
            name=f"supervised-{sub.name}",
        )
        sub.thread = t
        t.start()

    # -- the supervision loop -------------------------------------------------

    def _set_up_gauge(self, sub: _Subsystem, up: bool) -> None:
        m = self._metrics
        if m is not None and hasattr(m, "subsystem_up"):
            try:
                m.subsystem_up.labels(subsystem=sub.name).set(1.0 if up else 0.0)
            except Exception:  # noqa: BLE001 - metrics must not break supervision
                pass

    def _count(self, sub: _Subsystem, metric_name: str) -> None:
        m = self._metrics
        if m is not None and hasattr(m, metric_name):
            try:
                getattr(m, metric_name).labels(subsystem=sub.name).inc()
            except Exception:  # noqa: BLE001
                pass

    def _supervise(self, sub: _Subsystem) -> None:
        stop = self._stop
        backoff = JitteredBackoff(self._backoff_min_s, self._backoff_max_s)
        while not stop.is_set():
            sub.state = STATE_RUNNING
            sub.started_monotonic = time.monotonic()
            self._set_up_gauge(sub, True)
            error: Optional[BaseException] = None
            try:
                sub.target(stop)
            except faults.DieThread as e:
                error = e
            except BaseException as e:  # noqa: BLE001 - the whole point
                error = e
                logger.exception("subsystem %s crashed", sub.name)
            uptime = time.monotonic() - sub.started_monotonic
            if error is None:
                clean = stop.is_set() or sub.one_shot
                if not clean and sub.clean_exit is not None:
                    try:
                        clean = bool(sub.clean_exit())
                    except Exception:  # noqa: BLE001
                        clean = False
                if clean:
                    sub.state = (
                        STATE_DONE if sub.one_shot and not stop.is_set()
                        else STATE_STOPPED
                    )
                    self._set_up_gauge(sub, False)
                    return
                error = RuntimeError(
                    "subsystem returned before stop (silent loop death)"
                )
                logger.error("subsystem %s: %s", sub.name, error)
            # -- crash accounting ---------------------------------------------
            now = time.monotonic()
            sub.last_error = f"{type(error).__name__}: {error}"
            sub.last_crash_monotonic = now
            sub.crash_times.append(now)
            cutoff = now - self._crash_loop_window_s
            sub.crash_times = [t for t in sub.crash_times if t >= cutoff]
            self._set_up_gauge(sub, False)
            if len(sub.crash_times) >= self._crash_loop_threshold:
                # circuit breaker: stop thrashing; surface loudly instead
                sub.state = STATE_FAILED
                sub.crash_loops += 1
                self._count(sub, "subsystem_crash_loops")
                logger.error(
                    "subsystem %s FAILED: %d crashes within %.0fs "
                    "(last: %s) — circuit breaker open, no more restarts%s",
                    sub.name, len(sub.crash_times),
                    self._crash_loop_window_s, sub.last_error,
                    "; CRITICAL: flipping /healthz to 503 so the liveness "
                    "probe restarts this pod"
                    if sub.criticality == CRITICAL else "",
                )
                if self._timeline is not None:
                    from .timeline import KIND_SUBSYSTEM_CRASH_LOOP

                    self._timeline.emit(
                        KIND_SUBSYSTEM_CRASH_LOOP,
                        subsystem=sub.name,
                        criticality=sub.criticality,
                        crashes_in_window=len(sub.crash_times),
                        error=sub.last_error,
                    )
                if sub.criticality == CRITICAL:
                    self.terminal.set()
                return
            sub.restarts += 1
            self._count(sub, "subsystem_restarts")
            if self._timeline is not None:
                from .timeline import KIND_SUBSYSTEM_RESTART

                self._timeline.emit(
                    KIND_SUBSYSTEM_RESTART,
                    subsystem=sub.name, restart=sub.restarts,
                    error=sub.last_error,
                )
            if uptime > 2 * self._backoff_max_s:
                backoff.reset()  # it ran long enough: healthy again
            delay = backoff.next_delay()
            logger.warning(
                "subsystem %s: restart #%d in %.2fs (crash: %s)",
                sub.name, sub.restarts, delay, sub.last_error,
            )
            sub.state = STATE_BACKOFF
            if stop.wait(delay):
                break
        sub.state = STATE_STOPPED
        self._set_up_gauge(sub, False)

    # -- introspection --------------------------------------------------------

    def status(self) -> Dict[str, dict]:
        """Per-subsystem snapshot for /healthz and the doctor bundle."""
        now = time.monotonic()
        out: Dict[str, dict] = {}
        with self._lock:
            subs = list(self._subsystems.values())
        for sub in subs:
            out[sub.name] = {
                "criticality": sub.criticality,
                "state": sub.state,
                "restarts": sub.restarts,
                "crash_loops": sub.crash_loops,
                "last_error": sub.last_error,
                "uptime_s": (
                    round(now - sub.started_monotonic, 3)
                    if sub.state == STATE_RUNNING
                    and sub.started_monotonic is not None else None
                ),
            }
        return out

    def critical_failed(self) -> List[str]:
        with self._lock:
            return sorted(
                s.name for s in self._subsystems.values()
                if s.state == STATE_FAILED and s.criticality == CRITICAL
            )

    def degraded_subsystems(self) -> List[str]:
        """Non-critical subsystems that are circuit-broken (plus any
        subsystem currently crash-restarting): the node still binds, but
        an operator should know."""
        with self._lock:
            return sorted(
                s.name for s in self._subsystems.values()
                if (s.state == STATE_FAILED and s.criticality != CRITICAL)
                or s.state == STATE_BACKOFF
            )

    def healthz(self) -> dict:
        """The /healthz contract: ``critical_failed`` non-empty means the
        endpoint answers 503 (liveness probe restarts the pod)."""
        return {
            "critical_failed": self.critical_failed(),
            "degraded": self.degraded_subsystems(),
            "subsystems": self.status(),
        }


# -- process-wide thread-death accounting -------------------------------------
#
# Even with every known loop supervised, a thread someone forgot to
# register (or a library thread) can still die on an uncaught exception.
# threading.excepthook is the process-wide net: every such death bumps
# elastic_tpu_thread_crashes_total so it at least cannot happen
# *unobserved*.

_thread_crashes = 0
_thread_crashes_lock = threading.Lock()


def thread_crash_count() -> int:
    return _thread_crashes


def install_thread_excepthook(metrics=None):
    """Install a counting threading.excepthook; returns the previous hook
    (pass it to ``uninstall_thread_excepthook`` to restore — tests)."""
    previous = threading.excepthook

    def _hook(args):
        global _thread_crashes
        with _thread_crashes_lock:
            _thread_crashes += 1
        if metrics is not None and hasattr(metrics, "thread_crashes"):
            try:
                metrics.thread_crashes.inc()
            except Exception:  # noqa: BLE001
                pass
        name = args.thread.name if args.thread is not None else "?"
        logger.error(
            "unsupervised thread %r died: %s: %s",
            name, getattr(args.exc_type, "__name__", args.exc_type),
            args.exc_value,
        )
        try:
            previous(args)
        except Exception:  # noqa: BLE001 - never raise from the hook
            pass

    threading.excepthook = _hook
    return previous


def uninstall_thread_excepthook(previous) -> None:
    threading.excepthook = previous
