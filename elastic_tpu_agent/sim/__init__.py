"""Cluster-in-a-box fleet simulator + the observability layer over it.

ROADMAP open item 1: production is a fleet, and nothing node-local can
measure fleet bind p99, reconcile convergence, or kubelet/apiserver
request amplification. This package runs N complete in-process agents —
each against its own fake kubelet and its own stub operator, all sharing
ONE fake apiserver — and reads the result the way production would: by
scraping every agent's /metrics endpoint.

- fleet.py: FleetSim — builds/starts/drives/stops the simulated fleet
  (reuses the hermetic rigs in tests/fake_apiserver.py and
  tests/fake_kubelet.py; this is a dev/bench tool, never shipped in the
  DaemonSet image).
- aggregator.py: FleetAggregator — scrapes each agent over HTTP, merges
  histogram buckets for fleet-level quantiles, computes per-bind request
  amplification, tracks per-node reconcile convergence, and follows
  admission-stamped trace ids to whichever node bound the pod.
- traffic.py: TraceGenerator — seeded, replayable request/pod arrival
  traces (diurnal load, flash crowds, prefix-cache-hostile prompts,
  mixed train/serve tenancy); same seed ⇒ byte-identical trace.
- chaos.py: ChaosMatrix — overlapping fault programs (brownouts, flaky
  disks, drains, kubelet flaps, throttles) replayed over live traffic,
  scored by fleet goodput + SLO attainment with the compound
  conservation invariants judged by scale_problems().
"""

from .aggregator import FleetAggregator, histogram_quantile  # noqa: F401
from .chaos import (  # noqa: F401
    ChaosMatrix, ChaosProgram, OpCursor, ScenarioRunner, repro_line,
)
from .fleet import FleetSim  # noqa: F401
from .scale import ScaleHarness, scale_problems  # noqa: F401
from .traffic import Trace, TraceCursor, TraceGenerator  # noqa: F401
