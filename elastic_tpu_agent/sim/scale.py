"""Cluster-in-a-box scale harness: thousand-pod fleet load generation.

ROADMAP item 1's last open half: the fleet observatory (fleet.py,
PR 6) proved N agents against one apiserver *works*; nothing yet proved
the write paths and memory bounds HOLD at production pod counts. This
module is the load generator that does: it composes 16-32 complete
agents (full TPUManager each — supervised reconciler, drain
orchestrator, sinks, sampler) against ONE shared FakeAPIServer and
churns thousands of concurrent pods through deterministic scenario
phases:

1. **admission waves** — pods admitted and bound in W fleet-wide
   concurrent waves (the mass-reschedule shape: a big job landing);
2. **steady-state churn** — a fraction of the fleet's pods deleted
   (apiserver + kubelet, like the control plane would) and replaced,
   driving GC/reconcile traffic alongside fresh binds;
3. **drain wave** — maintenance announced on several nodes at once,
   then cleared: cordon/signal/cancel across the fleet mid-load;
4. **slice reform** — a multi-host slice forms and loses a member pod;
   survivors must re-form while the rest of the fleet churns;
5. **repartition ticks** — one controller policy pass per node, timed
   at fleet pod counts (the tick walks the store and the ledger);
6. **cardinality storm** — 10k+ distinct pod-series pushed through the
   real BoundedLabeledGauge guards, proving bounded series AND bounded
   RSS while everything above is still resident.

Everything it reports is measured the way production would measure it:
fleet bind p50/p99 from scraped histogram merges (aggregator.py),
request amplification from source-side counters (kubelet List counter,
sink write counters, the FakeAPIServer's own ``request_counts``,
storage commit counters), convergence from the reconciler's converged
timestamp, and memory from ``/proc/self/statm`` sampled continuously
for the peak.

The two enabling refactors it exists to measure — group-commit storage
batching (storage/batcher.py) and coalesced sink traffic (async_sink
flush window) — are knobs here, so one run with them and one without
gives a same-run write-amplification comparison (bench.py --scale).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..common import read_rss_bytes
from ..tracing import get_tracer
from .aggregator import FleetAggregator
from .fleet import FleetSim, PodRef


class RSSWatcher:
    """Samples this process's RSS on a background thread; keeps the
    peak. The scale run's memory ceiling is asserted against the PEAK,
    not a lucky end-of-run sample taken after the churn's garbage was
    collected."""

    def __init__(self, period_s: float = 0.05) -> None:
        self._period_s = period_s
        self._stop = threading.Event()
        self.start_bytes = read_rss_bytes()
        self.peak_bytes = self.start_bytes
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="scale-rss-watcher"
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._period_s):
            rss = read_rss_bytes()
            if rss > self.peak_bytes:
                self.peak_bytes = rss

    def stop(self) -> dict:
        self._stop.set()
        self._thread.join(timeout=5.0)
        rss = read_rss_bytes()
        if rss > self.peak_bytes:
            self.peak_bytes = rss
        return {
            "start_rss_bytes": self.start_bytes,
            "peak_rss_bytes": self.peak_bytes,
            "rss_delta_bytes": max(0, self.peak_bytes - self.start_bytes),
        }


class ScaleHarness:
    """One scale scenario over one FleetSim. Build → run() → report.

    ``storage_batch_window_s`` / ``sink_flush_window_s`` select the
    batched (coalesced) or the historical per-write shape; bench.py
    --scale runs both and reports the measured amplification reduction.
    """

    def __init__(
        self,
        base_dir: str,
        nodes: int = 16,
        pods_per_node: int = 125,
        admission_waves: int = 4,
        workers_per_node: int = 2,
        churn_fraction: float = 0.2,
        drain_nodes: int = 2,
        slice_world: int = 4,
        cardinality_series_total: int = 10_500,
        storage_batch_window_s: float = 0.005,
        sink_flush_window_s: float = 0.02,
        reconcile_period_s: float = 2.0,
        enable_sampler: bool = True,
        convergence_timeout_s: float = 120.0,
        phase_timeout_s: float = 120.0,
    ) -> None:
        self.nodes = nodes
        self.pods_per_node = pods_per_node
        self.admission_waves = max(1, admission_waves)
        self.workers_per_node = workers_per_node
        self.churn_fraction = churn_fraction
        self.drain_nodes = min(drain_nodes, nodes)
        self.slice_world = min(slice_world, nodes)
        self.cardinality_series_total = cardinality_series_total
        self.storage_batch_window_s = storage_batch_window_s
        self.sink_flush_window_s = sink_flush_window_s
        self.convergence_timeout_s = convergence_timeout_s
        self.phase_timeout_s = phase_timeout_s
        self.sim = FleetSim(
            base_dir,
            nodes=nodes,
            reconcile_period_s=reconcile_period_s,
            enable_sampler=enable_sampler,
            storage_batch_window_s=storage_batch_window_s,
            sink_flush_window_s=sink_flush_window_s,
        )

    # -- phases ---------------------------------------------------------------

    def _phase_admission_waves(self) -> dict:
        """W waves of fleet-wide concurrent admission + bind — the
        thundering-herd shape a mass reschedule produces."""
        sim = self.sim
        per_wave = max(1, self.pods_per_node // self.admission_waves)
        waves = []
        for w in range(self.admission_waves):
            count = (
                self.pods_per_node - per_wave * (self.admission_waves - 1)
                if w == self.admission_waves - 1 else per_wave
            )
            if count <= 0:
                continue
            refs = sim.admit_pods(count, namespace=f"wave{w}")
            sim.wait_synced(refs, timeout_s=self.phase_timeout_s)
            driver = sim.churn(
                refs, workers_per_node=self.workers_per_node,
                timeout_s=self.phase_timeout_s * 4,
            )
            waves.append({
                "pods": driver["pods"],
                "bound": driver["bound"],
                "error_count": driver["error_count"],
                "bind_p50_ms": driver["bind_p50_ms"],
                "bind_p99_ms": driver["bind_p99_ms"],
                "binds_per_s": driver["binds_per_s"],
            })
            self._refs.extend(refs)
            self._last_churn_end_ts = driver["churn_end_ts"]
        return {
            "waves": waves,
            "admitted": sum(w["pods"] for w in waves),
            "bound": sum(w["bound"] for w in waves),
            "errors": sum(w["error_count"] for w in waves),
        }

    def _phase_steady_churn(self) -> dict:
        """Delete a fraction of the live fleet (control-plane style:
        apiserver DELETE + kubelet unassign), wait for the GC/reconcile
        machinery to reclaim every binding, then admit and bind
        replacements — the steady-state pod-lifecycle load."""
        sim = self.sim
        stride = max(2, int(1 / max(0.01, self.churn_fraction)))
        victims = self._refs[::stride]
        if not victims:
            return {"skipped": True, "reason": "no pods admitted"}
        t0 = time.perf_counter()
        sim.delete_pods(victims)
        reclaim_s = sim.wait_reclaimed(
            victims, timeout_s=self.phase_timeout_s
        )
        victim_keys = {id(v) for v in victims}
        self._refs = [r for r in self._refs if id(r) not in victim_keys]
        # Replacements: same per-node counts the victims had.
        by_node: Dict[int, int] = {}
        for v in victims:
            by_node[v.node_idx] = by_node.get(v.node_idx, 0) + 1
        replacements: List[PodRef] = []
        for idx, count in sorted(by_node.items()):
            replacements.extend(sim.admit_pods(
                count, namespace="replace", node_idxs=[idx]
            ))
        sim.wait_synced(replacements, timeout_s=self.phase_timeout_s)
        driver = sim.churn(
            replacements, workers_per_node=self.workers_per_node,
            timeout_s=self.phase_timeout_s * 2,
        )
        self._refs.extend(replacements)
        self._last_churn_end_ts = driver["churn_end_ts"]
        return {
            "deleted": len(victims),
            "reclaim_wait_s": round(reclaim_s, 3),
            "replaced": driver["pods"],
            "rebound": driver["bound"],
            "errors": driver["error_count"],
            "rebind_p99_ms": driver["bind_p99_ms"],
            "wall_s": round(time.perf_counter() - t0, 3),
        }

    def _phase_drain_wave(self) -> dict:
        """Maintenance announced on ``drain_nodes`` nodes AT ONCE (a
        rack maintenance window), then cleared: every one must cordon
        and signal, then cancel back to active — while the rest of the
        fleet keeps its pods."""
        sim = self.sim
        idxs = list(range(self.nodes - self.drain_nodes, self.nodes))
        if not idxs:
            return {"skipped": True, "reason": "no drain nodes configured"}
        t0 = time.perf_counter()
        for i in idxs:
            sim.trigger_maintenance(i)
        states = {}
        for i in idxs:
            states[sim.nodes[i].name] = sim.wait_drain_state(
                i, ("cordoned", "draining", "drained"),
                timeout_s=self.phase_timeout_s,
            )
        signal_s = time.perf_counter() - t0
        t1 = time.perf_counter()
        for i in idxs:
            sim.clear_maintenance(i)
        for i in idxs:
            sim.wait_drain_state(i, "active", timeout_s=self.phase_timeout_s)
        return {
            "nodes": len(idxs),
            "states_reached": states,
            "all_signaled_s": round(signal_s, 3),
            "all_cancelled_s": round(time.perf_counter() - t1, 3),
        }

    def _phase_slice_reform(self) -> dict:
        """A multi-host slice forms across ``slice_world`` nodes, binds,
        then loses one member POD (deleted at the apiserver, the node
        stays up): survivors must re-form to the smaller world while the
        fleet around them is fully loaded."""
        from ..common import EnvSliceEpoch  # noqa: F401 - doc pointer
        from ..slice_env import ordered_worker_hostnames

        sim = self.sim
        node_idxs = list(range(self.slice_world))
        hosts = [sim.nodes[i].name for i in node_idxs]
        t0 = time.perf_counter()
        refs = sim.admit_slice("scale-slice", node_idxs)
        sim.wait_synced(refs, timeout_s=self.phase_timeout_s)
        for ref in refs:
            sim.bind_pod(ref)
        formation_s = time.perf_counter() - t0
        victim, survivors = refs[-1], refs[:-1]
        surviving_order, _ = ordered_worker_hostnames(hosts[:-1])
        sim.delete_pods([victim])
        reform_s = sim.wait_slice_reformed(
            survivors, surviving_order, expected_epoch=1,
            timeout_s=self.phase_timeout_s,
        )
        # The victim's binding must also be RECLAIMED (GC off the
        # sitter's DELETED event), so the fleet's stored-bind ground
        # truth stays exact; survivors stay resident and counted.
        sim.wait_reclaimed([victim], timeout_s=self.phase_timeout_s)
        self._refs.extend(survivors)
        return {
            "world": len(refs),
            "formation_s": round(formation_s, 3),
            "reform_convergence_s": round(reform_s, 3),
        }

    def _phase_repartition_ticks(self) -> dict:
        """One repartition-controller policy pass per node, timed: the
        tick diffs the sampler view against the store and the donation
        ledger — at fleet pod counts its cost is a per-node scaling
        number, not a constant."""
        sim = self.sim
        durations = []
        for node in sim.nodes:
            controller = getattr(node.manager, "repartition", None)
            if controller is None:
                return {
                    "skipped": True,
                    "reason": "repartition controller disabled "
                              "(sampler off)",
                }
            t0 = time.perf_counter()
            try:
                controller.tick()
            except Exception as e:  # noqa: BLE001 - reported, not fatal
                return {
                    "failed": True,
                    "error": f"{node.name}: {type(e).__name__}: {e}",
                }
            durations.append(time.perf_counter() - t0)
        durations.sort()
        return {
            "ticks": len(durations),
            "tick_p50_ms": round(durations[len(durations) // 2] * 1000, 3),
            "tick_max_ms": round(durations[-1] * 1000, 3),
        }

    def _phase_cardinality_storm(self) -> dict:
        """Push 10k+ distinct pod-series through every node's REAL
        bounded gauges (the sampler's export path) while the whole
        fleet is resident: the per-node series count must hold at the
        cap, eviction accounting must add up, and the RSS watcher
        running over this phase is what the memory ceiling is asserted
        against."""
        sim = self.sim
        per_node = max(1, self.cardinality_series_total // self.nodes)
        problems: List[str] = []
        total_inserted = 0
        for node in sim.nodes:
            gauge = node.metrics.pod_core_used
            before_count = gauge.series_count
            for i in range(per_node):
                gauge.set(float(i % 97), pod=f"storm/p-{i}")
            total_inserted += per_node
            cap = gauge._max
            if gauge.series_count > cap:
                problems.append(
                    f"{node.name}: {gauge.series_count} series > cap {cap}"
                )
            # eviction accounting: at least (inserted + pre-existing -
            # cap) series must have been counted out (the sampler may
            # be inserting concurrently, so >= not ==; exact accounting
            # is pinned single-writer in tests/test_cardinality.py)
            expect_evicted = before_count + per_node - cap
            if expect_evicted > 0:
                evicted = node.metrics.series_evicted._value.get()
                if evicted < expect_evicted:
                    problems.append(
                        f"{node.name}: evicted counter {evicted} < "
                        f"expected >= {expect_evicted}"
                    )
        return {
            "series_inserted": total_inserted,
            "per_node": per_node,
            "problems": problems,
        }

    # -- the run --------------------------------------------------------------

    def run(self) -> dict:
        self._refs: List[PodRef] = []
        self._last_churn_end_ts: Optional[float] = None
        watcher = RSSWatcher()
        sim = self.sim
        t_start = time.perf_counter()
        sim.start()
        startup_s = time.perf_counter() - t_start
        try:
            agg = FleetAggregator(sim.targets())
            phases = {}
            phases["admission_waves"] = self._phase_admission_waves()
            phases["steady_churn"] = self._phase_steady_churn()
            phases["drain_wave"] = self._phase_drain_wave()
            phases["slice_reform"] = self._phase_slice_reform()
            phases["repartition_ticks"] = self._phase_repartition_ticks()
            phases["cardinality_storm"] = self._phase_cardinality_storm()
            # Convergence measured from the LAST churn's end: every node
            # must reach a fully-converged reconcile pass with the whole
            # scenario's state resident.
            anchor = self._last_churn_end_ts or time.time()
            convergence = agg.convergence_summary(agg.wait_converged(
                anchor, timeout_s=self.convergence_timeout_s,
            ))
            rollup = agg.rollup()
            stored = sim.stored_binds()
            storage_stats = [
                node.storage.write_stats() for node in sim.nodes
            ]
            sink_stats = self._sink_stats()
            timeline_rows = sum(
                node.storage.timeline_count() for node in sim.nodes
            )
            timeline_evicted = sum(
                node.storage.timeline_evicted_total()
                for node in sim.nodes
            )
            # Goodput rollup: force a fresh ledger replay everywhere,
            # then read the fleet SLI through the aggregator — the
            # scale story must price its drain wave / reform /
            # repartition churn in downtime-by-cause, not just latency.
            sim.tick_goodput()
            fleet_goodput = agg.fleet_goodput()
            # Snapshot source-side counters BEFORE stop(): stop drops
            # the apiserver and swaps the sim's big trace ring back out.
            api_counts = dict(sim.apiserver.request_counts)
            api_total = sim.apiserver.requests_total()
            trace_ring_bytes = get_tracer().ring_bytes()
        finally:
            sim.stop()
        memory = watcher.stop()
        fleet = rollup["fleet"]
        binds = fleet["binds_total"] or 0
        storage_writes = sum(s["writes_total"] for s in storage_stats)
        storage_commits = sum(s["commits_total"] for s in storage_stats)
        # Series resident at peak: bounded gauges hold <= cap each, but
        # the CEILING is asserted against what was DRIVEN through the
        # process — the 10k+ storm plus two series per bound pod.
        series_driven = (
            phases["cardinality_storm"].get("series_inserted", 0)
            + 2 * len(self._refs)
        )
        rss_delta = memory["rss_delta_bytes"]
        return {
            "nodes": self.nodes,
            "pods": len(self._refs),
            "pods_per_node": self.pods_per_node,
            "startup_s": round(startup_s, 3),
            "batching": {
                "storage_batch_window_s": self.storage_batch_window_s,
                "sink_flush_window_s": self.sink_flush_window_s,
            },
            "phases": phases,
            "fleet_bind_p50_ms": fleet["fleet_bind_p50_ms"],
            "fleet_bind_p99_ms": fleet["fleet_bind_p99_ms"],
            "goodput": {
                **fleet_goodput["fleet"],
                "conservation_problems": (
                    fleet_goodput["conservation_problems"]
                ),
                "unreachable_nodes": fleet_goodput["unreachable"],
            },
            "binds_total": binds,
            "stored_binds": sum(stored.values()),
            "reconcile_convergence_s": convergence,
            "amplification": {
                "kubelet_lists_per_bind": (
                    fleet["request_amplification"]["kubelet_lists_per_bind"]
                ),
                "sink_writes_per_bind": (
                    fleet["request_amplification"]["sink_writes_per_bind"]
                ),
                "apiserver_requests_total": api_total,
                "apiserver_requests_per_bind": (
                    round(api_total / binds, 4) if binds else None
                ),
                "apiserver_request_counts": api_counts,
                "storage_writes_total": storage_writes,
                "storage_commits_total": storage_commits,
                "storage_commits_per_bind": (
                    round(storage_commits / binds, 4) if binds else None
                ),
                "storage_writes_per_commit": (
                    round(storage_writes / storage_commits, 3)
                    if storage_commits else None
                ),
                "sink": sink_stats,
            },
            "memory": {
                **memory,
                "series_driven": series_driven,
                "rss_delta_per_series_bytes": (
                    round(rss_delta / series_driven, 1)
                    if series_driven else None
                ),
                "trace_ring_bytes": trace_ring_bytes,
                "timeline_rows_total": timeline_rows,
                "timeline_evicted_total": timeline_evicted,
            },
        }

    def _sink_stats(self) -> dict:
        """Fleet-summed sink coalescing counters, read from the live
        recorders (merged = apiserver writes the coalescing window
        saved; dropped = queue-bound losses)."""
        out = {"writes": 0, "merged": 0, "dropped": 0}
        for node in self.sim.nodes:
            for rec in (node.manager.crd_recorder, node.manager.events):
                sink = getattr(rec, "_sink", None)
                if sink is None:
                    continue
                out["writes"] += sink.writes_total
                out["merged"] += sink.merged
                out["dropped"] += sink.dropped
        return out


def scale_problems(report: dict, bounds: Optional[dict] = None) -> List[str]:
    """Structural assertions over a scale OR chaos report (shared by
    `make scale-smoke`, `make chaos-matrix-smoke` and tests).

    Scale reports (ScaleHarness.report()): every bind lands, every node
    converges, request amplification stays within bound, memory holds
    its documented ceiling. Chaos reports (sim/chaos.py ScenarioRunner)
    carry a ``compound`` block instead, judged by the compound-scenario
    invariants: no stream drops or resets client-visibly, no bind
    double-lands, goodput/request-phase conservation holds through
    arbitrary fault overlap, every handoff is adopted, every open
    intent resolves, and no node replays a reclaimed bind. Returns
    problems (empty = the run held)."""
    b = {
        # kubelet Lists per bind: the fleet leg measures ~0.9; 2.0 is
        # the regression alarm, not the target.
        "kubelet_lists_per_bind": 2.0,
        # async sink writes per bind, per sink (events ~1, CRD ~1-2).
        "sink_writes_per_bind": 4.0,
        # apiserver requests per bind across ALL kinds (sink writes +
        # membership lists + GC gets).
        "apiserver_requests_per_bind": 6.0,
        # documented memory ceiling: RSS growth per driven pod-series
        # (docs/operations.md "Scale & capacity planning").
        "rss_delta_per_series_bytes": 64 * 1024,
        # the trace ring is capacity-bounded; its bytes must stay small
        # against the process (64 MiB is far past any healthy ring).
        "trace_ring_bytes": 64 * 1024 * 1024,
        # compound-scenario invariants (chaos reports): request-phase
        # conservation residual ceiling, and optional score floors a
        # smoke can raise (None = not enforced).
        "worst_residual_s": 0.05,
        "min_goodput_percent": None,
        "min_slo_attainment": None,
        **(bounds or {}),
    }
    problems: List[str] = []
    if "compound" in report:
        problems += _compound_problems(report, b)
        gp = report.get("goodput", {})
        if gp.get("goodput_percent") is None:
            problems.append("goodput: fleet rollup missing")
        for p in gp.get("conservation_problems", []):
            problems.append(f"goodput conservation: {p}")
        return problems
    phases = report.get("phases", {})
    adm = phases.get("admission_waves", {})
    if adm.get("bound") != adm.get("admitted") or adm.get("errors"):
        problems.append(
            f"admission waves: {adm.get('bound')}/{adm.get('admitted')} "
            f"bound, {adm.get('errors')} error(s)"
        )
    churn = phases.get("steady_churn", {})
    if not churn.get("skipped") and (
        churn.get("rebound") != churn.get("replaced") or churn.get("errors")
    ):
        problems.append(f"steady churn: {churn}")
    for name in ("drain_wave", "slice_reform", "repartition_ticks"):
        phase = phases.get(name, {})
        if phase.get("failed") or phase.get("problems"):
            problems.append(f"{name}: {phase}")
    storm = phases.get("cardinality_storm", {})
    for p in storm.get("problems", []):
        problems.append(f"cardinality storm: {p}")
    if report.get("stored_binds") != report.get("pods"):
        problems.append(
            f"stored binds {report.get('stored_binds')} != live pods "
            f"{report.get('pods')}"
        )
    conv = report.get("reconcile_convergence_s", {})
    if conv.get("unconverged_nodes"):
        problems.append(
            f"unconverged nodes: {conv['unconverged_nodes']}"
        )
    amp = report.get("amplification", {})
    checks = [
        ("kubelet_lists_per_bind", amp.get("kubelet_lists_per_bind")),
        ("apiserver_requests_per_bind",
         amp.get("apiserver_requests_per_bind")),
    ]
    for sink, value in (amp.get("sink_writes_per_bind") or {}).items():
        checks.append((f"sink_writes_per_bind ({sink})", value))
    for label, value in checks:
        bound_key = label.partition(" ")[0]
        if value is None:
            problems.append(f"{label}: missing")
        elif value > b[bound_key]:
            problems.append(f"{label}: {value} > bound {b[bound_key]}")
    mem = report.get("memory", {})
    per_series = mem.get("rss_delta_per_series_bytes")
    if per_series is None:
        problems.append("memory: rss_delta_per_series_bytes missing")
    elif per_series > b["rss_delta_per_series_bytes"]:
        problems.append(
            f"memory: {per_series} B/series > ceiling "
            f"{b['rss_delta_per_series_bytes']}"
        )
    ring = mem.get("trace_ring_bytes", 0)
    if ring > b["trace_ring_bytes"]:
        problems.append(
            f"trace ring {ring} B > bound {b['trace_ring_bytes']}"
        )
    if not report.get("fleet_bind_p99_ms"):
        problems.append("fleet bind p99 missing from scraped histograms")
    gp = report.get("goodput", {})
    if gp.get("goodput_percent") is None:
        problems.append("goodput: fleet rollup missing")
    for p in gp.get("conservation_problems", []):
        problems.append(f"goodput conservation: {p}")
    return problems


def _compound_problems(report: dict, b: dict) -> List[str]:
    """The compound-scenario invariant set (chaos reports): what must
    hold through ARBITRARY fault overlap, judged after recovery."""
    problems: List[str] = []
    c = report["compound"]
    streams = c.get("streams", {})
    if streams.get("admitted") != streams.get("finished"):
        problems.append(
            f"stream conservation: {streams.get('admitted')} admitted "
            f"!= {streams.get('finished')} finished"
        )
    for key in ("live_leftover", "pending_handoff_leftover"):
        if streams.get(key):
            problems.append(f"streams: {streams[key]} {key}")
    if streams.get("client_visible_drops"):
        problems.append(
            f"client-visible stream drops: "
            f"{streams['client_visible_drops']} "
            f"(reasons: {streams.get('finish_reasons')})"
        )
    h = c.get("handoffs", {})
    if h.get("published") != h.get("adopted", 0) + h.get("expired", 0):
        problems.append(
            f"handoffs: {h.get('published')} published != "
            f"{h.get('adopted')} adopted + {h.get('expired')} expired"
        )
    if h.get("expired"):
        problems.append(f"handoffs: {h['expired']} expired unadopted")
    residual = abs(c.get("worst_residual_s") or 0.0)
    if residual > b["worst_residual_s"]:
        problems.append(
            f"request-phase conservation: worst residual {residual}s > "
            f"{b['worst_residual_s']}s"
        )
    tokens = c.get("tokens", {})
    if tokens.get("emitted") != tokens.get("accounted"):
        problems.append(
            f"token conservation: {tokens.get('emitted')} emitted != "
            f"{tokens.get('accounted')} accounted"
        )
    binds = c.get("binds", {})
    if binds.get("double_lands"):
        problems.append(f"bind double-lands: {binds['double_lands']}")
    if binds.get("records_missing"):
        problems.append(
            f"serve binds missing after recovery: "
            f"{binds['records_missing']}"
        )
    if c.get("open_intents"):
        problems.append(
            f"open intents unresolved: {c['open_intents']}"
        )
    rec = report.get("recovery", {})
    if rec.get("binds_never_landed"):
        problems.append(
            f"binds never landed: {rec['binds_never_landed']}"
        )
    if rec.get("reclaimed_bind_replays"):
        problems.append(
            f"reclaimed binds replayed: "
            f"{rec['reclaimed_bind_replays']}"
        )
    if rec.get("reclaim_error"):
        problems.append(f"reclaim: {rec['reclaim_error']}")
    for p in rec.get("problems", []) or []:
        problems.append(f"recovery: {p}")
    floor = b.get("min_goodput_percent")
    gp = (report.get("goodput") or {}).get("goodput_percent")
    if floor is not None and (gp is None or gp < floor):
        problems.append(f"goodput {gp}% < floor {floor}%")
    att_floor = b.get("min_slo_attainment")
    if att_floor is not None:
        for slo, block in (report.get("slo") or {}).items():
            att = block.get("attainment")
            if att is not None and att < att_floor:
                problems.append(
                    f"SLO {slo} attainment {att} < floor {att_floor}"
                )
    return problems
