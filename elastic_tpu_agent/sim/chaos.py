"""Compound-fault chaos matrix: overlapping faults, scored by goodput.

Every fault the fleet survives in the scripted scenarios is injected
one at a time; the failures that reach production are the ones that
*compose* — a maintenance drain while the apiserver is browned out
while the storage batcher's disk is flaky, under a flash crowd. This
module turns the existing fault surfaces into a composable, seeded
vocabulary and replays them OVER live trace-driven traffic
(sim/traffic.py), so conservation invariants and fleet SLIs are checked
through arbitrary fault overlap rather than around hand-picked gaps.

Three layers, deliberately separated:

- :class:`ChaosProgram` — *pure data*. ``generate(seed, ...)`` draws a
  schedule of overlapping fault windows from one ``random.Random``
  stream; ``ops()`` compiles it to a start/stop timeline; ``lines()``/
  ``digest()`` are canonical bytes. Nothing here touches a clock or a
  fleet, which is what makes "same ``(trace_seed, chaos_seed)`` ⇒ same
  schedule" a byte-level guarantee, testable on a ManualClock.
- :class:`ScenarioRunner` — the executor: replays one trace + one
  program against a RUNNING FleetSim through the real admission paths
  (apiserver pod upserts, kubelet-shaped binds, RequestObservatory
  lifecycles with real cross-node handoff stitching on drain), then
  heals everything and scores the run with
  ``FleetAggregator.fleet_goodput()`` / ``fleet_slo()``. The report's
  ``compound`` block carries the conservation ledger that
  ``scale_problems()`` (sim/scale.py) judges: no client-visible stream
  drop, no bind double-land, every handoff adopted, every open intent
  resolved, request-phase residual ~0, goodput conservation clean.
- :class:`ChaosMatrix` — a bounded seeded scenario set plus the
  known-bad self-test (``sabotage``) that proves the checker trips.

Fault vocabulary (all composable, all reproducible from the seed):

=====================  ====================================================
``apiserver_brownout``  FakeAPIServer.set_brownout: seeded per-op 503
                        rate + latency window, healed at window end.
``failpoint``           faults.py registry window: arm ``point=spec`` at
                        start, disarm at end — brownout kinds
                        (``prob:``/``delay-range:``) compose here.
``maintenance_drain``   GCE maintenance notice on one node; its open
                        streams hand off to a survivor (the real
                        handoff_begin/adopt stitching), cleared at end.
``preemption``          spot preemption notice (never un-rings).
``kubelet_flap``        FakeKubelet.restart_registration(): socket torn
                        down and recreated; the agent must re-register.
``throttle``            the real usage-report → sampler → repartition
                        loop clamps a seeded hog pod for the window.
=====================  ====================================================

On any invariant violation the report carries (and bench prints) a
one-line repro: ``python bench.py --chaos-matrix-smoke --trace-seed S
--chaos-seed C --scenario NAME``.
"""

from __future__ import annotations

import hashlib
import json
import random
import time
from typing import Dict, Iterator, List, Optional

from .. import faults
from ..common import SYSTEM_CLOCK, Clock
from .traffic import Trace, TraceCursor, TraceGenerator

# Finish reasons the client SEES as a broken stream. Everything a
# healthy compound scenario produces must finish outside this set —
# a drained node's streams migrate (handoff/adopt), they do not drop.
CLIENT_VISIBLE_DROP_REASONS = frozenset(
    {"dropped", "reset", "evicted", "handoff_expired"}
)

# Per-block token share of a prompt (prefill cache attribution): chains
# are CHAIN_DEPTH blocks deep, cached tokens = hit blocks * share.
_TOKENS_PER_BLOCK_DIV = 8  # == traffic.CHAIN_DEPTH

# Synthetic decode pacing (seconds): small enough that scenarios finish
# in seconds, non-zero so streams stay OPEN across fault windows.
_SERVICE_FLOOR_S = 0.02
_PER_TOKEN_S = 0.0004


def repro_line(trace_seed: int, chaos_seed: int, scenario: str) -> str:
    """The one-line repro printed on any failure."""
    return (
        f"python bench.py --chaos-matrix-smoke --trace-seed {trace_seed} "
        f"--chaos-seed {chaos_seed} --scenario {scenario}"
    )


class ChaosProgram:
    """One seeded schedule of overlapping fault actions (pure data)."""

    def __init__(self, seed: int, actions: List[dict], meta: Dict) -> None:
        self.seed = seed
        self.actions = actions
        self.meta = meta

    # -- canonical serialization (determinism contract) -------------------

    def lines(self) -> List[str]:
        return [
            json.dumps(a, sort_keys=True, separators=(",", ":"))
            for a in self.actions
        ]

    def digest(self) -> str:
        h = hashlib.sha256()
        for line in self.lines():
            h.update(line.encode())
            h.update(b"\n")
        return h.hexdigest()[:16]

    # -- start/stop timeline ----------------------------------------------

    def ops(self) -> List[dict]:
        """Compile actions to a flat start/stop timeline: windowed
        actions yield two ops, instant ones a single ``start``. Sorted
        by time (stable tie-break on action id) — the schedule a
        ManualClock test steps through."""
        out: List[dict] = []
        for i, a in enumerate(self.actions):
            out.append({"t": a["t"], "op": "start", "id": i, "action": a})
            if a.get("duration_s"):
                out.append({
                    "t": round(a["t"] + a["duration_s"], 6),
                    "op": "stop", "id": i, "action": a,
                })
        out.sort(key=lambda o: (o["t"], o["id"], o["op"] == "start"))
        return out

    def end_t(self) -> float:
        return max(
            (a["t"] + a.get("duration_s", 0.0) for a in self.actions),
            default=0.0,
        )

    def overlaps(self) -> int:
        """How many action pairs overlap in time — the 'compound' in
        compound-fault; generate() guarantees at least one."""
        n = 0
        for i, a in enumerate(self.actions):
            a_end = a["t"] + a.get("duration_s", 0.0)
            for b in self.actions[i + 1:]:
                if b["t"] < a_end and a["t"] < b["t"] + b.get(
                    "duration_s", 0.0
                ):
                    n += 1
        return n

    # -- generation --------------------------------------------------------

    @classmethod
    def generate(
        cls,
        seed: int,
        duration_s: float = 4.0,
        nodes: int = 2,
        intensity: float = 1.0,
        include_drain: bool = True,
        include_throttle: bool = False,
        include_preemption: bool = False,
    ) -> "ChaosProgram":
        """Draw a schedule of overlapping fault windows from one seeded
        stream. Windows are long relative to the scenario (30-60%), so
        overlap is the common case; if the draw happens to produce a
        disjoint schedule, the second action is pulled into the first's
        window — compound by construction, still a pure function of the
        seed."""
        rng = random.Random(seed)
        acts: List[dict] = []

        def window(frac_lo: float, frac_hi: float):
            dur = duration_s * rng.uniform(frac_lo, frac_hi)
            start = rng.uniform(0.0, max(duration_s - dur, 1e-6))
            return round(start, 6), round(dur, 6)

        # Always: an apiserver brownout (the fleet's loudest shared
        # dependency) and a flaky group-commit disk.
        t, d = window(0.3, 0.6)
        acts.append({
            "kind": "apiserver_brownout", "t": t, "duration_s": d,
            "error_rate": round(rng.uniform(0.15, 0.35), 4),
            "latency_s": round(rng.uniform(0.0, 0.005), 6),
            "seed": rng.randrange(1 << 30),
        })
        t, d = window(0.3, 0.6)
        acts.append({
            "kind": "failpoint", "t": t, "duration_s": d,
            "point": "storage.batch_flush",
            "spec": f"prob:{round(rng.uniform(0.05, 0.2), 4)}"
                    f":{rng.randrange(1 << 30)}",
        })
        # Jittery-slow kubelet pod-resources answers.
        t, d = window(0.2, 0.5)
        acts.append({
            "kind": "failpoint", "t": t, "duration_s": d,
            "point": "podresources.list",
            "spec": f"delay-range:0.001:0.02:{rng.randrange(1 << 30)}",
        })
        if include_drain and nodes >= 2:
            t, d = window(0.25, 0.45)
            drain_node = rng.randrange(1, nodes)
            acts.append({
                "kind": "maintenance_drain", "t": t, "duration_s": d,
                "node": drain_node,
            })
            if include_preemption:
                # the migration killer compound: the host backing the
                # DRAINING node rings a spot-preemption notice mid-
                # window, so the pre-copy/cutover budget clamps to the
                # shorter horizon while its streams are handing off
                acts.append({
                    "kind": "preemption",
                    "t": round(t + d * rng.uniform(0.25, 0.5), 6),
                    "node": drain_node,
                })
        elif include_preemption and nodes >= 2:
            t, _ = window(0.25, 0.45)
            acts.append({
                "kind": "preemption", "t": t,
                "node": rng.randrange(1, nodes),
            })
        if include_throttle:
            t, d = window(0.25, 0.45)
            acts.append({
                "kind": "throttle", "t": t, "duration_s": d, "node": 0,
            })
        # A kubelet socket flap lands somewhere in the middle third.
        acts.append({
            "kind": "kubelet_flap",
            "t": round(rng.uniform(
                duration_s / 3.0, 2.0 * duration_s / 3.0
            ), 6),
            "node": rng.randrange(nodes),
        })
        # Intensity scales extra brownout-kind failpoints.
        for _ in range(max(0, round(intensity) - 1)):
            t, d = window(0.2, 0.4)
            acts.append({
                "kind": "failpoint", "t": t, "duration_s": d,
                "point": "sitter.relist",
                "spec": f"prob:{round(rng.uniform(0.05, 0.15), 4)}"
                        f":{rng.randrange(1 << 30)}",
            })
        prog = cls(seed, acts, {})
        if prog.overlaps() == 0 and len(acts) >= 2:
            # pull the second window into the first: overlap guaranteed
            first = acts[0]
            acts[1]["t"] = round(
                first["t"] + first.get("duration_s", 0.0) / 2.0, 6
            )
        acts.sort(key=lambda a: (a["t"], a["kind"]))
        prog.meta = {
            "chaos_seed": seed,
            "duration_s": duration_s,
            "nodes": nodes,
            "intensity": intensity,
            "actions": len(acts),
            "overlapping_pairs": prog.overlaps(),
            "kinds": sorted({a["kind"] for a in acts}),
        }
        return prog


class OpCursor:
    """Time-ordered consumption of a program's start/stop ops; like
    traffic.TraceCursor, it never reads a clock — the driver (or a
    ManualClock test) supplies ``now``."""

    def __init__(self, ops: List[dict]) -> None:
        self._ops = ops
        self._i = 0

    @property
    def exhausted(self) -> bool:
        return self._i >= len(self._ops)

    def due(self, now: float) -> Iterator[dict]:
        while self._i < len(self._ops) and self._ops[self._i]["t"] <= now:
            op = self._ops[self._i]
            self._i += 1
            yield op

    def drain(self) -> Iterator[dict]:
        return self.due(float("inf"))


class ScenarioRunner:
    """Replay one (trace, program) pair against a running FleetSim and
    score it.

    The runner is the only layer with side effects: it routes trace
    requests into per-node RequestObservatories (attached to each
    node's real metrics endpoint, so ``fleet_slo`` scrapes them the
    production way), admits/binds train-tenant pods through the real
    apiserver + kubelet-shaped bind path, applies chaos ops as they
    come due, migrates open streams off draining nodes via the real
    handoff/adopt stitching, then HEALS (disarm, clear, retry, reclaim)
    and scores. ``sabotage`` deliberately breaks stream accounting
    ("drop-streams": every finish becomes a client-visible drop) so the
    known-bad self-test can prove the checker trips.
    """

    def __init__(
        self,
        fleet,
        trace: Trace,
        program: ChaosProgram,
        name: str = "scenario",
        serve_pods_per_node: int = 2,
        sabotage: Optional[str] = None,
        tick_s: float = 0.01,
        settle_timeout_s: float = 60.0,
        clock: Clock = SYSTEM_CLOCK,
    ) -> None:
        self.fleet = fleet
        self.trace = trace
        self.program = program
        self.name = name
        self.serve_pods_per_node = serve_pods_per_node
        self.sabotage = sabotage
        self.tick_s = tick_s
        self.settle_timeout_s = settle_timeout_s
        self.clock = clock
        # runtime state
        self.obs: Dict[int, object] = {}      # node idx -> observatory
        self.open: Dict[int, dict] = {}       # rid -> stream state
        self.unavailable: set = set()         # draining/preempted nodes
        self.seen_chains: Dict[int, set] = {}
        self.train_refs: Dict[str, object] = {}
        self.train_deleted: set = set()
        self.pending_binds: List[object] = []
        self.bind_errors: List[str] = []
        self.admitted = 0
        self.emitted_tokens = 0
        self.routed_rr = 0
        self.throttle_nodes: Dict[int, dict] = {}
        self.execution_log: List[dict] = []

    # -- routing -----------------------------------------------------------

    def _healthy_idxs(self) -> List[int]:
        return [
            i for i, node in enumerate(self.fleet.nodes)
            if not node.dead and i not in self.unavailable
        ]

    def _route(self) -> int:
        healthy = self._healthy_idxs()
        if not healthy:  # every node faulted: degrade, don't drop
            healthy = [
                i for i, n in enumerate(self.fleet.nodes) if not n.dead
            ]
        self.routed_rr += 1
        return healthy[self.routed_rr % len(healthy)]

    # -- trace-event side --------------------------------------------------

    def _dispatch_request(self, ev: dict, now: float) -> None:
        idx = self._route()
        obs = self.obs[idx]
        uid = obs.admit(self.fleet.nodes[idx].name, slo=ev["slo"])
        obs.prefill_start(uid)
        seen = self.seen_chains.setdefault(idx, set())
        per_block = max(1, ev["prompt_tokens"] // _TOKENS_PER_BLOCK_DIV)
        hits = 0
        for d in ev["chain"]:
            if d in seen:
                hits += 1
            else:
                break  # prefix cache: a miss ends the cached run
        seen.update(ev["chain"])
        cached = min(hits * per_block, ev["prompt_tokens"])
        obs.prefill_done(
            uid,
            cached_tokens=cached,
            computed_tokens=ev["prompt_tokens"] - cached,
            prefix_digest=ev["chain"][-1],
            chain_digests=tuple(ev["chain"]),
        )
        obs.first_token(uid)
        self.admitted += 1
        self.emitted_tokens += 1  # first_token counts one
        self.open[ev["rid"]] = {
            "uid": uid,
            "node": idx,
            "tokens_left": max(0, ev["output_tokens"] - 1),
            "finish_t": now + _SERVICE_FLOOR_S
            + ev["output_tokens"] * _PER_TOKEN_S,
        }

    def _dispatch_pod(self, ev: dict) -> None:
        name = ev["pod"]
        if ev["kind"] == "pod_admit":
            idx = self._route()
            ref = self.fleet.admit_pod("train", name, idx)
            self.train_refs[name] = ref
            self.pending_binds.append(ref)
        else:  # pod_delete
            ref = self.train_refs.get(name)
            if ref is None or name in self.train_deleted:
                return
            self.pending_binds = [
                r for r in self.pending_binds if r is not ref
            ]
            self.fleet.delete_pods([ref])
            self.train_deleted.add(name)

    def _try_pending_binds(self) -> None:
        """Opportunistic binds: under a brownout or flush fault a bind
        may legitimately fail (FaultError/GroupCommitError surface as
        the kubelet seeing an Allocate error) — it stays queued and is
        retried; recovery drains the queue after the faults heal."""
        still: List[object] = []
        for ref in self.pending_binds:
            if self.fleet.nodes[ref.node_idx].dead:
                still.append(ref)
                continue
            try:
                self.fleet.bind_pod(ref)
            except Exception as e:  # noqa: BLE001 - chaos-era failure
                self.bind_errors.append(
                    f"{ref.pod_key}: {type(e).__name__}"
                )
                still.append(ref)
        self.pending_binds = still

    def _finish_due(self, now: float) -> None:
        done = [
            rid for rid, st in self.open.items()
            if st["finish_t"] <= now
        ]
        for rid in done:
            st = self.open.pop(rid)
            obs = self.obs[st["node"]]
            if st["tokens_left"]:
                obs.tokens_emitted(st["uid"], st["tokens_left"])
                self.emitted_tokens += st["tokens_left"]
            reason = (
                "dropped" if self.sabotage == "drop-streams"
                else "released"
            )
            obs.finish(st["uid"], reason)

    # -- chaos-op side -----------------------------------------------------

    def _migrate_streams_off(self, idx: int) -> None:
        """The drain story's client half: every open stream on the
        draining node hands off (real handoff_begin/adopt stitching)
        to a healthy node and keeps decoding there — TTFT/conservation
        accounting continues on the SAME record."""
        src = self.obs[idx]
        healthy = [i for i in self._healthy_idxs() if i != idx]
        if not healthy:
            return  # nowhere to go; streams finish in place
        for st in self.open.values():
            if st["node"] != idx:
                continue
            rec = src.handoff_begin(st["uid"])
            if rec is None:
                continue
            dst_idx = healthy[self.routed_rr % len(healthy)]
            self.routed_rr += 1
            dst = self.obs[dst_idx]
            st["uid"] = dst.adopt(rec, self.fleet.nodes[dst_idx].name)
            st["node"] = dst_idx

    def _throttle_drive(self, idx: int, hog_duty: float) -> None:
        from ..workloads.telemetry import write_usage_report

        state = self.throttle_nodes.get(idx)
        if state is None:
            return
        node = self.fleet.nodes[idx]
        now = time.time()
        write_usage_report(
            node.opts.alloc_spec_dir, state["calm_hash"], 2.0, ts=now
        )
        write_usage_report(
            node.opts.alloc_spec_dir, state["hog_hash"], hog_duty, ts=now
        )
        node.manager.sampler.sample_once(now=now)
        node.manager.repartition.tick(now=now)

    def _throttle_start(self, idx: int) -> None:
        from ..common import AnnotationRepartition

        ann = {AnnotationRepartition: "true"}
        calm = self.fleet.admit_pod(
            "qos", f"calm-{idx}", idx, chip=2, annotations=ann
        )
        hog = self.fleet.admit_pod(
            "qos", f"hog-{idx}", idx, chip=2, annotations=ann
        )
        self.fleet.wait_synced([calm, hog])
        # The throttle window opens DURING other fault windows (that is
        # the matrix's whole point), so these binds can hit an injected
        # flush failure exactly like the train-tenant binds — retry
        # through it rather than letting one unlucky draw kill the
        # scenario. Persistent failure surfaces as a violation: the
        # refs go to pending_binds and recovery's never-landed check.
        for ref in (calm, hog):
            deadline = time.monotonic() + 5.0
            while True:
                try:
                    self.fleet.bind_pod(ref)
                    break
                except Exception as e:  # noqa: BLE001 - chaos-era
                    self.bind_errors.append(
                        f"{ref.pod_key}: {type(e).__name__}"
                    )
                    if time.monotonic() > deadline:
                        self.pending_binds.append(ref)
                        return  # no throttle state: binds never landed
                    time.sleep(0.05)
        self.throttle_nodes[idx] = {
            "calm_hash": self.fleet.alloc_hash_of(calm),
            "hog_hash": self.fleet.alloc_hash_of(hog),
            "refs": [calm, hog],
            "active": True,
            "was_throttled": False,
        }

    def _apply_op(self, op: dict, now: float) -> None:
        a = op["action"]
        kind, phase = a["kind"], op["op"]
        self.execution_log.append({
            "t": round(now, 4), "op": phase, "kind": kind,
        })
        registry = faults.get_registry()
        if kind == "apiserver_brownout":
            if phase == "start":
                self.fleet.apiserver.set_brownout(
                    error_rate=a["error_rate"],
                    latency_s=a.get("latency_s", 0.0),
                    seed=a["seed"],
                )
            else:
                self.fleet.apiserver.clear_brownout()
        elif kind == "failpoint":
            if phase == "start":
                registry.arm(a["point"], a["spec"])
            else:
                registry.disarm(a["point"])
        elif kind == "maintenance_drain":
            if phase == "start":
                self.unavailable.add(a["node"])
                self._migrate_streams_off(a["node"])
                self.fleet.trigger_maintenance(a["node"])
            else:
                self.fleet.clear_maintenance(a["node"])
                # routing stays off the node until scenario end: the
                # drain orchestrator un-cordons on its own schedule
        elif kind == "preemption":
            self.unavailable.add(a["node"])
            self._migrate_streams_off(a["node"])
            self.fleet.trigger_preemption(a["node"])
        elif kind == "kubelet_flap":
            self.fleet.nodes[a["node"]].kubelet.restart_registration()
        elif kind == "throttle":
            if phase == "start":
                self._throttle_start(a["node"])
            else:
                state = self.throttle_nodes.get(a["node"])
                if state:
                    state["active"] = False

    # -- main loop ---------------------------------------------------------

    def run(self) -> dict:
        from ..workloads.request_obs import RequestObservatory

        wall_t0 = time.perf_counter()
        for i, node in enumerate(self.fleet.nodes):
            if node.dead:
                continue
            obs = RequestObservatory(max_finished=65536)
            node.metrics.attach_requests(obs)
            self.obs[i] = obs

        # Serve-tenant homes, bound through the real paths BEFORE any
        # fault window opens.
        serve_refs = self.fleet.admit_pods(
            self.serve_pods_per_node, namespace="serve"
        )
        self.fleet.wait_synced(serve_refs)
        for ref in serve_refs:
            self.fleet.bind_pod(ref)

        tcur = TraceCursor(self.trace)
        ocur = OpCursor(self.program.ops())
        horizon = max(
            self.trace.meta["duration_s"], self.program.end_t()
        )
        t0 = self.clock.monotonic()
        deadline = t0 + self.settle_timeout_s
        while True:
            now = self.clock.monotonic() - t0
            for op in ocur.due(now):
                self._apply_op(op, now)
            for ev in tcur.due(now):
                if ev["kind"] == "request":
                    self._dispatch_request(ev, now)
                elif ev["kind"].startswith("pod_"):
                    self._dispatch_pod(ev)
            self._try_pending_binds()
            self._finish_due(now)
            for idx, state in self.throttle_nodes.items():
                if state["active"]:
                    self._throttle_drive(idx, 90.0)
                    if "qos/hog-%d" % idx in self.fleet.nodes[
                        idx
                    ].manager.repartition.status()["throttled_pods"]:
                        state["was_throttled"] = True
            if (
                now >= horizon
                and not self.open
                and tcur.exhausted
                and ocur.exhausted
            ):
                break
            if self.clock.monotonic() > deadline:
                break  # scored anyway; leftovers become violations
            time.sleep(self.tick_s)

        recovery = self._recover()
        report = self._score(serve_refs)
        report["recovery"] = recovery
        report["wall_s"] = round(time.perf_counter() - wall_t0, 3)
        return report

    # -- recovery ----------------------------------------------------------

    def _recover(self) -> dict:
        """Heal the world, then let in-flight work converge: faults
        disarmed, brownout cleared, drains cancelled, queued binds
        retried, hogs unthrottled, train tenants reclaimed. A scenario
        that cannot recover to a clean fleet IS a finding — leftovers
        surface through the compound invariants."""
        out: Dict[str, object] = {}
        for op in self.program.ops():
            if op["op"] == "stop":
                # stops that never came due (scenario ended inside a
                # window) must still apply so arm/brownout state cannot
                # leak; re-applying an executed stop is a no-op (disarm
                # of an unarmed point, clearing a cleared brownout).
                self._apply_op(op, -1.0)
        faults.get_registry().disarm()
        self.fleet.apiserver.clear_brownout()
        for idx in list(self.unavailable):
            try:
                self.fleet.clear_maintenance(idx)
            except Exception:  # noqa: BLE001 - preempted nodes keep it
                pass
        self._finish_due(float("inf"))
        deadline = time.monotonic() + self.settle_timeout_s / 2.0
        attempts = 0
        while self.pending_binds and time.monotonic() < deadline:
            attempts += 1
            self._try_pending_binds()
            if self.pending_binds:
                time.sleep(0.05)
        out["bind_retry_rounds"] = attempts
        out["binds_never_landed"] = [
            r.pod_key for r in self.pending_binds
        ]
        # unthrottle any still-clamped hog (drive good behavior)
        for idx, state in self.throttle_nodes.items():
            t_end = time.monotonic() + 10.0
            while (
                "qos/hog-%d" % idx in self.fleet.nodes[idx].manager
                .repartition.status()["throttled_pods"]
            ):
                if time.monotonic() > t_end:
                    out.setdefault("problems", []).append(
                        f"hog-{idx} never unthrottled"
                    )
                    break
                self._throttle_drive(idx, 5.0)
                time.sleep(0.05)
        # train tenants: delete whatever the trace left admitted, then
        # require every deleted pod's bind to be reclaimed (GC through
        # the healed apiserver) — a replay afterwards is a violation.
        leftover = [
            ref for name, ref in self.train_refs.items()
            if name not in self.train_deleted
        ]
        if leftover:
            self.fleet.delete_pods(leftover)
        reclaim_refs = [
            ref for ref in self.train_refs.values()
            if not self.fleet.nodes[ref.node_idx].dead
        ]
        try:
            out["reclaim_wait_s"] = round(self.fleet.wait_reclaimed(
                reclaim_refs, timeout_s=self.settle_timeout_s / 2.0
            ), 3)
        except RuntimeError as e:
            out["reclaim_error"] = str(e)
        # replay check: one reconcile period later the records must
        # still be gone (a reconciler replaying a reclaimed bind is
        # exactly the class of bug the matrix exists to catch)
        time.sleep(min(1.0, 2.0 * self.fleet.reconcile_period_s))
        replays = [
            ref.pod_key for ref in reclaim_refs
            if self.fleet.nodes[ref.node_idx].storage.load(
                ref.namespace, ref.name
            ) is not None
        ]
        out["reclaimed_bind_replays"] = replays
        self.fleet.tick_goodput()
        return out

    # -- scoring -----------------------------------------------------------

    def _records_of(self, ref) -> int:
        node = self.fleet.nodes[ref.node_idx]
        if node.dead:
            return -1  # unknowable; not a double-land
        info = node.storage.load(ref.namespace, ref.name)
        if info is None:
            return 0
        return sum(1 for _ in info.records())

    def _score(self, serve_refs) -> dict:
        from .aggregator import FleetAggregator

        agg = FleetAggregator(self.fleet.targets())
        goodput = agg.fleet_goodput()
        slo = agg.fleet_slo()

        finished = live = pending = 0
        reasons: Dict[str, int] = {}
        published = adopted = 0
        worst_residual = 0.0
        accounted_tokens = 0
        for obs in self.obs.values():
            finished += obs.finished_total
            live += obs.live_count
            pending += obs.pending_handoff_count
            published += obs.handoffs_published
            adopted += obs.handoffs_adopted
            for reason, n in obs.finish_reasons.items():
                reasons[reason] = reasons.get(reason, 0) + n
            worst = obs._worst_residual_s
            if abs(worst) > abs(worst_residual):
                worst_residual = worst
            accounted_tokens += sum(
                rec.tokens for rec in obs._finished
            )
        drops = sum(
            n for r, n in reasons.items()
            if r in CLIENT_VISIBLE_DROP_REASONS
        )
        expired = reasons.get("handoff_expired", 0)

        double_lands = missing = 0
        for ref in serve_refs:
            n = self._records_of(ref)
            if n > 1:
                double_lands += 1
            elif n == 0 and ref.node_idx not in self.unavailable:
                missing += 1
        open_intents = sum(
            len(node.storage.open_intents())
            for node in self.fleet.nodes if not node.dead
        )
        throttles = {
            f"node-{idx}": state["was_throttled"]
            for idx, state in self.throttle_nodes.items()
        }
        return {
            "scenario": self.name,
            "trace": {**self.trace.meta, "digest": self.trace.digest()},
            "program": {
                **self.program.meta, "digest": self.program.digest(),
            },
            "repro": repro_line(
                self.trace.seed, self.program.seed, self.name
            ),
            "goodput": {
                **goodput["fleet"],
                "conservation_problems": goodput[
                    "conservation_problems"
                ],
                "unreachable_nodes": goodput["unreachable"],
            },
            "slo": slo["fleet"]["classes"],
            "compound": {
                "streams": {
                    "admitted": self.admitted,
                    "finished": finished,
                    "live_leftover": live,
                    "pending_handoff_leftover": pending,
                    "client_visible_drops": drops,
                    "finish_reasons": reasons,
                },
                "handoffs": {
                    "published": published,
                    "adopted": adopted,
                    "expired": expired,
                },
                "worst_residual_s": round(worst_residual, 6),
                "tokens": {
                    "emitted": self.emitted_tokens,
                    "accounted": accounted_tokens,
                },
                "binds": {
                    "serve_pods": len(serve_refs),
                    "double_lands": double_lands,
                    "records_missing": missing,
                    "bind_errors_during_faults": len(self.bind_errors),
                },
                "open_intents": open_intents,
                "throttled": throttles,
            },
        }


class ChaosMatrix:
    """A bounded, seeded set of compound scenarios; every verdict
    reproducible from ``(trace_seed, chaos_seed)``."""

    def __init__(
        self,
        trace_seed: int = 1,
        chaos_seed: int = 1,
        scenarios: Optional[List[dict]] = None,
        nodes: int = 2,
        serve_pods_per_node: int = 2,
        enable_events: bool = True,
    ) -> None:
        self.trace_seed = trace_seed
        self.chaos_seed = chaos_seed
        self.nodes = nodes
        self.serve_pods_per_node = serve_pods_per_node
        # Poll-only mode (events.py disabled): the matrix must stay
        # green either way — the periodic sweeps remain the correctness
        # backstop, events are only an acceleration.
        self.enable_events = enable_events
        self.scenarios = scenarios or self.default_scenarios()

    def default_scenarios(self) -> List[dict]:
        return [
            {
                "name": "brownout-flash-crowd",
                "trace": {
                    "duration_s": 2.5, "base_rps": 24.0,
                    "flash_crowds": 1, "hostile_fraction": 0.3,
                    "train_pods": 2,
                },
                "program": {
                    "duration_s": 2.5, "include_drain": False,
                },
            },
            {
                "name": "drain-under-hostile-prefix",
                "trace": {
                    "duration_s": 3.0, "base_rps": 16.0,
                    "flash_crowds": 1, "hostile_fraction": 0.9,
                    "train_pods": 2,
                },
                "program": {
                    "duration_s": 3.0, "include_drain": True,
                },
            },
            {
                # A spot-preemption notice rings on the node ALREADY
                # mid-migration (draining, streams handing off) while a
                # flash crowd runs — the live-migration acceptance
                # scenario: zero client-visible drops/resets, every
                # handoff adopted, and goodput/SLO floors hold.
                "name": "preemption-during-migration",
                "trace": {
                    "duration_s": 3.0, "base_rps": 20.0,
                    "flash_crowds": 1, "hostile_fraction": 0.3,
                    "train_pods": 2,
                },
                "program": {
                    "duration_s": 3.0, "include_drain": True,
                    "include_preemption": True,
                },
                "bounds": {
                    "min_goodput_percent": 25.0,
                    "min_slo_attainment": 0.5,
                },
            },
        ]

    def _seeds_for(self, i: int, spec: Optional[dict] = None):
        """Per-scenario sub-seeds. A spec carrying an explicit
        ``index`` (a filtered run, e.g. bench --scenario) keeps the
        seeds it had at its position in the full matrix — the repro
        line must rebuild the exact same trace and program."""
        idx = spec.get("index", i) if spec else i
        return self.trace_seed + 1000 * idx, self.chaos_seed + 1000 * idx

    def schedules(self) -> List[dict]:
        """Generate (but do not execute) every scenario's trace+program
        — the cheap half a determinism check runs twice."""
        out = []
        for i, spec in enumerate(self.scenarios):
            ts, cs = self._seeds_for(i, spec)
            trace = TraceGenerator(seed=ts, **spec["trace"]).generate()
            program = ChaosProgram.generate(
                seed=cs, nodes=self.nodes, **spec["program"]
            )
            out.append({
                "scenario": spec["name"],
                "trace_digest": trace.digest(),
                "program_digest": program.digest(),
                "trace_events": len(trace.events),
                "program_actions": len(program.actions),
                "overlapping_pairs": program.meta["overlapping_pairs"],
            })
        return out

    def schedule_digest(self) -> str:
        h = hashlib.sha256()
        for s in self.schedules():
            h.update(s["trace_digest"].encode())
            h.update(s["program_digest"].encode())
        return h.hexdigest()[:16]

    def _run_one(
        self, i: int, spec: dict, base_dir: str,
        sabotage: Optional[str] = None,
    ) -> dict:
        import os

        from .fleet import FleetSim
        from .scale import scale_problems

        ts, cs = self._seeds_for(i, spec)
        trace = TraceGenerator(seed=ts, **spec["trace"]).generate()
        program = ChaosProgram.generate(
            seed=cs, nodes=self.nodes, **spec["program"]
        )
        sim = FleetSim(
            os.path.join(base_dir, f"s{i}"),
            nodes=self.nodes,
            reconcile_period_s=0.5,
            slice_membership_ttl_s=0.25,
            drain_deadline_s=30.0,  # scenarios end before the deadline
            drain_period_s=0.25,
            migration_period_s=0.1,
            goodput_period_s=3600.0,  # ticked explicitly
            enable_sampler=True,
            sampler_period_s=3600.0,  # throttle drives by hand
            repartition_period_s=3600.0,
            storage_batch_window_s=0.004,  # flush faults need batching
            sink_flush_window_s=0.02,
            enable_events=self.enable_events,
        )
        os.makedirs(os.path.join(base_dir, f"s{i}"), exist_ok=True)
        try:
            sim.start()
            runner = ScenarioRunner(
                sim, trace, program,
                name=spec["name"],
                serve_pods_per_node=self.serve_pods_per_node,
                sabotage=sabotage,
            )
            report = runner.run()
        finally:
            faults.get_registry().disarm()
            sim.stop()
        report["problems"] = scale_problems(
            report, spec.get("bounds")
        )
        return report

    def run(self, base_dir: str) -> dict:
        """Execute every scenario; the matrix verdict is the union of
        per-scenario problems (empty = the ugly day was served)."""
        results = []
        problems: List[str] = []
        for i, spec in enumerate(self.scenarios):
            report = self._run_one(i, spec, base_dir)
            results.append(report)
            for p in report["problems"]:
                problems.append(f"{spec['name']}: {p}")
        return {
            "trace_seed": self.trace_seed,
            "chaos_seed": self.chaos_seed,
            "schedule_digest": self.schedule_digest(),
            "scenarios": results,
            "problems": problems,
        }

    def self_test(self, base_dir: str) -> dict:
        """Known-bad run: sabotaged stream accounting must trip the
        checker — a matrix whose checker cannot fail is not a check."""
        spec = self.scenarios[0]
        report = self._run_one(
            0, spec, base_dir, sabotage="drop-streams"
        )
        return {
            "tripped": bool(report["problems"]),
            "problems": report["problems"][:5],
            "repro": report["repro"],
        }
