"""FleetAggregator: the fleet-level read side of the observatory.

Scrapes every agent's /metrics endpoint (prometheus text format — the
same bytes a production Prometheus would ingest), then rolls the node
samples up into the three fleet questions ROADMAP item 1 asks:

- **fleet bind latency**: per-node elastic_tpu_prestart_seconds
  histograms merged bucket-wise, quantiles estimated the
  histogram_quantile() way (linear interpolation inside the bucket) —
  so fleet p50/p99 is computed from scraped data, not from driver-side
  stopwatches (the driver's exact percentiles ride along as a
  cross-check).
- **reconcile convergence**: per-node
  elastic_tpu_reconcile_last_converged_timestamp; convergence time
  after an event (churn end, fault clear) = first converged timestamp
  past the anchor, minus the anchor.
- **request amplification**: elastic_tpu_kubelet_list_total and
  elastic_tpu_sink_writes_total{sink=} divided by binds — how many
  kubelet Lists and apiserver sink writes the fleet pays per bind.

Trace continuity rides the same targets' /debug/traces?trace=<id>
endpoint: admission stamps the id, the binding agent adopts it, and the
aggregator follows it to the node that bound the pod.
"""

from __future__ import annotations

import json
import math
import statistics
import time
import urllib.request
from typing import Dict, List, Optional, Tuple


def _parse_le(value: str) -> float:
    return math.inf if value == "+Inf" else float(value)


def histogram_quantile(
    buckets: Dict[float, float], q: float
) -> Optional[float]:
    """Prometheus-style quantile estimate over cumulative ``le ->
    count`` buckets (merged across nodes by summing counts per bound).
    Returns seconds, or None for an empty histogram. Values past the
    largest finite bucket clamp to that bound, like histogram_quantile().
    """
    if not buckets:
        return None
    bounds = sorted(buckets)
    total = buckets[bounds[-1]]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_count = 0.0, 0.0
    for le in bounds:
        count = buckets[le]
        if count >= rank:
            if math.isinf(le):
                # +Inf bucket: report the largest finite bound
                return prev_le
            if count == prev_count:
                return le
            return prev_le + (le - prev_le) * (
                (rank - prev_count) / (count - prev_count)
            )
        prev_le, prev_count = le, count
    return bounds[-1] if not math.isinf(bounds[-1]) else prev_le


class NodeScrape:
    """One node's parsed /metrics payload: sample name -> [(labels,
    value)], plus O(1) helpers."""

    def __init__(self, samples: Dict[str, List[Tuple[dict, float]]]) -> None:
        self.samples = samples

    def value(
        self, name: str, labels: Optional[dict] = None, default: float = 0.0
    ) -> float:
        for sample_labels, value in self.samples.get(name, []):
            if labels is None or all(
                sample_labels.get(k) == v for k, v in labels.items()
            ):
                return value
        return default

    def buckets(self, histogram: str) -> Dict[float, float]:
        out: Dict[float, float] = {}
        for sample_labels, value in self.samples.get(
            f"{histogram}_bucket", []
        ):
            if "le" in sample_labels:
                out[_parse_le(sample_labels["le"])] = value
        return out


class FleetAggregator:
    def __init__(
        self, targets: Dict[str, str], timeout_s: float = 5.0
    ) -> None:
        self.targets = dict(targets)  # node name -> http://host:port
        self.timeout_s = timeout_s

    # -- scraping -------------------------------------------------------------

    def _get(self, url: str) -> bytes:
        with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
            return resp.read()

    def scrape_node(self, node: str) -> NodeScrape:
        from prometheus_client.parser import text_string_to_metric_families

        text = self._get(f"{self.targets[node]}/metrics").decode()
        samples: Dict[str, List[Tuple[dict, float]]] = {}
        for family in text_string_to_metric_families(text):
            for sample in family.samples:
                samples.setdefault(sample.name, []).append(
                    (dict(sample.labels), sample.value)
                )
        return NodeScrape(samples)

    def scrape(self) -> Dict[str, NodeScrape]:
        return {node: self.scrape_node(node) for node in self.targets}

    # -- the fleet rollup -----------------------------------------------------

    def rollup(
        self, scrapes: Optional[Dict[str, NodeScrape]] = None
    ) -> dict:
        """One fleet snapshot: per-node rows plus the fleet aggregates
        (merged-histogram bind quantiles, request-amplification ratios,
        convergence timestamps)."""
        if scrapes is None:
            scrapes = self.scrape()
        per_node: Dict[str, dict] = {}
        merged_bind: Dict[float, float] = {}
        totals = {
            "binds": 0.0, "allocates": 0.0, "kubelet_lists": 0.0,
            "sink_writes_events": 0.0, "sink_writes_crd": 0.0,
            "series_evicted": 0.0,
        }
        for node, scrape in scrapes.items():
            binds = scrape.value("elastic_tpu_prestart_seconds_count")
            row = {
                "binds": binds,
                "allocates": scrape.value(
                    "elastic_tpu_allocate_seconds_count"
                ),
                "bound_allocations": scrape.value(
                    "elastic_tpu_bound_allocations"
                ),
                "kubelet_lists": scrape.value(
                    "elastic_tpu_kubelet_list_total"
                ),
                "sink_writes": {
                    "events": scrape.value(
                        "elastic_tpu_sink_writes_total", {"sink": "events"}
                    ),
                    "crd": scrape.value(
                        "elastic_tpu_sink_writes_total", {"sink": "crd"}
                    ),
                },
                "reconcile_runs": scrape.value(
                    "elastic_tpu_reconcile_runs_total"
                ),
                "reconcile_last_converged_ts": scrape.value(
                    "elastic_tpu_reconcile_last_converged_timestamp"
                ),
                "reconcile_duration_p50_s": histogram_quantile(
                    scrape.buckets("elastic_tpu_reconcile_duration_seconds"),
                    0.5,
                ),
                "series_evicted": scrape.value(
                    "elastic_tpu_metric_series_evicted_total"
                ),
                "open_bind_intents": scrape.value(
                    "elastic_tpu_bind_intents_open"
                ),
            }
            node_buckets = scrape.buckets("elastic_tpu_prestart_seconds")
            for le, count in node_buckets.items():
                merged_bind[le] = merged_bind.get(le, 0.0) + count
            for q, key in ((0.5, "bind_p50_ms"), (0.99, "bind_p99_ms")):
                quantile = histogram_quantile(node_buckets, q)
                row[key] = (
                    None if quantile is None else round(quantile * 1000, 3)
                )
            per_node[node] = row
            totals["binds"] += binds
            totals["allocates"] += row["allocates"]
            totals["kubelet_lists"] += row["kubelet_lists"]
            totals["sink_writes_events"] += row["sink_writes"]["events"]
            totals["sink_writes_crd"] += row["sink_writes"]["crd"]
            totals["series_evicted"] += row["series_evicted"]
        binds = totals["binds"]
        p50 = histogram_quantile(merged_bind, 0.5)
        p99 = histogram_quantile(merged_bind, 0.99)
        return {
            "nodes": len(per_node),
            "per_node": per_node,
            "fleet": {
                "binds_total": binds,
                "fleet_bind_p50_ms": (
                    None if p50 is None else round(p50 * 1000, 3)
                ),
                "fleet_bind_p99_ms": (
                    None if p99 is None else round(p99 * 1000, 3)
                ),
                "request_amplification": {
                    "kubelet_lists_total": totals["kubelet_lists"],
                    "kubelet_lists_per_bind": (
                        round(totals["kubelet_lists"] / binds, 4)
                        if binds else None
                    ),
                    "sink_writes_per_bind": {
                        "events": (
                            round(totals["sink_writes_events"] / binds, 4)
                            if binds else None
                        ),
                        "crd": (
                            round(totals["sink_writes_crd"] / binds, 4)
                            if binds else None
                        ),
                    },
                },
                "series_evicted_total": totals["series_evicted"],
            },
        }

    # -- reconcile convergence ------------------------------------------------

    def wait_converged(
        self,
        after_ts: float,
        timeout_s: float = 60.0,
        poll_s: float = 0.25,
    ) -> Dict[str, Optional[float]]:
        """Per-node reconcile convergence time after the ``after_ts``
        anchor (e.g. churn end): seconds until the node's last-converged
        timestamp first advanced past the anchor; None = never converged
        inside the timeout (THE divergent node to triage)."""
        pending = set(self.targets)
        out: Dict[str, Optional[float]] = {n: None for n in self.targets}
        deadline = time.monotonic() + timeout_s
        while pending and time.monotonic() < deadline:
            for node in sorted(pending):
                try:
                    scrape = self.scrape_node(node)
                except Exception:  # noqa: BLE001 - scrape blip: retry
                    continue
                ts = scrape.value(
                    "elastic_tpu_reconcile_last_converged_timestamp"
                )
                if ts > after_ts:
                    out[node] = round(ts - after_ts, 3)
                    pending.discard(node)
            if pending:
                time.sleep(poll_s)
        return out

    @staticmethod
    def convergence_summary(
        per_node: Dict[str, Optional[float]]
    ) -> dict:
        done = [v for v in per_node.values() if v is not None]
        return {
            "per_node": per_node,
            "converged_nodes": len(done),
            "unconverged_nodes": sorted(
                n for n, v in per_node.items() if v is None
            ),
            "median_s": round(statistics.median(done), 3) if done else None,
            "max_s": round(max(done), 3) if done else None,
        }

    # -- fleet-wide lifecycle timeline ----------------------------------------

    def node_timeline(
        self, node: str, since: Optional[float] = None
    ) -> dict:
        """One node's /debug/timeline payload (its durable journal,
        seq-ordered, plus the ring counters)."""
        url = f"{self.targets[node]}/debug/timeline"
        if since is not None:
            url += f"?since={since}"
        return json.loads(self._get(url))

    def merged_timeline(
        self,
        pod: Optional[str] = None,
        slice_id: Optional[str] = None,
        chip: Optional[int] = None,
        since: Optional[float] = None,
        kinds=None,
        limit: Optional[int] = None,
    ) -> dict:
        """Interleave every node's lifecycle journal into ONE
        fleet-ordered causal view, so a slice reform reads as one story
        — maintenance notice on node A, proactive draining annotation,
        survivors restamping at epoch N+1, reclaim — instead of N
        disjoint logs.

        Ordering: within a node, seq order (the node's own causal
        order) is never violated; across nodes the merge goes by wall
        time, and adopted trace ids (the admission id every bind
        continues under) stitch the cross-node causality no clock
        could. Entity filtering + causal expansion run over the MERGED
        list with the same semantics as one node's query
        (timeline.select_events), so a pod's fleet history includes the
        reform events its slice peers journaled on other nodes."""
        from ..timeline import merge_node_events, select_events

        per_node = {}
        unreachable = []
        for node in sorted(self.targets):
            try:
                per_node[node] = self.node_timeline(
                    node, since=since
                ).get("events", [])
            except Exception:  # noqa: BLE001 - a dead node: its journal
                unreachable.append(node)  # is still on ITS db, not here
        merged = merge_node_events(per_node)
        events = select_events(
            merged, pod=pod, slice_id=slice_id, chip=chip,
            kinds=kinds, limit=limit,
        )
        return {
            "nodes": sorted(per_node),
            "unreachable": unreachable,
            "events": events,
        }

    # -- fleet goodput (goodput.py) -------------------------------------------

    def node_goodput(self, node: str) -> dict:
        """One node's /debug/goodput payload: the ledger's per-pod
        state partitions + downtime-by-cause rollup."""
        return json.loads(self._get(f"{self.targets[node]}/debug/goodput"))

    def fleet_goodput(self) -> dict:
        """Fleet goodput % and downtime-by-cause, summed over every
        node's ledger — the SLI the migrate/drain/scale bench legs
        report next to their latency numbers.

        Migration stories get one extra join the per-node ledgers
        cannot do alone: a completed migration's TRUE downtime spans
        two pods on two nodes (source checkpoint signal -> verified
        resume on the destination), so each completion is stitched to
        the source pod's terminal non-productive run on its source
        node's ledger. Falls back to the coordinator's own measured
        window (ack -> verify) when the source ledger is unreachable."""
        per_node = {}
        unreachable = []
        for node in sorted(self.targets):
            try:
                per_node[node] = self.node_goodput(node)
            except Exception:  # noqa: BLE001 - dead node: its db still
                unreachable.append(node)  # has the ledger, not this view
        lifetime = productive = 0.0
        downtime: dict = {}
        conservation: list = []
        for node, payload in per_node.items():
            for pod, entry in payload.get("pods", {}).items():
                lifetime += entry.get("lifetime_s") or 0.0
                productive += (entry.get("states") or {}).get(
                    "productive", 0.0
                )
            for cause, seconds in payload.get(
                "downtime_by_cause", {}
            ).items():
                downtime[cause] = downtime.get(cause, 0.0) + seconds
            for problem in payload.get("conservation_problems", []):
                conservation.append(f"{node}: {problem}")
        stories = []
        for node, payload in per_node.items():
            for story in payload.get("migrations", []):
                downtime_s = story.get("coordinator_downtime_s")
                source = story.get("source_node")
                src_entry = (
                    per_node.get(source, {}).get("pods", {})
                    .get(story.get("pod"))
                    if source else None
                )
                if src_entry:
                    # the source pod's terminal non-productive run:
                    # walk back from its last interval while the state
                    # stays non-productive — its start is the signal
                    run_start = None
                    for itv in reversed(src_entry.get("intervals", [])):
                        if itv["state"] == "productive":
                            break
                        run_start = itv["start"]
                    if run_start is not None and story.get(
                        "completed_ts"
                    ) is not None:
                        downtime_s = round(
                            story["completed_ts"] - run_start, 6
                        )
                stories.append({**story, "downtime_s": downtime_s})
        return {
            "nodes": sorted(per_node),
            "unreachable": unreachable,
            "fleet": {
                "lifetime_s": round(lifetime, 6),
                "productive_s": round(productive, 6),
                "goodput_percent": (
                    round(100.0 * productive / lifetime, 3)
                    if lifetime > 0 else None
                ),
                "downtime_by_cause": {
                    k: round(v, 6) for k, v in sorted(downtime.items())
                },
            },
            "migrations": stories,
            "conservation_problems": conservation,
            "per_node": {
                node: {
                    "pods": len(payload.get("pods", {})),
                    "downtime_by_cause": payload.get(
                        "downtime_by_cause", {}
                    ),
                }
                for node, payload in per_node.items()
            },
        }

    # -- critical-path latency (latency.py) -----------------------------------

    def node_latency(self, node: str) -> dict:
        """One node's /debug/latency payload: phase-attributed bind
        breakdown + per-loop detection-lag classes."""
        return json.loads(self._get(f"{self.targets[node]}/debug/latency"))

    def fleet_detection_lag(self) -> dict:
        """Fleet origin->repair lag per divergence class: every node's
        recent detection-lag observations merged, with p50/p99 computed
        over the merged sample — the number ROADMAP item 3 moves (the
        ~0.7s poll-bound divergence-repair lag) measured end to end
        from injected origin timestamps rather than driver stopwatches.

        Each node's /debug/latency keeps a bounded per-class window of
        recent observations (not the full history), so this is a rollup
        of the recent fleet, same as the per-node blocks it merges."""
        per_node = {}
        unreachable = []
        for node in sorted(self.targets):
            try:
                per_node[node] = self.node_latency(node)
            except Exception:  # noqa: BLE001 - dead node: no lag block
                unreachable.append(node)
        merged: Dict[str, List[dict]] = {}
        clamped_total = 0
        open_marks = 0
        for node, payload in per_node.items():
            lag = payload.get("detection_lag") or {}
            clamped_total += int(lag.get("clamped_total") or 0)
            open_marks += int(lag.get("open_marks") or 0)
            for cls, block in (lag.get("classes") or {}).items():
                for entry in block.get("recent", []):
                    merged.setdefault(cls, []).append(
                        {**entry, "node": node}
                    )
        classes = {}
        for cls, entries in sorted(merged.items()):
            lags = sorted(e["lag_s"] for e in entries)

            def q(p: float) -> Optional[float]:
                if not lags:
                    return None
                idx = min(len(lags) - 1, int(round(p * (len(lags) - 1))))
                return round(lags[idx], 6)

            classes[cls] = {
                "count": len(lags),
                "p50_s": q(0.5),
                "p99_s": q(0.99),
                "max_s": round(lags[-1], 6) if lags else None,
                "loops": sorted({e["loop"] for e in entries}),
                "nodes": sorted({e["node"] for e in entries}),
            }
        return {
            "nodes": sorted(per_node),
            "unreachable": unreachable,
            "classes": classes,
            "clamped_total": clamped_total,
            "open_marks": open_marks,
        }

    # -- request-level SLO (workloads/request_obs.py) -------------------------

    def node_requests(self, node: str) -> dict:
        """One node's /debug/requests payload: the request observatory's
        per-class ledgers, phase breakdown, and conservation check."""
        return json.loads(
            self._get(f"{self.targets[node]}/debug/requests")
        )

    def fleet_slo(
        self, targets: Optional[Dict[str, Dict[str, float]]] = None
    ) -> dict:
        """Fleet TTFT/TPOT percentiles and SLO attainment per class,
        merged from every node's bounded request histograms — the SLI
        the gateway PR routes against, living beside fleet_goodput.

        Node histograms merge exactly (cumulative le -> count buckets
        sum across nodes), so with one node the fleet numbers EQUAL the
        node's own exposition — the equality the request-obs smoke
        pins. Attainment per class is the cumulative bucket count at
        the class target divided by total observations; targets default
        to the observatory's (deliberately placed on bucket bounds so
        this division is exact, not interpolated). ``batch`` has no
        latency target — it attains by finishing."""
        from ..workloads.request_obs import (
            DEFAULT_SLO_TARGETS, SLO_CLASSES,
        )

        targets = targets or DEFAULT_SLO_TARGETS
        scrapes: Dict[str, NodeScrape] = {}
        unreachable = []
        for node in sorted(self.targets):
            try:
                scrapes[node] = self.scrape_node(node)
            except Exception:  # noqa: BLE001 - dead node: skip
                unreachable.append(node)

        def slo_buckets(
            scrape: NodeScrape, name: str, slo: str
        ) -> Dict[float, float]:
            # NodeScrape.buckets() ignores non-le labels, which would
            # sum the SLO classes together — filter by hand instead
            out: Dict[float, float] = {}
            for labels, value in scrape.samples.get(
                f"{name}_bucket", []
            ):
                if labels.get("slo") == slo and "le" in labels:
                    le = _parse_le(labels["le"])
                    out[le] = out.get(le, 0.0) + value
            return out

        def merge(name: str, slo: str) -> Dict[float, float]:
            merged: Dict[float, float] = {}
            for scrape in scrapes.values():
                for le, count in slo_buckets(scrape, name, slo).items():
                    merged[le] = merged.get(le, 0.0) + count
            return merged

        def total(buckets: Dict[float, float]) -> float:
            return max(buckets.values()) if buckets else 0.0

        def attained_ratio(
            buckets: Dict[float, float], target: float
        ) -> Optional[float]:
            n = total(buckets)
            if n <= 0:
                return None
            # cumulative count at the largest bound <= target: exact
            # when the target sits on a bound (the default targets do)
            eligible = [le for le in buckets if le <= target]
            if not eligible:
                return 0.0
            return round(buckets[max(eligible)] / n, 4)

        classes = {}
        for slo in SLO_CLASSES:
            ttft = merge("elastic_tpu_request_ttft_seconds", slo)
            tpot = merge("elastic_tpu_request_tpot_seconds", slo)
            if not ttft and not tpot:
                continue
            tgt = targets.get(slo, {})
            if "ttft_s" in tgt:
                attainment = attained_ratio(ttft, tgt["ttft_s"])
            elif "tpot_s" in tgt:
                attainment = attained_ratio(tpot, tgt["tpot_s"])
            else:
                attainment = 1.0 if total(ttft) > 0 else None
            classes[slo] = {
                "ttft_observed": int(total(ttft)),
                "tpot_observed": int(total(tpot)),
                "ttft_p50_s": histogram_quantile(ttft, 0.5),
                "ttft_p99_s": histogram_quantile(ttft, 0.99),
                "tpot_p50_s": histogram_quantile(tpot, 0.5),
                "tpot_p99_s": histogram_quantile(tpot, 0.99),
                "attainment": attainment,
                "target": dict(tgt),
            }
        per_node = {}
        for node, scrape in scrapes.items():
            node_classes = {}
            for slo in SLO_CLASSES:
                att = scrape.value(
                    "elastic_tpu_request_slo_attainment_ratio",
                    {"slo": slo}, default=-1.0,
                )
                count = scrape.value(
                    "elastic_tpu_request_ttft_seconds_count",
                    {"slo": slo}, default=0.0,
                )
                if att < 0 and count <= 0:
                    continue
                node_classes[slo] = {
                    "attainment": att if att >= 0 else None,
                    "ttft_observed": int(count),
                }
            per_node[node] = {
                "live": scrape.value("elastic_tpu_requests_live"),
                "pending_handoff": scrape.value(
                    "elastic_tpu_requests_pending_handoff"
                ),
                "classes": node_classes,
            }
        return {
            "nodes": sorted(scrapes),
            "unreachable": unreachable,
            "fleet": {"classes": classes},
            "per_node": per_node,
        }

    # -- trace continuity -----------------------------------------------------

    def trace_lookup(self, trace_id: str) -> List[dict]:
        """Every completed trace carrying ``trace_id``, across all
        targets, deduplicated (in-process sims share one ring, so every
        node answers with the same traces; a real fleet has per-node
        rings and only the binding node answers)."""
        found: List[dict] = []
        seen = set()
        for node in sorted(self.targets):
            try:
                payload = json.loads(self._get(
                    f"{self.targets[node]}/debug/traces?trace={trace_id}"
                ))
            except Exception:  # noqa: BLE001 - an unreachable node: skip
                continue
            for trace in payload.get("traces", []):
                key = (
                    trace.get("trace_id"), trace.get("name"),
                    trace.get("start_ts"),
                )
                if key in seen:
                    continue
                seen.add(key)
                found.append(trace)
        return found

    def check_continuity(
        self, samples: List[Tuple[str, str, str]]
    ) -> dict:
        """``samples`` = (expected_node, admission_trace_id, pod_key)
        triples; a sample is continuous when a completed bind
        (PreStartContainer) trace under the admission id exists AND its
        ``node`` attribute names the node kubelet actually bound the pod
        on. Returns the continuity fraction + the broken samples."""
        broken: List[dict] = []
        for expected_node, trace_id, pod_key in samples:
            traces = self.trace_lookup(trace_id)
            binds = [
                t for t in traces
                if t.get("name") == "PreStartContainer"
                and t.get("attrs", {}).get("node") == expected_node
            ]
            if not binds:
                broken.append({
                    "pod": pod_key,
                    "trace_id": trace_id,
                    "expected_node": expected_node,
                    "found_traces": len(traces),
                })
        n = len(samples)
        return {
            "sampled": n,
            "continuous": n - len(broken),
            "fraction": round((n - len(broken)) / n, 4) if n else None,
            "broken": broken[:5],
        }
