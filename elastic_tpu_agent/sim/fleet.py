"""FleetSim: N in-process agents, N fake kubelets, ONE fake apiserver.

Every node is a complete agent — real TPUManager, real gRPC device-plugin
servers registered with its own FakeKubelet, real supervised reconciler,
real CRD/Event sinks writing to the shared FakeAPIServer — with its own
AgentMetrics on a private registry served on an ephemeral loopback port,
so the FleetAggregator reads the fleet exactly the way a production
Prometheus would: one scrape target per node.

The bind drive is in-process (the Allocate/PreStartContainer servicers
are invoked directly, like the bench churn phase): on the small CI box,
per-RPC gRPC overhead at fleet concurrency would benchmark the loopback
fabric instead of the agent. The pod-resources Lists the locators and
reconcilers issue still cross real gRPC to each node's fake kubelet, and
the sinks still cross real HTTP to the shared apiserver — the traffic
the fleet observatory meters is real.

Admission stamps ``elasticgpu.io/trace-id`` on every pod, so one trace
id follows the pod from the shared apiserver to whichever agent binds it
(the bind adopts the id; plugins/tpushare.py). All in-process agents
share the one process-wide trace ring, so bind traces also carry a
``node`` attribute — the aggregator attributes a trace to its binding
node by that attribute, exactly as it would pick the one answering ring
in a real multi-process fleet.
"""

from __future__ import annotations

import os
import statistics
import sys
import threading
import time
from typing import Dict, List, Optional

from ..common import (
    AnnotationAssumed,
    AnnotationSliceID,
    AnnotationSliceName,
    AnnotationSliceWorkerHosts,
    AnnotationSliceWorkerID,
    AnnotationTraceID,
    EnvSliceEpoch,
    ResourceTPUCore,
    container_annotation,
)
from ..gen import deviceplugin_pb2 as dp
from ..kube.client import KubeClient
from ..manager import ManagerOptions, TPUManager
from ..tracing import Tracer, new_trace_id, set_tracer

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


def _import_fakes():
    """The fake control-plane rigs live in tests/ (they are test/bench
    material, not agent code); make them importable from bench and
    tooling without an installed package."""
    tests_dir = os.path.join(_REPO, "tests")
    if tests_dir not in sys.path:
        sys.path.insert(0, tests_dir)
    try:
        from fake_apiserver import FakeAPIServer, make_pod
        from fake_kubelet import FakeKubelet
    except ImportError as e:  # pragma: no cover - repo layout broken
        raise RuntimeError(
            "FleetSim needs tests/fake_apiserver.py and "
            "tests/fake_kubelet.py next to the package "
            f"(looked in {tests_dir}): {e}"
        ) from e
    return FakeAPIServer, FakeKubelet, make_pod


class SimNode:
    """One simulated node: fake kubelet + full agent + metrics endpoint."""

    def __init__(self, name: str, root: str) -> None:
        self.name = name
        self.root = root
        self.kubelet = None
        self.manager: Optional[TPUManager] = None
        self.metrics = None
        self.metrics_url: str = ""
        self.dead = False  # killed by a chaos scenario (kill_node)
        self.opts: Optional[ManagerOptions] = None  # kept for restart_node
        self.operator_kind: str = ""

    @property
    def storage(self):
        return self.manager.storage


class PodRef:
    """One admitted pod: where it was scheduled and its admission id."""

    __slots__ = ("node_idx", "namespace", "name", "chip", "trace_id")

    def __init__(self, node_idx, namespace, name, chip, trace_id) -> None:
        self.node_idx = node_idx
        self.namespace = namespace
        self.name = name
        self.chip = chip
        self.trace_id = trace_id

    @property
    def pod_key(self) -> str:
        return f"{self.namespace}/{self.name}"


class SimWorkload:
    """Stub in-pod workload for migration chaos scenarios: a thread
    ticking a step counter with the REAL LifecycleWatcher woven in —
    the same spec-polling / atomic-ack code path a production runner
    uses — writing stub checkpoints (a state file whose digest the ack
    carries) to a shared 'PVC' directory. On a drain or reform signal
    it saves, acks and (for drains) exits, exactly the contract
    workloads/lifecycle.py documents; a replacement pod finds the
    destination agent's restore stamp, adopts the checkpointed step and
    acks the resume for verification."""

    def __init__(
        self,
        alloc_spec_dir: str,
        alloc_hash: str,
        ckpt_dir: str,
        tick_s: float = 0.02,
        resume_wait_s: float = 0.0,
        exit_on_drain: bool = True,
        precopy: bool = False,
        state_bytes: int = 0,
        dirty_fraction: float = 0.05,
        precopy_interval_ticks: int = 2,
        ship_bps: float = 0.0,
    ) -> None:
        from ..workloads.lifecycle import LifecycleWatcher

        self.ckpt_dir = ckpt_dir
        self.tick_s = tick_s
        self.resume_wait_s = resume_wait_s
        self.exit_on_drain = exit_on_drain
        # Pre-copy mode (ISSUE 20): the workload carries a synthetic
        # mutable parameter blob; on a drain it STREAMS delta rounds
        # through a DeltaCheckpointer while training continues, then
        # pauses only for the final delta at the coordinator's cutover
        # signal. ship_bps simulates shared-storage bandwidth — the
        # sleep per shipped byte is what makes the full-vs-delta
        # downtime difference measurable; pipelined ships keep ticking
        # steps under the sleep, paused ships are pure downtime.
        self.precopy = precopy
        self.dirty_fraction = max(0.0, min(1.0, dirty_fraction))
        self.precopy_interval_ticks = max(1, int(precopy_interval_ticks))
        self.ship_bps = float(ship_bps)
        if precopy and state_bytes <= 0:
            state_bytes = 1 << 20
        self._state = bytearray(state_bytes)
        self._delta = None
        if state_bytes > 0:
            from ..workloads.checkpointing import DeltaCheckpointer

            self._delta = DeltaCheckpointer(ckpt_dir, block_size=4096)
        self.step = 0
        self.saved_step: Optional[int] = None
        self.resumed_step: Optional[int] = None
        self.last_signal = None
        # Measured by whichever checkpoint path ran on the drain: how
        # long training was PAUSED shipping state (the downtime the
        # bench compares full-checkpoint vs pre-copy cutover on).
        self.pause_ms: Optional[float] = None
        self.precopy_rounds = 0
        self.final_delta_bytes: Optional[int] = None
        self.full_bytes: Optional[int] = None
        self.final_chain: str = ""
        self.exited = threading.Event()
        self.watcher = LifecycleWatcher(
            alloc_spec_dir, alloc_hash, poll_interval_s=0.0
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"sim-workload-{alloc_hash[:8]}",
        )

    def start(self) -> "SimWorkload":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10.0)

    def _save(self) -> None:
        import json as _json

        os.makedirs(self.ckpt_dir, exist_ok=True)
        with open(os.path.join(self.ckpt_dir, "state.json"), "w") as f:
            _json.dump({"step": self.step}, f)
        self.saved_step = self.step

    def _mutate(self) -> None:
        """Dirty a deterministic, step-dependent subset of state blocks
        — the working set a pre-copy round has to re-ship."""
        if not self._state:
            return
        bs = 4096
        n_blocks = max(1, len(self._state) // bs)
        dirty = max(1, int(n_blocks * self.dirty_fraction))
        stamp = (self.step & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
        for i in range(dirty):
            off = ((self.step * 31 + i * 7) % n_blocks) * bs
            self._state[off:off + 8] = stamp

    def _ship(self, n_bytes, pause: bool) -> None:
        """Model shipping ``n_bytes`` to shared storage at ship_bps.
        ``pause=True`` stops training for the duration (downtime);
        ``pause=False`` pipelines — steps keep ticking under the
        transfer, which is the whole point of pre-copy."""
        if self.ship_bps <= 0.0 or not n_bytes:
            return
        end = time.monotonic() + float(n_bytes) / self.ship_bps
        while not self._stop.is_set():
            left = end - time.monotonic()
            if left <= 0:
                return
            if pause:
                time.sleep(min(0.005, left))
            else:
                self.step += 1
                self._mutate()
                self._stop.wait(min(self.tick_s, left))

    def _precopy_drain(self, sig) -> None:
        """The pre-copy half of the lifecycle contract: stream delta
        rounds (kind="precopy" acks) while training continues, pause at
        the coordinator's cutover signal, ship ONLY the final delta,
        then write the ordinary cutover ack the early-reclaim pass
        completes the drain on."""
        from ..workloads.lifecycle import SIGNAL_CUTOVER

        os.makedirs(self.ckpt_dir, exist_ok=True)
        round_ = 0
        cut = None
        while not self._stop.is_set() and cut is None:
            summary = self._delta.save(
                self.step, bytes(self._state), round_=round_
            )
            self._ship(summary["delta_bytes"], pause=False)
            self.watcher.ack_precopy(
                summary["step"], round_, checkpoint_dir=self.ckpt_dir,
                delta_bytes=summary["delta_bytes"],
                total_bytes=summary["total_bytes"],
                digest=summary["chain"], signal=sig.value,
            )
            self.precopy_rounds = round_ + 1
            round_ += 1
            for _ in range(self.precopy_interval_ticks):
                if self._stop.is_set():
                    break
                self.step += 1
                self._mutate()
                got = self.watcher.poll(force=True)
                if got is not None and got.kind == SIGNAL_CUTOVER:
                    cut = got
                    break
                self._stop.wait(self.tick_s)
        # Cutover: training PAUSES here; everything below is downtime.
        t0 = time.monotonic()
        summary = self._delta.save(
            self.step, bytes(self._state), round_=round_
        )
        self._ship(summary["delta_bytes"], pause=True)
        self._save()
        self.pause_ms = (time.monotonic() - t0) * 1000.0
        self.precopy_rounds = round_ + 1
        self.final_delta_bytes = summary["delta_bytes"]
        self.full_bytes = summary["total_bytes"]
        self.final_chain = summary["chain"]
        self.watcher.ack(
            self.step, checkpoint_dir=self.ckpt_dir,
            signal=sig.value, epoch=sig.epoch, digest=summary["chain"],
            extra={
                "precopy_rounds": round_,
                "delta_bytes": summary["delta_bytes"],
                "full_bytes": summary["total_bytes"],
                "cutover_ms": round(self.pause_ms, 3),
            },
        )

    def _maybe_resume(self) -> None:
        import json as _json

        deadline = time.monotonic() + self.resume_wait_s
        while not self._stop.is_set():
            req = self.watcher.restore_request()
            if req:
                step = None
                if self._delta is not None:
                    # a pre-copy source left a delta chain: reassemble
                    # (and implicitly verify digests) before trusting it
                    try:
                        from ..workloads.checkpointing import (
                            DeltaCheckpointer,
                        )

                        payload, manifest = DeltaCheckpointer(
                            req["checkpoint_dir"], block_size=4096
                        ).load()
                        self._state = bytearray(payload)
                        step = int(manifest["step"])
                    except (ValueError, OSError, KeyError, TypeError):
                        step = None
                if step is None:
                    try:
                        with open(os.path.join(
                            req["checkpoint_dir"], "state.json"
                        )) as f:
                            step = int(_json.load(f)["step"])
                    except (OSError, ValueError, KeyError, TypeError):
                        step = int(req.get("step") or 0)
                self.step = step
                self.resumed_step = self.step
                self.watcher.ack_resume(
                    self.step, checkpoint_dir=req["checkpoint_dir"]
                )
                return
            if time.monotonic() >= deadline:
                return
            time.sleep(0.02)

    def _run(self) -> None:
        from ..workloads.lifecycle import SIGNAL_DRAIN

        # The destination agent stamps the restore env moments AFTER
        # the replacement's bind; a migrating workload polls briefly
        # before concluding it starts from scratch.
        self._maybe_resume()
        while not self._stop.is_set():
            self.step += 1
            if self._state:
                self._mutate()
            sig = self.watcher.poll(force=True)
            if sig is not None:
                self.last_signal = sig
                if self.precopy and sig.kind == SIGNAL_DRAIN:
                    self._precopy_drain(sig)
                    if self.exit_on_drain:
                        break
                    self._stop.wait(self.tick_s)
                    continue
                t0 = time.monotonic()
                self._save()
                if self._state and self._delta is not None:
                    # full-checkpoint baseline: the WHOLE state ships
                    # inside the pause window
                    summary = self._delta.save(
                        self.step, bytes(self._state), round_=0
                    )
                    self._ship(summary["total_bytes"], pause=True)
                    self.full_bytes = summary["total_bytes"]
                    self.final_chain = summary["chain"]
                if sig.kind == SIGNAL_DRAIN:
                    self.pause_ms = (time.monotonic() - t0) * 1000.0
                self.watcher.ack(
                    self.step, checkpoint_dir=self.ckpt_dir,
                    signal=sig.value, epoch=sig.epoch,
                )
                if sig.kind == SIGNAL_DRAIN and self.exit_on_drain:
                    break
            self._stop.wait(self.tick_s)
        self.exited.set()


class FleetSim:
    """Build, drive and tear down an N-node simulated fleet.

    ``base_dir`` must be SHORT (AF_UNIX socket paths cap at ~107 chars;
    each node's kubelet sockets live under ``base_dir/n<i>/``).
    """

    def __init__(
        self,
        base_dir: str,
        nodes: int = 8,
        operator_kind: str = "stub:v5litepod-4",
        reconcile_period_s: float = 2.0,
        dp_pool_size: int = 4,
        enable_sampler: bool = False,
        core_units_per_pod: int = 10,
        slice_membership_ttl_s: float = 1.0,
        operator_kinds: Optional[List[str]] = None,
        drain_deadline_s: float = 5.0,
        preemption_notice_s: Optional[float] = None,
        drain_period_s: float = 0.5,
        migration_period_s: float = 0.25,
        timeline_cap: Optional[int] = None,
        storage_batch_window_s: float = 0.0,
        sink_flush_window_s: float = 0.0,
        goodput_period_s: float = 1.0,
        sampler_period_s: float = 10.0,
        repartition_period_s: float = 10.0,
        slow_span_ms: Optional[float] = None,
        profile_hz: float = 0.0,
        enable_events: bool = True,
        event_safety_net_factor: float = 1.0,
    ) -> None:
        self.base_dir = base_dir
        self.n_nodes = nodes
        self.operator_kind = operator_kind
        # Heterogeneous fleet (ROADMAP item 5): one operator kind PER
        # NODE, cycling through the list — e.g. ["stub:v4-8",
        # "stub:v5litepod-8", "stub:v6e-8"] mixes generations with
        # per-generation core-count/HBM shapes from topology.CHIP_SPECS.
        self.operator_kinds = list(operator_kinds or [])
        self.reconcile_period_s = reconcile_period_s
        self.dp_pool_size = dp_pool_size
        self.enable_sampler = enable_sampler
        self.core_units_per_pod = core_units_per_pod
        # Short TTL: a chaos scenario expects reform within a few
        # reconcile periods, not after a production-sized cache window.
        self.slice_membership_ttl_s = slice_membership_ttl_s
        # Drain lifecycle pacing: sim deadlines are seconds, not the
        # production 300s — chaos scenarios assert reclaim-on-deadline.
        self.drain_deadline_s = drain_deadline_s
        # Preemption-notice clamp (drain.py): None = the production
        # default; sim deadlines are already shorter than the default
        # notice, so only clamp-specific scenarios set this.
        self.preemption_notice_s = preemption_notice_s
        self.drain_period_s = drain_period_s
        # Migration-coordinator tick (migration.py): sim scenarios
        # assert ack-to-early-reclaim latency in fractions of the
        # already-short sim drain deadline.
        self.migration_period_s = migration_period_s
        # Lifecycle-timeline ring cap override (timeline.py): the
        # timeline smoke shrinks it to prove the ring + eviction
        # counter under churn; None = the production default.
        self.timeline_cap = timeline_cap
        # Scale-harness batching knobs (ISSUE 13): group-commit storage
        # writes (storage/batcher.py) and coalesced sink traffic
        # (async_sink flush window). 0/0 = the historical per-write
        # shape — the scale leg's unbatched baseline.
        self.storage_batch_window_s = storage_batch_window_s
        self.sink_flush_window_s = sink_flush_window_s
        # Goodput-ledger replay period (goodput.py): sim scenarios read
        # downtime attribution within seconds of the transitions, not
        # after a production-paced 10s tick.
        self.goodput_period_s = goodput_period_s
        # Sampler/repartition pacing: scenarios that drive the usage ->
        # quota loop by hand park both supervised loops (3600.0) so a
        # background tick can't race their round-paced assertions.
        self.sampler_period_s = sampler_period_s
        self.repartition_period_s = repartition_period_s
        # Latency observatory knobs (latency.py / profiler.py): the
        # latency smoke lowers the slow-span threshold to exercise the
        # slow_span journal path and turns the self-profiler on to pin
        # its measured overhead.
        self.slow_span_ms = slow_span_ms
        self.profile_hz = profile_hz
        # Event-driven core (events.py): enable_events=False is the
        # poll-only fallback A/B baseline. The safety-net factor
        # defaults to 1.0 IN THE SIM (production default is 10x):
        # existing scenarios time their assertions against the base
        # periods, and a stretched sweep must be opted into by the
        # scenarios that prove the stretch.
        self.enable_events = enable_events
        self.event_safety_net_factor = event_safety_net_factor
        self.nodes: List[SimNode] = []
        self.apiserver = None
        self.api_url = ""
        self._prev_tracer = None
        self._started = False

    # -- lifecycle ------------------------------------------------------------

    def start(self, trace_capacity: Optional[int] = None) -> None:
        FakeAPIServer, FakeKubelet, _ = _import_fakes()
        from prometheus_client import CollectorRegistry

        from ..metrics import AgentMetrics

        # One ring serves all in-process agents; churning thousands of
        # binds through the default 256-slot ring would evict the very
        # traces the continuity check follows. Swapped back at stop().
        if trace_capacity is None:
            trace_capacity = max(1024, 4 * self.n_nodes * 256)
        self._prev_tracer = set_tracer(Tracer(capacity=trace_capacity))

        self.apiserver = FakeAPIServer()
        self.api_url = self.apiserver.start()
        try:
            self._start_nodes(FakeKubelet, AgentMetrics, CollectorRegistry)
        except BaseException:
            # A node that failed to come up must not leak the ones that
            # did (or the swapped global tracer) into the caller's test.
            self.stop()
            raise
        self._started = True

    def _start_nodes(
        self, FakeKubelet, AgentMetrics, CollectorRegistry
    ) -> None:
        for i in range(self.n_nodes):
            node = SimNode(f"sim-{i}", os.path.join(self.base_dir, f"n{i}"))
            os.makedirs(os.path.join(node.root, "dev"), exist_ok=True)
            node.kubelet = FakeKubelet(
                os.path.join(node.root, "dp"),
                os.path.join(node.root, "pr", "kubelet.sock"),
            )
            node.kubelet.start()
            node.metrics = AgentMetrics(registry=CollectorRegistry())
            httpd = node.metrics.serve(0)  # ephemeral loopback port
            node.metrics_url = f"http://127.0.0.1:{httpd.server_address[1]}"
            node.operator_kind = (
                self.operator_kinds[i % len(self.operator_kinds)]
                if self.operator_kinds else self.operator_kind
            )
            node.opts = ManagerOptions(
                node_name=node.name,
                db_path=os.path.join(node.root, "meta.db"),
                operator_kind=node.operator_kind,
                dev_root=os.path.join(node.root, "dev"),
                device_plugin_dir=os.path.join(node.root, "dp"),
                pod_resources_socket=os.path.join(
                    node.root, "pr", "kubelet.sock"
                ),
                alloc_spec_dir=os.path.join(node.root, "alloc"),
                kube_client=KubeClient(self.api_url),
                metrics=node.metrics,
                dp_pool_size=self.dp_pool_size,
                enable_sampler=self.enable_sampler,
                reconcile_period_s=self.reconcile_period_s,
                slice_membership_ttl_s=self.slice_membership_ttl_s,
                drain_deadline_s=self.drain_deadline_s,
                drain_period_s=self.drain_period_s,
                **(
                    {"preemption_notice_s": self.preemption_notice_s}
                    if self.preemption_notice_s is not None else {}
                ),
                migration_period_s=self.migration_period_s,
                storage_batch_window_s=self.storage_batch_window_s,
                sink_flush_window_s=self.sink_flush_window_s,
                goodput_period_s=self.goodput_period_s,
                sampler_period_s=self.sampler_period_s,
                repartition_period_s=self.repartition_period_s,
                slow_span_ms=self.slow_span_ms,
                profile_hz=self.profile_hz,
                enable_event_bus=self.enable_events,
                event_safety_net_factor=self.event_safety_net_factor,
                **(
                    {"timeline_cap": self.timeline_cap}
                    if self.timeline_cap is not None else {}
                ),
            )
            node.manager = TPUManager(node.opts)
            node.manager.run(block=False)
            self.nodes.append(node)  # appended first: stop() reaps it
            if not node.kubelet.wait_registrations(2, timeout=20):
                raise RuntimeError(
                    f"{node.name}: agent failed to register with its "
                    "fake kubelet"
                )

    def stop(self) -> None:
        for node in self.nodes:
            try:
                node.manager.stop()
            except Exception:  # noqa: BLE001 - teardown keeps going
                pass
            try:
                node.metrics.close()
            except Exception:  # noqa: BLE001
                pass
            try:
                node.kubelet.stop()
            except Exception:  # noqa: BLE001
                pass
        self.nodes = []
        if self.apiserver is not None:
            self.apiserver.stop()
            self.apiserver = None
        if self._prev_tracer is not None:
            set_tracer(self._prev_tracer)
            self._prev_tracer = None
        self._started = False

    def targets(self) -> Dict[str, str]:
        """node name -> metrics base URL (the aggregator's scrape list);
        killed nodes drop out, exactly as they would from a production
        scrape discovery."""
        return {
            node.name: node.metrics_url
            for node in self.nodes if not node.dead
        }

    # -- chaos: kill one agent ------------------------------------------------

    def kill_node(self, idx: int) -> SimNode:
        """Take one node down hard: agent stopped, kubelet gone, metrics
        endpoint dark. The node's PODS stay at the apiserver until the
        caller deletes them (in production that is the node controller's
        eviction, not the dead agent's doing) — slice chaos scenarios
        delete the member pod to model the eviction."""
        node = self.nodes[idx]
        node.dead = True
        for closer in (
            lambda: node.manager.stop(),
            lambda: node.metrics.close(),
            lambda: node.kubelet.stop(),
        ):
            try:
                closer()
            except Exception:  # noqa: BLE001 - a kill is best-effort
                pass
        return node

    # -- chaos: drain lifecycle (drain.py) ------------------------------------

    def trigger_maintenance(
        self, idx: int, event: str = "TERMINATE_ON_HOST_MAINTENANCE"
    ) -> None:
        """Announce a GCE maintenance event on one node's stub operator;
        the node's drain orchestrator picks it up on its next poll."""
        self.nodes[idx].manager.operator.set_maintenance_event(event)

    def clear_maintenance(self, idx: int) -> None:
        self.nodes[idx].manager.operator.set_maintenance_event("NONE")

    def trigger_preemption(self, idx: int) -> None:
        """Spot-preemption notice: never un-rings (like real GCE)."""
        self.nodes[idx].manager.operator.set_preempted(True)

    def drain_status(self, idx: int) -> Dict:
        return self.nodes[idx].manager.drain.status()

    def wait_drain_state(
        self, idx: int, states, timeout_s: float = 30.0
    ) -> str:
        """Block until node ``idx``'s drain lifecycle reaches one of
        ``states``; returns the state reached."""
        states = {states} if isinstance(states, str) else set(states)
        deadline = time.monotonic() + timeout_s
        while True:
            state = self.nodes[idx].manager.drain.state
            if state in states:
                return state
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"{self.nodes[idx].name}: drain state {state!r} never "
                    f"reached {sorted(states)} "
                    f"(status: {self.drain_status(idx)})"
                )
            time.sleep(0.02)

    def restart_node(self, idx: int) -> SimNode:
        """Kill and re-boot one node's AGENT over its surviving
        db/kubelet/disk — the mid-drain restart scenario: the new manager
        must resume the journaled drain lifecycle (cordon, deadline,
        replay suppression) before its boot reconcile runs."""
        node = self.nodes[idx]
        old_op = node.manager.operator
        # The stub operator is process memory; the real metadata server
        # would still be announcing the event to the restarted agent, so
        # carry any injected maintenance/preemption state across.
        maint = (
            old_op.maintenance_event()
            if hasattr(old_op, "maintenance_event") else None
        )
        preempted = old_op.preempted() if hasattr(old_op, "preempted") else False
        try:
            node.manager.stop()
        except Exception:  # noqa: BLE001 - a crash is allowed to be messy
            pass
        prior = len(node.kubelet.registrations)  # count is cumulative
        node.manager = TPUManager(node.opts)
        new_op = node.manager.operator
        if maint and hasattr(new_op, "set_maintenance_event"):
            new_op.set_maintenance_event(maint)
        if preempted and hasattr(new_op, "set_preempted"):
            new_op.set_preempted(True)
        node.manager.run(block=False)
        if not node.kubelet.wait_registrations(prior + 2, timeout=20):
            raise RuntimeError(
                f"{node.name}: restarted agent failed to re-register"
            )
        return node

    # -- admission (the scheduler's half) -------------------------------------

    def _n_chips(self, node: SimNode) -> int:
        return len(node.manager.operator.devices())

    def admit_pods(
        self,
        pods_per_node: int,
        namespace: str = "fleet",
        node_idxs: Optional[List[int]] = None,
    ) -> List[PodRef]:
        """Schedule pods round-robin over each node's chips, stamping the
        elastic-scheduler annotations plus an admission trace id.
        ``node_idxs`` restricts admission to the named nodes (default:
        all) — e.g. a churn burst aimed at one node's journal."""
        _, _, make_pod = _import_fakes()
        refs: List[PodRef] = []
        for i in (
            range(self.n_nodes) if node_idxs is None else node_idxs
        ):
            node = self.nodes[i]
            n_chips = self._n_chips(node)
            for j in range(pods_per_node):
                ref = PodRef(
                    i, namespace, f"p{i}-{j}", j % n_chips, new_trace_id()
                )
                self.apiserver.upsert_pod(make_pod(
                    ref.namespace, ref.name, node.name,
                    annotations={
                        AnnotationAssumed: "true",
                        container_annotation("jax"): str(ref.chip),
                        AnnotationTraceID: ref.trace_id,
                    },
                    containers=[{"name": "jax"}],
                ))
                refs.append(ref)
        return refs

    def admit_pod(
        self,
        namespace: str,
        name: str,
        node_idx: int,
        chip: int = 0,
        annotations: Optional[Dict[str, str]] = None,
    ) -> PodRef:
        """Admit ONE pod with an explicit identity — the migration
        scenarios' replacement admission: the external scheduler lands
        the workload's next generation (same ns/name) on whatever node
        has room, and that node's agent finds the MigrationRecord."""
        _, _, make_pod = _import_fakes()
        node = self.nodes[node_idx]
        ref = PodRef(node_idx, namespace, name, chip, new_trace_id())
        ann = {
            AnnotationAssumed: "true",
            container_annotation("jax"): str(chip),
            AnnotationTraceID: ref.trace_id,
        }
        ann.update(annotations or {})
        self.apiserver.upsert_pod(make_pod(
            ref.namespace, ref.name, node.name,
            annotations=ann, containers=[{"name": "jax"}],
        ))
        return ref

    # -- migration handshake (migration.py) -----------------------------------

    def alloc_hash_of(self, ref: PodRef) -> str:
        """The pod's allocation hash — the key its ack file is written
        under ('' when unbound). In a real container this is the
        agent-injected ``TPU`` env; the sim reads the bound record."""
        info = self.nodes[ref.node_idx].storage.load(
            ref.namespace, ref.name
        )
        if info is None:
            return ""
        for rec in info.records():
            return rec.device.hash
        return ""

    def start_workload(
        self,
        ref: PodRef,
        ckpt_dir: str,
        tick_s: float = 0.02,
        resume_wait_s: float = 0.0,
        exit_on_drain: bool = True,
        precopy: bool = False,
        state_bytes: int = 0,
        dirty_fraction: float = 0.05,
        precopy_interval_ticks: int = 2,
        ship_bps: float = 0.0,
    ) -> SimWorkload:
        """Run a stub workload (REAL LifecycleWatcher) inside ``ref``'s
        binding; the pod must be bound first (the hash comes from its
        stamped spec)."""
        alloc_hash = self.alloc_hash_of(ref)
        if not alloc_hash:
            raise RuntimeError(f"{ref.pod_key} is not bound (no TPU env)")
        return SimWorkload(
            self.nodes[ref.node_idx].opts.alloc_spec_dir, alloc_hash,
            ckpt_dir, tick_s=tick_s, resume_wait_s=resume_wait_s,
            exit_on_drain=exit_on_drain, precopy=precopy,
            state_bytes=state_bytes, dirty_fraction=dirty_fraction,
            precopy_interval_ticks=precopy_interval_ticks,
            ship_bps=ship_bps,
        ).start()

    def migration_status(self, idx: int) -> Dict:
        return self.nodes[idx].manager.migration.status()

    def wait_migration_completed(
        self, idx: int, pod_key: str, timeout_s: float = 30.0
    ) -> Dict:
        """Block until node ``idx``'s coordinator VERIFIES the inbound
        resume of ``pod_key``; returns the completion entry."""
        deadline = time.monotonic() + timeout_s
        while True:
            status = self.migration_status(idx)
            for c in status.get("recent_completions", []):
                if c.get("pod") == pod_key:
                    return c
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"{self.nodes[idx].name}: migration of {pod_key} "
                    f"never verified (status: {status})"
                )
            time.sleep(0.02)

    # -- goodput ledger (goodput.py) ------------------------------------------

    def tick_goodput(self) -> None:
        """Force one ledger replay on every live node so the NEXT
        /debug/goodput (and the aggregator's fleet_goodput) reads the
        journal as of now — deterministic scenarios must not wait out
        the supervised loop's period."""
        for node in self.nodes:
            if not node.dead:
                node.manager.goodput.tick()

    def goodput_status(self, idx: int, **kwargs) -> Dict:
        return self.nodes[idx].manager.goodput.status(**kwargs)

    def wait_synced(self, refs: List[PodRef], timeout_s: float = 60.0) -> None:
        """Wait until every node's sitter has seen its LAST admitted pod
        (watch events are ordered per node, so the last one suffices)."""
        last_by_node: Dict[int, PodRef] = {}
        for ref in refs:
            last_by_node[ref.node_idx] = ref
        deadline = time.monotonic() + timeout_s
        for i, ref in last_by_node.items():
            sitter = self.nodes[i].manager.sitter
            while sitter.get_pod(ref.namespace, ref.name) is None:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"{self.nodes[i].name}: sitter never saw "
                        f"{ref.pod_key}"
                    )
                time.sleep(0.005)

    # -- the bind drive (kubelet's half) --------------------------------------

    def _core_ids(self, ref: PodRef) -> List[str]:
        # The unit field of a fake id is never parsed (only the chip
        # is), so embedding the pod KEY makes every pod's id set
        # pairwise distinct on its node without unit-space bookkeeping.
        # The namespace must be in there too: a real kubelet never
        # assigns one device id to two live pods, and scenario phases
        # reuse pod names across namespaces (admission waves, churn
        # replacements) — name-only ids would alias their device-set
        # hashes and make the locator's hash->owner mapping ambiguous.
        from ..plugins.tpushare import core_device_id

        return [
            core_device_id(ref.chip, f"{ref.namespace}.{ref.name}u{j}")
            for j in range(self.core_units_per_pod)
        ]

    def bind_pod(self, ref: PodRef) -> None:
        """One kubelet-shaped bind on the pod's node: Allocate, record
        the assignment in pod-resources, PreStartContainer — servicers
        invoked in-process, Lists/sinks over real transports."""
        node = self.nodes[ref.node_idx]
        core = node.manager.plugin.core
        ids = self._core_ids(ref)
        core.Allocate(dp.AllocateRequest(container_requests=[
            dp.ContainerAllocateRequest(devicesIDs=ids)
        ]), None)
        node.kubelet.assign(
            ref.namespace, ref.name, "jax", ResourceTPUCore, ids
        )
        core.PreStartContainer(
            dp.PreStartContainerRequest(devicesIDs=ids), None
        )

    def churn(
        self,
        refs: List[PodRef],
        workers_per_node: int = 2,
        timeout_s: float = 600.0,
    ) -> dict:
        """Bind every admitted pod, ``workers_per_node`` concurrent
        binders per node across the whole fleet at once; returns driver-
        side latency/throughput stats plus ``churn_end_ts`` (the anchor
        for reconcile-convergence measurement)."""
        by_node: Dict[int, List[PodRef]] = {}
        for ref in refs:
            by_node.setdefault(ref.node_idx, []).append(ref)
        bind_ms: List[Optional[float]] = [None] * len(refs)
        index_of = {id(ref): i for i, ref in enumerate(refs)}
        errors: List[str] = []
        err_lock = threading.Lock()
        n_workers = sum(
            min(workers_per_node, len(v)) for v in by_node.values()
        )
        barrier = threading.Barrier(n_workers + 1)

        def worker(chunk: List[PodRef]) -> None:
            barrier.wait()
            for ref in chunk:
                try:
                    t0 = time.perf_counter()
                    self.bind_pod(ref)
                    bind_ms[index_of[id(ref)]] = (
                        time.perf_counter() - t0
                    ) * 1000
                except Exception as e:  # noqa: BLE001 - collected, not fatal
                    with err_lock:
                        errors.append(
                            f"{ref.pod_key}: {type(e).__name__}: {e}"
                        )

        threads = []
        for node_refs in by_node.values():
            w = min(workers_per_node, len(node_refs))
            for k in range(w):
                threads.append(threading.Thread(
                    target=worker, args=(node_refs[k::w],), daemon=True,
                ))
        for t in threads:
            t.start()
        barrier.wait()
        wall_t0 = time.perf_counter()
        # One shared deadline, not one per join: 16 wedged workers must
        # not stack 16 timeouts. Workers still alive afterwards are
        # REPORTED (timed_out_workers) — the numbers below would
        # otherwise read as a healthy-but-slow fleet while daemon
        # threads keep mutating the stores under the caller's reads.
        deadline = time.monotonic() + timeout_s
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        timed_out = sum(1 for t in threads if t.is_alive())
        wall_s = time.perf_counter() - wall_t0
        done = sorted(v for v in bind_ms if v is not None)
        return {
            "pods": len(refs),
            "bound": len(done),
            "errors": errors[:5],
            "error_count": len(errors),
            "workers": n_workers,
            "timed_out_workers": timed_out,
            "bind_p50_ms": statistics.median(done) if done else None,
            "bind_p99_ms": (
                done[max(0, int(len(done) * 0.99) - 1)] if done else None
            ),
            "binds_per_s": len(done) / wall_s if wall_s > 0 else None,
            "wall_s": wall_s,
            "churn_end_ts": time.time(),
        }

    # -- pod deletion (steady-state churn: the scheduler's other half) --------

    def delete_pods(self, refs: List[PodRef]) -> None:
        """Delete admitted pods the way the control plane would: gone
        from the apiserver (the sitter's DELETED event feeds each
        node's GC) and unassigned at the node's kubelet (so the
        reconciler doesn't replay the bind back)."""
        for ref in refs:
            self.nodes[ref.node_idx].kubelet.unassign_pod(
                ref.namespace, ref.name
            )
            self.apiserver.delete_pod(ref.namespace, ref.name)

    def wait_reclaimed(
        self, refs: List[PodRef], timeout_s: float = 60.0
    ) -> float:
        """Block until every deleted pod's checkpoint record is gone
        (GC/reconciler reclaimed the binding); returns the wait."""
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        for ref in refs:
            node = self.nodes[ref.node_idx]
            if node.dead:
                continue
            while node.storage.load(ref.namespace, ref.name) is not None:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"{node.name}: {ref.pod_key} never reclaimed "
                        "after delete"
                    )
                time.sleep(0.02)
        return time.monotonic() - t0

    # -- fleet-side ground truth (assertions, not metrics) --------------------

    def stored_binds(self) -> Dict[str, int]:
        """Per-node checkpoint-store record counts (the 'every bind
        landed' ground truth the smoke asserts against)."""
        return {
            node.name: node.storage.count()
            for node in self.nodes if not node.dead
        }

    # -- multi-host slices (slices/) ------------------------------------------

    def admit_slice(
        self,
        slice_id: str,
        node_idxs: List[int],
        accelerator_type: str = "v4-32",
        namespace: str = "slice",
    ) -> List[PodRef]:
        """Admit one slice-member pod per named node, carrying the full
        slice contract: identity, shape, index-ordered host list and
        this member's worker id — what the elastic scheduler would
        stamp."""
        _, _, make_pod = _import_fakes()
        hosts = ",".join(self.nodes[i].name for i in node_idxs)
        refs: List[PodRef] = []
        for w, i in enumerate(node_idxs):
            node = self.nodes[i]
            ref = PodRef(
                i, namespace, f"m{w}-{slice_id}", 0, new_trace_id()
            )
            self.apiserver.upsert_pod(make_pod(
                ref.namespace, ref.name, node.name,
                annotations={
                    AnnotationAssumed: "true",
                    container_annotation("jax"): "0",
                    AnnotationTraceID: ref.trace_id,
                    AnnotationSliceID: slice_id,
                    AnnotationSliceName: accelerator_type,
                    AnnotationSliceWorkerID: str(w),
                    AnnotationSliceWorkerHosts: hosts,
                },
                containers=[{"name": "jax"}],
            ))
            refs.append(ref)
        return refs

    def slice_env_of(self, ref: PodRef) -> Dict[str, str]:
        """The env stamped into ``ref``'s on-disk alloc spec (empty when
        unbound) — the ground truth slice assertions read."""
        node = self.nodes[ref.node_idx]
        info = node.storage.load(ref.namespace, ref.name)
        if info is None:
            return {}
        core = node.manager.plugin.core
        for by_resource in info.allocations.values():
            for rec in by_resource.values():
                spec = core.read_alloc_spec(rec.device.hash)
                if spec and spec.get("env"):
                    return dict(spec["env"])
        return {}

    def wait_slice_reformed(
        self,
        refs: List[PodRef],
        expected_hosts: List[str],
        expected_epoch: int,
        timeout_s: float = 60.0,
    ) -> float:
        """Block until every surviving member's stamped env shows the
        expected host list AND epoch; returns the wait in seconds."""
        want_hosts = ",".join(expected_hosts)
        want_epoch = str(expected_epoch)
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        for ref in refs:
            if self.nodes[ref.node_idx].dead:
                continue
            while True:
                env = self.slice_env_of(ref)
                if (
                    env.get("TPU_WORKER_HOSTNAMES") == want_hosts
                    and env.get(EnvSliceEpoch) == want_epoch
                ):
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"{ref.pod_key} never re-formed to "
                        f"[{want_hosts}] epoch {want_epoch}; env now: "
                        f"{ {k: v for k, v in env.items() if k.startswith(('TPU_', 'ELASTIC_TPU_SLICE'))} }"
                    )
                time.sleep(0.02)
        return time.monotonic() - t0
