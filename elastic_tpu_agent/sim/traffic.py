"""Trace-driven traffic: seeded, replayable request/pod arrival traces.

Chaos scoring (sim/chaos.py) is only as honest as the traffic it runs
over: scripted two-pod scenarios cannot surface the races that live in
admission waves, prefix-cache churn and mixed tenancy. This generator
emits the ugly day's *workload* half — a deterministic event stream
that the chaos runner replays against a real FleetSim through the real
admission paths, so every run is reproducible from its ``trace_seed``
alone.

What a trace contains (all from ONE ``random.Random(seed)`` stream, so
same seed ⇒ the same events in the same order, byte-identical when
serialized):

- **diurnal load** — request arrival rate follows a compressed sine
  "day" around ``base_rps``, so scenarios see both trough and rush-hour
  admission pressure inside a few seconds of sim time;
- **flash crowds** — short seeded windows where the arrival rate
  multiplies (the retweeted-demo moment), landing mid-scenario so
  faults overlap the surge;
- **prefix-cache-hostile prompts** — each request carries a block-chain
  digest path. ``friendly`` requests share long common prefixes (the
  affinity-cache's best case); ``hostile`` requests draw adversarial
  chains that share block 0 and then diverge immediately — maximal
  digest-table pressure, zero reuse beyond the root, defeating
  prefix-affinity routing by construction;
- **mixed tenancy** — pod arrival/departure events interleave ``serve``
  pods (the request engines' homes) with ``train`` pods that churn
  through admission/bind/delete, so serving SLOs are scored while
  training tenants fight for the same nodes.

The trace is *pure data* (`Trace.lines()` is canonical JSON, one event
per line, sorted keys, fixed float formatting): generation never reads
clocks or touches the fleet. Replay pacing belongs to the driver —
``TraceCursor`` hands out events whose trace-time has come, against
whatever clock the chaos program runs on (ManualClock in tests).
"""

from __future__ import annotations

import hashlib
import json
import math
import random
from typing import Dict, Iterator, List, Optional

# Request SLO classes must match the observatory's label space
# (workloads/request_obs.py SLO_CLASSES) or every admit coerces to
# the default class and the per-class attainment score goes blind.
SLO_CLASSES = ("ttft", "tpot", "batch")

# Digest-path shape: chains are this many blocks deep; friendly traffic
# shares prefixes from a pool this wide.
CHAIN_DEPTH = 8
FRIENDLY_PREFIX_POOL = 4


def _digest(*parts: object) -> str:
    """Stable short content digest (the block-chain digest stand-in the
    observatory attributes prefill cache hits to)."""
    h = hashlib.sha256("/".join(str(p) for p in parts).encode())
    return h.hexdigest()[:16]


class Trace:
    """One generated trace: events sorted by time, plus the recipe that
    produced them (seed + knobs) for the repro line."""

    def __init__(self, seed: int, meta: Dict, events: List[dict]) -> None:
        self.seed = seed
        self.meta = meta
        self.events = events

    def lines(self) -> List[str]:
        """Canonical serialization: one JSON object per event, sorted
        keys, no whitespace — byte-identical across runs of one seed
        (the determinism contract tests assert on these bytes)."""
        return [
            json.dumps(e, sort_keys=True, separators=(",", ":"))
            for e in self.events
        ]

    def digest(self) -> str:
        """Content digest of the canonical serialization — what the
        chaos report prints so two runs can be compared at a glance."""
        h = hashlib.sha256()
        for line in self.lines():
            h.update(line.encode())
            h.update(b"\n")
        return h.hexdigest()[:16]

    def requests(self) -> List[dict]:
        return [e for e in self.events if e["kind"] == "request"]

    def pod_events(self) -> List[dict]:
        return [e for e in self.events if e["kind"].startswith("pod_")]


class TraceGenerator:
    """Seeded generator for replayable request/pod arrival traces.

    All randomness flows from one ``random.Random(seed)`` consumed in a
    fixed order; every knob is part of the recipe recorded in
    ``Trace.meta`` so a repro line can rebuild the exact trace.
    """

    def __init__(
        self,
        seed: int,
        duration_s: float = 4.0,
        base_rps: float = 12.0,
        diurnal_amplitude: float = 0.6,
        day_length_s: float = 4.0,
        flash_crowds: int = 1,
        flash_multiplier: float = 4.0,
        flash_duration_s: float = 0.5,
        hostile_fraction: float = 0.5,
        train_pods: int = 2,
        train_pod_lifetime_s: float = 1.5,
        slo_mix: Optional[Dict[str, float]] = None,
    ) -> None:
        if duration_s <= 0:
            raise ValueError(f"duration_s must be positive: {duration_s}")
        if not 0.0 <= hostile_fraction <= 1.0:
            raise ValueError(
                f"hostile_fraction out of [0,1]: {hostile_fraction}"
            )
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.base_rps = float(base_rps)
        self.diurnal_amplitude = min(max(float(diurnal_amplitude), 0.0), 1.0)
        self.day_length_s = max(float(day_length_s), 1e-6)
        self.flash_crowds = int(flash_crowds)
        self.flash_multiplier = max(1.0, float(flash_multiplier))
        self.flash_duration_s = float(flash_duration_s)
        self.hostile_fraction = float(hostile_fraction)
        self.train_pods = int(train_pods)
        self.train_pod_lifetime_s = float(train_pod_lifetime_s)
        # Serving mix leans interactive: latency classes dominate, batch
        # rides along (matches the FlexNPU-style co-located traffic the
        # paper motivates).
        self.slo_mix = dict(slo_mix or {
            "ttft": 0.45, "tpot": 0.35, "batch": 0.20,
        })
        unknown = set(self.slo_mix) - set(SLO_CLASSES)
        if unknown:
            raise ValueError(f"unknown SLO classes in mix: {sorted(unknown)}")

    # -- rate model --------------------------------------------------------

    def _rate_at(self, t: float, flashes: List[dict]) -> float:
        """Instantaneous arrival rate: diurnal sine around base_rps,
        multiplied inside any flash-crowd window."""
        day = math.sin(2.0 * math.pi * t / self.day_length_s)
        rate = self.base_rps * (1.0 + self.diurnal_amplitude * day)
        for fc in flashes:
            if fc["t"] <= t < fc["t"] + fc["duration_s"]:
                rate *= self.flash_multiplier
        return max(rate, 0.05 * self.base_rps)

    # -- prompt model ------------------------------------------------------

    def _chain_for(self, rng: random.Random, rid: int, hostile: bool):
        """(chain_digests, shared_prefix_len): hostile chains share only
        the root block and diverge immediately (every request a distinct
        path — the affinity table learns nothing it can reuse);
        friendly chains extend one of a small pool of shared prefixes."""
        if hostile:
            root = _digest(self.seed, "hostile-root")
            chain = [root] + [
                _digest(self.seed, "hostile", rid, i)
                for i in range(1, CHAIN_DEPTH)
            ]
            return chain, 1
        family = rng.randrange(FRIENDLY_PREFIX_POOL)
        shared = rng.randint(CHAIN_DEPTH // 2, CHAIN_DEPTH - 1)
        chain = [
            _digest(self.seed, "family", family, i) for i in range(shared)
        ] + [
            _digest(self.seed, "tail", rid, i)
            for i in range(shared, CHAIN_DEPTH)
        ]
        return chain, shared

    def _pick_slo(self, rng: random.Random) -> str:
        x = rng.random() * sum(self.slo_mix.values())
        acc = 0.0
        for slo in SLO_CLASSES:  # fixed iteration order: determinism
            acc += self.slo_mix.get(slo, 0.0)
            if x < acc:
                return slo
        return "batch"

    # -- generation --------------------------------------------------------

    def generate(self) -> Trace:
        rng = random.Random(self.seed)
        events: List[dict] = []

        # Flash-crowd windows first (their placement must not depend on
        # how many arrivals the rate model produced).
        flashes = []
        for i in range(self.flash_crowds):
            start = rng.uniform(
                0.1 * self.duration_s,
                max(0.1 * self.duration_s,
                    self.duration_s - self.flash_duration_s),
            )
            flashes.append({
                "kind": "flash_crowd",
                "t": round(start, 6),
                "duration_s": round(self.flash_duration_s, 6),
                "multiplier": self.flash_multiplier,
                "idx": i,
            })
        events.extend(flashes)

        # Train-tenant churn: admit/delete pairs spread over the trace.
        for i in range(self.train_pods):
            t_admit = rng.uniform(0.0, self.duration_s * 0.6)
            t_del = min(
                t_admit + self.train_pod_lifetime_s
                * rng.uniform(0.7, 1.3),
                self.duration_s,
            )
            name = f"train-{self.seed}-{i}"
            events.append({
                "kind": "pod_admit", "t": round(t_admit, 6),
                "pod": name, "tenancy": "train",
            })
            events.append({
                "kind": "pod_delete", "t": round(t_del, 6),
                "pod": name, "tenancy": "train",
            })

        # Request arrivals: thinned Poisson process against the
        # instantaneous rate (classic Lewis-Shedler), all draws from the
        # single stream.
        peak = (
            self.base_rps * (1.0 + self.diurnal_amplitude)
            * self.flash_multiplier
        )
        t = 0.0
        rid = 0
        while True:
            t += rng.expovariate(peak)
            if t >= self.duration_s:
                break
            if rng.random() >= self._rate_at(t, flashes) / peak:
                continue  # thinned away
            hostile = rng.random() < self.hostile_fraction
            chain, shared = self._chain_for(rng, rid, hostile)
            prompt_tokens = rng.randint(64, 1024)
            events.append({
                "kind": "request",
                "t": round(t, 6),
                "rid": rid,
                "slo": self._pick_slo(rng),
                "tenancy": "serve",
                "hostile": hostile,
                "prompt_tokens": prompt_tokens,
                "output_tokens": rng.randint(8, 256),
                "chain": chain,
                "shared_prefix_blocks": shared,
            })
            rid += 1

        # Stable order: by time, ties broken by kind then id — sorted()
        # is stable and the keys are pure data, so the order is part of
        # the byte-identical contract.
        events.sort(key=lambda e: (
            e["t"], e["kind"], e.get("rid", -1), e.get("pod", ""),
        ))
        meta = {
            "trace_seed": self.seed,
            "duration_s": self.duration_s,
            "base_rps": self.base_rps,
            "diurnal_amplitude": self.diurnal_amplitude,
            "day_length_s": self.day_length_s,
            "flash_crowds": self.flash_crowds,
            "flash_multiplier": self.flash_multiplier,
            "flash_duration_s": self.flash_duration_s,
            "hostile_fraction": self.hostile_fraction,
            "train_pods": self.train_pods,
            "requests": rid,
            "events": len(events),
        }
        return Trace(self.seed, meta, events)


class TraceCursor:
    """Replay pacing: hands out events whose trace-time has come.

    The cursor never reads a clock — the driver (chaos runner, a test
    on ManualClock) calls ``due(now)`` with its own notion of elapsed
    scenario time and dispatches what comes back. Events are consumed
    exactly once, in trace order.
    """

    def __init__(self, trace: Trace) -> None:
        self.trace = trace
        self._i = 0

    @property
    def exhausted(self) -> bool:
        return self._i >= len(self.trace.events)

    @property
    def remaining(self) -> int:
        return len(self.trace.events) - self._i

    def due(self, now: float) -> Iterator[dict]:
        """Yield (and consume) every event with ``t <= now``."""
        while (
            self._i < len(self.trace.events)
            and self.trace.events[self._i]["t"] <= now
        ):
            e = self.trace.events[self._i]
            self._i += 1
            yield e

    def drain(self) -> Iterator[dict]:
        """Everything left, regardless of time (end-of-scenario flush)."""
        return self.due(float("inf"))
